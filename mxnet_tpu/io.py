"""Data iterators.

API parity with reference ``python/mxnet/io.py`` (DataDesc, DataBatch :118,
DataIter :182, NDArrayIter, ResizeIter, PrefetchingIter :349, CSVIter,
MNISTIter) and the C++ iterator registry semantics (SURVEY §2.1 Data I/O).
Host-side batching feeds the device through async device_put; heavy decode
paths live in gluon.data / image.
"""
from __future__ import annotations

import os
import queue as queue_mod
import threading
from collections import namedtuple

import numpy as np

from . import resilience, telemetry
from .base import MXNetError, fetch_host
from .context import cpu
from .ndarray import ndarray as nd_mod
from .ndarray.ndarray import NDArray
from .resilience import TransientError, chaos

_IO_RETRY = None


def _io_policy():
    """Prefetch retry policy. Retries ONLY explicit :class:`TransientError`
    (chaos faults — injected before the fetch advances anything — and
    iterators that raise it to mark a failure retry-safe): re-invoking
    ``next()`` on an iterator whose cursor already moved is NOT idempotent,
    so a broad retry would silently skip the faulted sample. Raw OSErrors
    and the like propagate to the consumer instead."""
    global _IO_RETRY
    if _IO_RETRY is None:
        _IO_RETRY = resilience.RetryPolicy(retry_on=(TransientError,))
    return _IO_RETRY

# pipeline health: batches staged ahead of the consumer, per pipeline kind —
# a stalled producer shows up as this counter flatlining while the step
# spans keep ticking
_T_PREFETCH = telemetry.counter(
    "mxnet_io_prefetch_batches_total",
    "batches prefetched ahead of the consumer",
    labels=("pipeline",))

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "MNISTIter", "ImageRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape (+dtype/layout) descriptor (reference io.py:DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch(object):
    """One mini-batch (reference io.py:118)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad if pad is not None else 0
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter(object):
    """Base iterator (reference io.py:182).

    Iterators that support deterministic elastic resume implement the
    cursor protocol: ``state_dict()`` returns a small picklable dict and
    ``set_state(state)`` repositions the stream so the NEXT batch
    delivered is exactly the one an uninterrupted run would have seen.
    ``elastic.CheckpointManager.save_training`` captures it per
    checkpoint; iterators without the protocol resume from the epoch
    start (replaying data — the pre-v2 behavior)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy array)
    (reference io.py:_init_data)."""
    assert (data is not None) or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = OrderedDictItems([(default_name, data[0])])
        else:
            data = OrderedDictItems(
                [("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)])
    if isinstance(data, dict):
        data = OrderedDictItems(sorted(data.items()))
    # ONE batched device->host transfer for every NDArray input instead
    # of a per-item .asnumpy() sync in the loop
    items = list(data)
    nd_idx = [i for i, (_k, v) in enumerate(items) if isinstance(v, NDArray)]
    fetched = dict(zip(nd_idx, fetch_host([items[i][1] for i in nd_idx])
                       if nd_idx else []))
    return [(k, np.asarray(fetched[i] if i in fetched else v))
            for i, (k, v) in enumerate(items)]


class OrderedDictItems(list):
    pass


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with shuffle/pad (reference
    io.py:NDArrayIter)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.num_data = self.data[0][1].shape[0]

        if shuffle:
            from . import random as _random

            idx = np.arange(self.num_data)
            _random.np_rng().shuffle(idx)
            self.data = [(k, v[idx]) for k, v in self.data]
            self.label = [(k, v[idx]) for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.num_data - self.num_data % batch_size
            self.data = [(k, v[:new_n]) for k, v in self.data]
            self.label = [(k, v[:new_n]) for k, v in self.label]
            self.num_data = new_n

        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [nd_mod.array(v[self.cursor:self.cursor + self.batch_size],
                                 dtype=v.dtype)
                    for _, v in data_source]
        # padding with wrap-around (last_batch_handle='pad')
        pad = self.batch_size - self.num_data + self.cursor
        return [nd_mod.array(np.concatenate([v[self.cursor:], v[:pad]], axis=0),
                             dtype=v.dtype)
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def state_dict(self):
        """Resume cursor. The shuffle permutation (applied once at
        construction from the seeded ``mx.random`` host stream) is NOT
        part of the state: a resumed run reconstructs the iterator under
        the same seed and gets the same order."""
        return {"cursor": int(self.cursor)}

    def set_state(self, state):
        self.cursor = int(state["cursor"])


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference
    io.py:ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Threaded prefetch over one or more iterators (reference io.py:349;
    the Python-side analogue of the C++ prefetcher iter_prefetcher.h).

    Worker failure contract: a transient fault in the underlying iterator
    (chaos site ``io.prefetch``) retries under the resilience policy;
    a terminal exception is captured and re-raised to the CONSUMER at the
    next ``__next__`` — never swallowed (which used to truncate the epoch
    silently) and never left to kill the worker thread (which used to
    block the consumer forever on ``data_ready``)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]
        self._errors = [None for _ in range(self.n_iter)]
        self._failed = False
        self._delivered = 0  # batches handed to the CONSUMER this pass

        def fetch_one(i):
            def attempt():
                chaos.maybe_fail("io.prefetch")
                return self.iters[i].next()

            return _io_policy().call(attempt, site="io.prefetch")

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = fetch_one(i)
                except StopIteration:
                    self.next_batch[i] = None
                except Exception as exc:  # noqa: BLE001 - delivered at next()
                    self.next_batch[i] = None
                    self._errors[i] = exc
                if self.next_batch[i] is not None:
                    _T_PREFETCH.inc(pipeline="PrefetchingIter")
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i])
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.daemon = True
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()
        for thread in self.prefetch_threads:
            thread.join(timeout=1)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        self._errors = [None for _ in range(self.n_iter)]
        self._failed = False
        self._delivered = 0
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        if self._failed:
            return False
        for e in self.data_ready:
            e.wait()
        errors = [e for e in self._errors if e is not None]
        if errors:
            # terminal worker failure: surface it on the consumer thread.
            # The stream then reads as ended (until reset()), so a consumer
            # that keeps iterating sees end-of-epoch instead of a hang.
            self._errors = [None for _ in range(self.n_iter)]
            self._failed = True
            raise errors[0]
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Different pad size in the data batches"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        self._delivered += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    def state_dict(self):
        """Resume cursor: batches DELIVERED to the consumer (the workers'
        own read-ahead is deliberately not part of the state — an
        in-flight prefetched batch was never trained on)."""
        return {"delivered": int(self._delivered)}

    def set_state(self, state):
        """Reposition by reset + host-side replay: the worker protocol
        starts fetching the moment the base iterators reset, so skipping
        at the base level would race it; consuming ``delivered`` batches
        through the normal path is the interleaving-safe equivalent and
        costs only host batch assembly (no training, no device work)."""
        self.reset()
        delivered = int(state.get("delivered", 0))  # host cursor, no device value
        for _ in range(delivered):
            self.next()


class CSVIter(NDArrayIter):
    """CSV file iterator (reference src/io/iter_csv.cc / io.py CSVIter)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[1:] == (1,):
                label = label.reshape(label.shape[0])
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch else "discard")


class MNISTIter(NDArrayIter):
    """MNIST idx-file iterator (reference src/io/iter_mnist.cc)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=None, input_shape=None, **kwargs):
        import gzip
        import struct

        opener = gzip.open if image.endswith(".gz") else open
        with opener(label, "rb") as fin:
            struct.unpack(">II", fin.read(8))
            lab = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.float32)
        with opener(image, "rb") as fin:
            _, n, r, c = struct.unpack(">IIII", fin.read(16))
            img = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.float32) / 255.0
            img = img.reshape(n, 1, r, c)
        if flat:
            img = img.reshape(n, r * c)
        elif input_shape is not None:
            img = img.reshape((n,) + tuple(input_shape))
        super().__init__(img, lab, batch_size=batch_size, shuffle=shuffle)


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=128,
                    shuffle=False, preprocess_threads=4, prefetch_buffer=4,
                    label_width=1, **kwargs):
    """ImageRecordIter over a .rec file (reference
    src/io/iter_image_recordio_2.cc:663). Decodes JPEG payloads host-side
    through mxnet_tpu.image, batches, and prefetches on threads."""
    from . import image as image_mod
    from . import recordio

    class _Iter(DataIter):
        def __init__(self):
            super().__init__(batch_size)
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self._rec = recordio.MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self._keys = list(self._rec.keys)
            else:
                self._rec = recordio.MXRecordIO(path_imgrec, "r")
                self._keys = None
            self._order = None
            self._pos = 0
            self.data_shape = tuple(data_shape)
            self.reset()

        @property
        def provide_data(self):
            return [DataDesc("data", (batch_size,) + self.data_shape)]

        @property
        def provide_label(self):
            shape = (batch_size,) if label_width == 1 else (batch_size, label_width)
            return [DataDesc("softmax_label", shape)]

        def reset(self):
            self._pos = 0
            if self._keys is not None:
                self._order = list(self._keys)
                if shuffle:
                    from . import random as _random

                    _random.np_rng().shuffle(self._order)
            else:
                self._rec.reset()

        def _read_one(self):
            if self._keys is not None:
                if self._pos >= len(self._order):
                    return None
                rec = self._rec.read_idx(self._order[self._pos])
                self._pos += 1
            else:
                rec = self._rec.read()
                if rec is None:
                    return None
            header, img_bytes = recordio.unpack(rec)
            img = image_mod.imdecode(img_bytes)  # HWC
            c, h, w = self.data_shape
            if img.shape[0] != h or img.shape[1] != w:
                img = image_mod.imresize(img, w, h)
            chw = img.asnumpy().transpose(2, 0, 1).astype(np.float32)
            label = header.label
            return chw, label

        def next(self):
            datas, labels = [], []
            pad = 0
            while len(datas) < batch_size:
                one = self._read_one()
                if one is None:
                    if not datas:
                        raise StopIteration
                    pad = batch_size - len(datas)
                    while len(datas) < batch_size:
                        datas.append(datas[-1])
                        labels.append(labels[-1])
                    break
                datas.append(one[0])
                labels.append(one[1])
            data = nd_mod.array(np.stack(datas))
            label = nd_mod.array(np.asarray(labels, dtype=np.float32))
            return DataBatch([data], [label], pad=pad,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)

        def iter_next(self):
            try:
                self._next_cache = self.next()
                return True
            except StopIteration:
                return False

    # multiprocess pipeline (the iter_image_recordio_2.cc counterpart):
    # used whenever an .idx exists and >1 preprocess worker is requested —
    # JPEG decode does not scale on Python threads (GIL). Spawned workers
    # need a re-importable __main__, so interactive/stdin sessions keep the
    # single-process path.
    import sys as _sys

    idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
    spawnable = getattr(_sys.modules.get("__main__"), "__file__", None) \
        is not None
    if preprocess_threads and preprocess_threads > 1 \
            and os.path.exists(idx_path) and spawnable \
            and not kwargs.pop("force_single_process", False):
        from .image_pipeline import MPImageRecordIter

        return MPImageRecordIter(
            path_imgrec=path_imgrec, data_shape=data_shape,
            batch_size=batch_size, shuffle=shuffle,
            label_width=label_width, preprocess_threads=preprocess_threads,
            prefetch_buffer=prefetch_buffer, **kwargs)

    it = _Iter()
    if preprocess_threads and prefetch_buffer:
        return PrefetchingIter(it)
    return it


class LibSVMIter(DataIter):
    """Sparse batch iterator over LibSVM text files (reference
    ``src/iter_libsvm.cc`` + ``iter_sparse_batchloader.h``): each line is
    ``label[,label..] idx:value idx:value ...``; batches come out as CSR
    arrays so sparse FullyConnected/dot paths consume them directly.

    Parameters mirror the reference: ``data_libsvm`` (path),
    ``data_shape`` (feature dimension), optional ``label_libsvm`` for
    multi-target labels stored in a second file.
    """

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, round_batch=True,
                 data_name="data", label_name="softmax_label", **_ignored):
        super().__init__(batch_size)
        from .ndarray import sparse as sp

        self._sp = sp
        self.data_shape = (data_shape,) if isinstance(data_shape, int) \
            else tuple(data_shape)
        self.num_features = int(np.prod(self.data_shape))
        self.round_batch = round_batch
        self.data_name = data_name
        self.label_name = label_name

        self._rows = self._parse(data_libsvm)  # list of (label, idx[], val[])
        if label_libsvm:
            lab = self._parse(label_libsvm)
            if len(lab) != len(self._rows):
                raise MXNetError("label_libsvm row count mismatch")
            # dense multi-target labels from the label file's indices/values
            width = (int(np.prod(label_shape)) if label_shape else
                     max((r[1][-1] + 1) if len(r[1]) else 1 for r in lab))
            labels = np.zeros((len(lab), width), dtype=np.float32)
            for i, (_, idx, val) in enumerate(lab):
                labels[i, idx] = val
            self._labels = labels
        else:
            self._labels = np.asarray([r[0] for r in self._rows],
                                      dtype=np.float32)
        self.cur = 0

    @staticmethod
    def _parse(path):
        rows = []
        with open(path) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                label = float(parts[0].split(",")[0])
                idx, val = [], []
                for tok in parts[1:]:
                    k, _, v = tok.partition(":")
                    idx.append(int(k))
                    val.append(float(v))
                rows.append((label, np.asarray(idx, dtype=np.int64),
                             np.asarray(val, dtype=np.float32)))
        return rows

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size, self.num_features))]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._labels.ndim == 1 \
            else (self.batch_size, self._labels.shape[1])
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        self.cur = 0

    def iter_next(self):
        return self.cur < len(self._rows)

    def next(self):
        if self.cur >= len(self._rows):
            raise StopIteration
        end = min(self.cur + self.batch_size, len(self._rows))
        rows = self._rows[self.cur:end]
        labels = self._labels[self.cur:end]
        pad = self.batch_size - len(rows)
        if pad and self.round_batch:
            rows = rows + self._rows[:pad]  # wrap like the reference
            labels = np.concatenate([labels, self._labels[:pad]], axis=0)
        self.cur = end
        indptr = np.zeros(len(rows) + 1, dtype=np.int64)
        for i, (_, idx, _v) in enumerate(rows):
            indptr[i + 1] = indptr[i] + len(idx)
        indices = np.concatenate([r[1] for r in rows]) if rows else \
            np.zeros((0,), np.int64)
        values = np.concatenate([r[2] for r in rows]) if rows else \
            np.zeros((0,), np.float32)
        data = self._sp.csr_matrix(
            (values, indices, indptr),
            shape=(len(rows), self.num_features))
        label = nd_mod.array(labels)
        return DataBatch([data], [label], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class DevicePrefetchIter(DataIter):
    """Device-infeed pipeline: stages upcoming batches into device memory
    on a background thread while the current step computes.

    The TPU analogue of the reference's prefetcher (``iter_prefetcher.h``)
    one level deeper: beyond overlapping host-side batch ASSEMBLY (which
    :class:`PrefetchingIter` covers), this overlaps the host→HBM transfer
    itself, so the accelerator never waits on PCIe/DMA — jax dispatch is
    async, and ``jax.device_put`` from the worker thread runs concurrently
    with the in-flight step.

    ``sharding`` (a ``jax.sharding.Sharding``, or a callable
    ``ndim -> Sharding`` for rank-dependent layouts) makes this the
    *pre-sharded feed* of the in-graph training plane: batches land
    already laid out over the mesh's ``dp`` axis, so the step's own
    shard pass (``parallel.shard_to_mesh``) degenerates to an equivalence
    check instead of a dispatch-serializing ``device_put``. Arrays already
    resident in the target layout are passed through untouched — the
    worker never issues a wasted D2D copy for data that is where it
    should be (the same ``is_equivalent_to`` skip the step itself uses).
    """

    def __init__(self, base_iter, ctx=None, depth=2, sharding=None):
        super().__init__(base_iter.batch_size)
        from .context import current_context

        self.base = base_iter
        self.ctx = ctx or current_context()
        self._sharding = sharding
        self._depth = max(1, depth)
        self._queue = queue_mod.Queue(maxsize=self._depth)
        self._sentinel = object()
        self._thread = None
        self._done = False
        self._delivered = 0  # batches handed to the consumer this pass
        self._skip = 0       # host-side fast-forward for set_state resume
        self._start()

    @property
    def provide_data(self):
        return self.base.provide_data

    @property
    def provide_label(self):
        return self.base.provide_label

    def _target(self, data):
        """Device-put target for one array: the configured sharding, else
        this iterator's context device."""
        from . import parallel

        tgt = parallel.resolve_sharding(self._sharding, data.ndim)
        if tgt is not None:
            return tgt
        import jax

        return jax.sharding.SingleDeviceSharding(self.ctx.jax_device())

    def _stage(self, batch):
        from . import parallel

        def put(arrs):
            out = []
            for a in arrs:
                if not isinstance(a, nd_mod.NDArray):
                    out.append(a)
                    continue
                data = a._data
                # parallel.put_sharded skips the put (returns `data`
                # itself) when the batch is already resident in the
                # target layout
                staged = parallel.put_sharded(data, self._target(data))
                out.append(a if staged is data
                           else type(a)(staged, self.ctx))
            return out

        return DataBatch(put(batch.data),
                         put(batch.label) if batch.label else batch.label,
                         pad=batch.pad, index=getattr(batch, "index", None),
                         provide_data=batch.provide_data,
                         provide_label=batch.provide_label)

    def _start(self):
        import threading as _threading

        def fetch(it):
            def attempt():
                chaos.maybe_fail("io.prefetch")
                return next(it)

            return _io_policy().call(attempt, site="io.prefetch")

        def worker():
            it = iter(self.base)
            try:
                # elastic resume: fast-forward the base stream host-side
                # (no staging, no device transfer) to the restored cursor
                skip, self._skip = self._skip, 0
                for _ in range(skip):
                    try:
                        fetch(it)
                    except StopIteration:
                        break
                while True:
                    try:
                        batch = fetch(it)
                    except StopIteration:
                        break
                    self._queue.put(self._stage(batch))
                    _T_PREFETCH.inc(pipeline="DevicePrefetchIter")
            except Exception as exc:  # noqa: BLE001 - delivered at next()
                self._queue.put(exc)
                return
            self._queue.put(self._sentinel)

        self._thread = _threading.Thread(target=worker, daemon=True,
                                         name="mxtpu-device-infeed")
        self._thread.start()

    def _drain(self):
        """Join the in-flight worker by draining its queue (it exits after
        the sentinel/error once nothing blocks its puts)."""
        while self._thread is not None and self._thread.is_alive():
            try:
                self._queue.get(timeout=0.1)
            except queue_mod.Empty:
                continue
        while not self._queue.empty():
            self._queue.get_nowait()

    def reset(self):
        # drain the in-flight queue, then restart on a fresh pass
        self._drain()
        self.base.reset()
        self._done = False
        self._delivered = 0
        self._skip = 0
        self._start()

    def state_dict(self):
        """Resume cursor: batches DELIVERED to the consumer; the worker's
        staged read-ahead (and its device copies) is not state — those
        batches were never trained on."""
        return {"delivered": int(self._delivered)}

    def set_state(self, state):
        """Reposition: restart the base stream and hand the worker a
        host-side skip count — the skipped batches are fetched but never
        staged, so resume costs no device transfers for data already
        consumed before the checkpoint. Exact when the base stream is
        deterministic (same seed/order), which elastic resume guarantees
        by restoring the RNG snapshot first."""
        self._drain()
        self.base.reset()
        self._done = False
        self._delivered = int(state.get("delivered", 0))
        self._skip = self._delivered
        self._start()

    def next(self):
        # a finished or failed stream stays finished (until reset()):
        # re-polling the queue after the worker exited would hang forever
        if self._done:
            raise StopIteration
        item = self._queue.get()
        if item is self._sentinel:
            self._done = True
            raise StopIteration
        if isinstance(item, Exception):
            self._done = True
            raise item
        self._delivered += 1
        return item

    def iter_next(self):
        try:
            self._cached = self.next()
            return True
        except StopIteration:
            return False


def MXDataIter(handle=None, **kwargs):  # noqa: N802 - reference name
    """Factory shim for the reference's C++-registered iterator wrapper
    (``python/mxnet/io.py:MXDataIter`` wrapping MXDataIterCreateIter
    handles). This build's iterators are Python classes over the native
    RecordIO layer, so the factory resolves by iterator name instead of a C
    handle: ``MXDataIter(name="ImageRecordIter", **params)``.
    """
    name = kwargs.pop("name", handle)
    factories = {
        "ImageRecordIter": ImageRecordIter,
        "MNISTIter": MNISTIter,
        "CSVIter": CSVIter,
        "LibSVMIter": LibSVMIter,
        "NDArrayIter": NDArrayIter,
    }
    if name not in factories:
        raise MXNetError(
            "MXDataIter: unknown iterator %r (available: %s)"
            % (name, sorted(factories)))
    return factories[name](**kwargs)
