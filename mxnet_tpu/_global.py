"""Process-global trace-time state: train/predict mode and the RNG stream.

The reference keeps train-mode on the autograd tape (`Imperative::is_training`,
reference `include/mxnet/imperative.h`) and RNG state in per-context Resource
pools (`src/resource.cc`, `src/common/random_generator.h`). On the XLA stack,
ops are pure functions, so:

* train-mode is a Python-level flag read at *trace* time (each executor /
  CachedOp traces separately for train and predict, mirroring the reference's
  `is_train` executor flag);
* randomness flows through an explicit jax PRNG key. Eagerly the key lives
  here and is split per call. Inside a jit trace, the executor pushes a
  *traced* key so compiled graphs receive fresh randomness as an argument on
  every execution instead of baking one sample into the HloModule.
"""
from __future__ import annotations

import threading

import jax
import numpy as np

__all__ = [
    "is_train",
    "set_train",
    "train_mode_scope",
    "seed",
    "next_key",
    "push_rng_key",
    "pop_rng_key",
    "current_rng_key",
    "rng_snapshot",
    "restore_rng_snapshot",
]


class _State(threading.local):
    def __init__(self):
        self.train_mode = False
        self.recording = False
        self.key_stack = []  # innermost last; each entry is a jax PRNG key
        self.base_key = None


_STATE = _State()


def _state() -> _State:
    return _STATE


def is_train() -> bool:
    return _STATE.train_mode


def set_train(mode: bool) -> bool:
    prev = _STATE.train_mode
    _STATE.train_mode = bool(mode)
    return prev


class train_mode_scope:
    def __init__(self, mode: bool):
        self.mode = mode
        self.prev = None

    def __enter__(self):
        self.prev = set_train(self.mode)
        return self

    def __exit__(self, *a):
        set_train(self.prev)


def seed(seed_val: int):
    """Global seed (reference `mx.random.seed`)."""
    _STATE.base_key = jax.random.PRNGKey(int(seed_val))
    _STATE.key_stack = []


def _base_key():
    if _STATE.base_key is None:
        _STATE.base_key = jax.random.PRNGKey(np.random.randint(0, 2**31 - 1))
    return _STATE.base_key


def next_key():
    """Split a fresh subkey from the innermost RNG stream.

    Eager: advances the global key. Under an executor trace (push_rng_key):
    advances the traced key so each compiled run draws new randomness.
    """
    if _STATE.key_stack:
        k = _STATE.key_stack[-1]
        k, sub = jax.random.split(k)
        _STATE.key_stack[-1] = k
        return sub
    k = _base_key()
    k, sub = jax.random.split(k)
    _STATE.base_key = k
    return sub


def push_rng_key(key):
    _STATE.key_stack.append(key)


def pop_rng_key():
    return _STATE.key_stack.pop()


def current_rng_key():
    return _STATE.key_stack[-1] if _STATE.key_stack else _base_key()


def rng_snapshot() -> np.ndarray:
    """The base key's raw data as a host array — the picklable stream
    cursor elastic checkpoints carry. Taken at a step boundary (empty key
    stack): restoring it makes every subsequent :func:`next_key` draw
    identical to an uninterrupted run's."""
    k = _base_key()
    try:
        return np.asarray(k)
    except TypeError:  # pragma: no cover - typed (new-style) PRNG keys
        return np.asarray(jax.random.key_data(k))


def restore_rng_snapshot(data) -> None:
    """Install a :func:`rng_snapshot` as the live base key (clearing any
    traced-key stack — snapshots are only taken/restored between steps)."""
    import jax.numpy as jnp

    _STATE.base_key = jnp.asarray(np.asarray(data))
    _STATE.key_stack = []
