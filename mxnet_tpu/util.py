"""General utilities (reference python/mxnet/util.py).

The reference's util.py carries makedirs/py3 shims plus feature helpers;
here the useful survivors are kept and TPU-stack introspection added.
"""
from __future__ import annotations

import functools
import os

__all__ = ["makedirs", "use_np_shape", "get_gpu_count", "get_gpu_memory",
           "default_array_context"]


def makedirs(d):
    """Create directory recursively if missing (reference util.py:makedirs)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def use_np_shape(func):
    """Zero-size/unknown-shape semantics are native on this stack (jax/numpy
    shapes); kept as an identity decorator for reference-code compat."""
    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapped


def get_gpu_count():
    """Accelerator count (reference mx.context.num_gpus analogue)."""
    from .context import num_tpus

    return num_tpus()


def get_gpu_memory(dev_id=0):
    """Per-device (free, total) memory in bytes when the backend reports it."""
    import jax

    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    if dev_id >= len(devs):
        return (0, 0)
    try:
        stats = devs[dev_id].memory_stats()
        total = stats.get("bytes_limit", 0)
        used = stats.get("bytes_in_use", 0)
        return (total - used, total)
    except Exception:  # pragma: no cover - backend without memory_stats
        return (0, 0)


def default_array_context():
    from .context import current_context

    return current_context()
