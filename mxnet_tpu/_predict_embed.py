"""Python half of the C predict API (src/predict/predict.cc).

The reference's ``c_predict_api.h`` exposes inference (load symbol JSON +
params, bind, set input, forward, read output) as a flat C ABI consumed by
the C++/Matlab/mobile frontends (``src/c_api/c_predict_api.cc``). In the
TPU build the executor lives in Python-on-JAX, so the C ABI embeds a
CPython interpreter and drives these functions; data crosses the boundary
as raw float32 buffers.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_HANDLES: Dict[int, "_Predictor"] = {}
_NEXT = [1]


class _Predictor:
    def __init__(self, symbol_json: str, param_bytes: bytes,
                 dev_type: int, input_shapes: Dict[str, Tuple[int, ...]]):
        import mxnet_tpu as mx
        from mxnet_tpu import symbol as sym_mod
        from mxnet_tpu.ndarray import io_utils

        self.mx = mx
        sym = sym_mod.load_json(symbol_json)
        ctx = mx.tpu() if dev_type == 2 else mx.cpu()
        params = {}
        if param_bytes:
            import io
            import os
            import tempfile

            fd, tmp = tempfile.mkstemp(suffix=".params")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(param_bytes)
                loaded = io_utils.load(tmp)
            finally:
                os.remove(tmp)
            for k, v in loaded.items():
                name = k.split(":", 1)[-1]  # strip arg:/aux: prefixes
                params[name] = v
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        shapes = dict(input_shapes)
        for name in arg_names:
            if name in params and name not in shapes:
                shapes[name] = params[name].shape
        self.executor = sym.simple_bind(ctx, grad_req="null", **shapes)
        self.executor.copy_params_from(
            {k: v for k, v in params.items() if k in arg_names},
            {k: v for k, v in params.items() if k in aux_names},
            allow_extra_params=True)
        self.input_names = list(input_shapes)
        self.input_shapes = input_shapes
        self.inputs: Dict[str, np.ndarray] = {}
        self.outputs: List[np.ndarray] = []

    def set_input(self, key: str, buf: bytes):
        shape = self.input_shapes[key]
        arr = np.frombuffer(buf, dtype=np.float32).reshape(shape)
        self.inputs[key] = arr

    def forward(self):
        feed = {k: self.mx.nd.array(v) for k, v in self.inputs.items()}
        outs = self.executor.forward(is_train=False, **feed)
        self.outputs = [o.asnumpy().astype(np.float32) for o in outs]

    def reshape(self, new_shapes: Dict[str, Tuple[int, ...]]):
        self.input_shapes.update(new_shapes)
        self.executor = self.executor.reshape(**new_shapes)


def create(symbol_json: str, param_bytes: bytes, dev_type: int,
           input_names: List[str], input_shapes: List[List[int]]) -> int:
    h = _NEXT[0]
    _NEXT[0] += 1
    _HANDLES[h] = _Predictor(symbol_json, param_bytes, dev_type,
                             {n: tuple(s) for n, s in
                              zip(input_names, input_shapes)})
    return h


def set_input(handle: int, key: str, buf: bytes) -> None:
    _HANDLES[handle].set_input(key, buf)


def forward(handle: int) -> None:
    _HANDLES[handle].forward()


def num_outputs(handle: int) -> int:
    return len(_HANDLES[handle].executor.outputs)


def get_output_shape(handle: int, index: int) -> List[int]:
    p = _HANDLES[handle]
    if p.outputs:
        return list(p.outputs[index].shape)
    return list(p.executor.outputs[index].shape)


def get_output(handle: int, index: int) -> bytes:
    return _HANDLES[handle].outputs[index].tobytes()


def free(handle: int) -> None:
    _HANDLES.pop(handle, None)
