"""Python half of the C predict API (src/predict/predict.cc).

The reference's ``c_predict_api.h`` exposes inference (load symbol JSON +
params, bind, set input, forward, read output) as a flat C ABI consumed by
the C++/Matlab/mobile frontends (``src/c_api/c_predict_api.cc``). In the
TPU build the executor lives in Python-on-JAX, so the C ABI embeds a
CPython interpreter and drives these functions; data crosses the boundary
as raw float32 buffers.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Tuple

import numpy as np

_HANDLES: Dict[int, "_Predictor"] = {}
_NEXT = [1]


class _Predictor:
    def __init__(self, symbol_json: str, param_bytes: bytes,
                 dev_type: int, input_shapes: Dict[str, Tuple[int, ...]]):
        import mxnet_tpu as mx
        from mxnet_tpu import symbol as sym_mod
        from mxnet_tpu.ndarray import io_utils

        self.mx = mx
        sym = sym_mod.load_json(symbol_json)
        ctx = mx.tpu() if dev_type == 2 else mx.cpu()
        params = {}
        if param_bytes:
            import io
            import os
            import tempfile

            fd, tmp = tempfile.mkstemp(suffix=".params")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(param_bytes)
                loaded = io_utils.load(tmp)
            finally:
                os.remove(tmp)
            for k, v in loaded.items():
                name = k.split(":", 1)[-1]  # strip arg:/aux: prefixes
                params[name] = v
        arg_names = sym.list_arguments()
        aux_names = sym.list_auxiliary_states()
        shapes = dict(input_shapes)
        for name in arg_names:
            if name in params and name not in shapes:
                shapes[name] = params[name].shape
        self.executor = sym.simple_bind(ctx, grad_req="null", **shapes)
        self.executor.copy_params_from(
            {k: v for k, v in params.items() if k in arg_names},
            {k: v for k, v in params.items() if k in aux_names},
            allow_extra_params=True)
        self.input_names = list(input_shapes)
        self.input_shapes = input_shapes
        self.inputs: Dict[str, np.ndarray] = {}
        self.outputs: List[np.ndarray] = []
        self._server = None  # lazy mxnet_tpu.serving.Server (see server())
        self._server_lock = threading.Lock()
        self._freed = False

    def set_input(self, key: str, buf: bytes):
        shape = self.input_shapes[key]
        arr = np.frombuffer(buf, dtype=np.float32).reshape(shape)
        self.inputs[key] = arr

    def forward(self):
        from mxnet_tpu.base import fetch_host

        feed = {k: self.mx.nd.array(v) for k, v in self.inputs.items()}
        outs = self.executor.forward(is_train=False, **feed)
        # one vectorized device->host copy for every output, instead of a
        # per-output .asnumpy() sync
        self.outputs = fetch_host(outs, dtype=np.float32)

    def reshape(self, new_shapes: Dict[str, Tuple[int, ...]]):
        self.input_shapes.update(new_shapes)
        self.executor = self.executor.reshape(**new_shapes)
        with self._server_lock:
            server, self._server = self._server, None
        if server is not None:
            # the server's sample shape and per-bucket executors are frozen
            # at build time; a rebind invalidates both (bounded join: a
            # wedged device must not hang the frontend)
            server.close(timeout=60.0)

    # -- dynamic-batching serve (mxnet_tpu.serving) --------------------
    def server(self, **kwargs):
        """Lazily build the dynamic-batching server over this predictor.

        Single-input predictors only (the predict ABI's common case). The
        per-request sample shape is the bound input shape minus its batch
        axis; each bucket gets one reshaped executor, compiled on first
        use (warm after ``Server.warmup()``)."""
        with self._server_lock:
            if self._freed:
                raise ValueError("predictor handle already freed")
            if self._server is None:
                from mxnet_tpu import serving

                if len(self.input_names) != 1:
                    raise ValueError("batched predict serves single-input "
                                     "models; got inputs %r"
                                     % self.input_names)
                key = self.input_names[0]
                sample_shape = tuple(self.input_shapes[key][1:])
                # the ABI caller blocks synchronously on every result, and
                # the first call per bucket pays an XLA compile that can
                # exceed any wall-clock deadline — no per-request timeout
                # unless asked
                kwargs.setdefault("timeout_ms", 0)
                self._server = serving.Server(
                    _ExecutorEngine(self, key), sample_shape,
                    name="predict", **kwargs)
            return self._server


class _ExecutorEngine:
    """``serving.Engine`` over a bound executor: one reshaped executor per
    batch bucket, created (and its XLA module compiled) on first use."""

    def __init__(self, predictor: "_Predictor", key: str):
        self._pred = predictor
        self._key = key
        self._executors: Dict[int, Any] = {}

    def run(self, batch: np.ndarray):
        from mxnet_tpu.base import fetch_host

        ex = self._executors.get(batch.shape[0])
        if ex is None:
            ex = self._pred.executor.reshape(**{self._key: batch.shape})
            self._executors[batch.shape[0]] = ex
        outs = ex.forward(is_train=False,
                          **{self._key: self._pred.mx.nd.array(batch)})
        host = fetch_host(outs, dtype=np.float32)
        return tuple(host) if len(host) > 1 else host[0]

    @property
    def compile_count(self) -> int:
        return len(self._executors)


def create(symbol_json: str, param_bytes: bytes, dev_type: int,
           input_names: List[str], input_shapes: List[List[int]]) -> int:
    h = _NEXT[0]
    _NEXT[0] += 1
    _HANDLES[h] = _Predictor(symbol_json, param_bytes, dev_type,
                             {n: tuple(s) for n, s in
                              zip(input_names, input_shapes)})
    return h


def set_input(handle: int, key: str, buf: bytes) -> None:
    _HANDLES[handle].set_input(key, buf)


def forward(handle: int) -> None:
    _HANDLES[handle].forward()


def num_outputs(handle: int) -> int:
    return len(_HANDLES[handle].executor.outputs)


def get_output_shape(handle: int, index: int) -> List[int]:
    p = _HANDLES[handle]
    if p.outputs:
        return list(p.outputs[index].shape)
    return list(p.executor.outputs[index].shape)


def get_output(handle: int, index: int) -> bytes:
    return _HANDLES[handle].outputs[index].tobytes()


def forward_batch(handle: int, bufs: List[bytes],
                  output_index: int = 0) -> List[bytes]:
    """Batched predict: N raw float32 sample buffers in, N raw float32
    output buffers out — one padded fixed-bucket XLA execution per
    micro-batch (via :mod:`mxnet_tpu.serving`) instead of N sequential
    ``set_input``/``forward`` round-trips. Each buffer holds one sample
    shaped like the bound input minus its batch axis.

    Load shedding is an *external-overload* policy; this caller owns its
    whole batch, so a full queue applies backpressure instead: wait for
    the oldest in-flight result, then resubmit. ``N`` may exceed the
    server queue depth.
    """
    import collections
    import time

    from mxnet_tpu import serving

    p = _HANDLES[handle]
    server = p.server()
    shape = tuple(p.input_shapes[p.input_names[0]][1:])

    def to_bytes(res):
        if isinstance(res, tuple):
            res = res[output_index]
        return np.ascontiguousarray(res).tobytes()

    outs: List[bytes] = [b""] * len(bufs)
    pending = collections.deque()
    for i, buf in enumerate(bufs):
        arr = np.frombuffer(buf, dtype=np.float32).reshape(shape)
        while True:
            try:
                pending.append((i, server.submit(arr)))
                break
            except serving.QueueFullError:
                if pending:  # drain our oldest in-flight request
                    j, fut = pending.popleft()
                    outs[j] = to_bytes(fut.result())
                else:  # queue filled by other threads: yield and retry
                    time.sleep(0.001)
    for j, fut in pending:
        outs[j] = to_bytes(fut.result())
    return outs


def free(handle: int) -> None:
    p = _HANDLES.pop(handle, None)
    if p is None:
        return
    with p._server_lock:  # a racing server() either finished or refuses now
        p._freed = True
        server, p._server = p._server, None
    if server is not None:
        # bounded: free() is driven from the C ABI and must not hang the
        # frontend if a wedged device has the batcher stuck mid-batch
        server.close(timeout=60.0)
