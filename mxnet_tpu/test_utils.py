"""Test helpers (reference python/mxnet/test_utils.py): assert_almost_equal,
check_numeric_gradient (finite differences vs autograd — the backbone of the
reference's test_operator.py), rand_ndarray, check_consistency across
contexts (the cpu-vs-tpu analogue of the reference's cpu-vs-gpu check)."""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from . import autograd
from .context import Context, cpu, current_context
from .ndarray import NDArray, array

__all__ = [
    "assert_almost_equal",
    "almost_equal",
    "rand_ndarray",
    "rand_shape_nd",
    "check_numeric_gradient",
    "check_consistency",
    "same",
    "default_context",
]


def default_context() -> Context:
    return current_context()


def same(a, b) -> bool:
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-8) -> bool:
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-8, names=("a", "b")):
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol, err_msg="%s vs %s" % names)


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=np.float32, ctx=None):
    data = np.random.uniform(-1, 1, size=shape).astype(dtype)
    if stype == "default":
        return array(data, ctx=ctx)
    from .ndarray.sparse import cast_storage

    if density is not None:
        mask = np.random.uniform(0, 1, size=(shape[0],) + (1,) * (len(shape) - 1)) < density
        data = data * mask
    return cast_storage(array(data, ctx=ctx), stype)


def _x64_enabled() -> bool:
    """True when jax x64 mode is explicitly on (JAX_ENABLE_X64)."""
    import jax

    return bool(jax.config.jax_enable_x64)


def numeric_grad(f: Callable[[List[np.ndarray]], np.ndarray], inputs: List[np.ndarray],
                 eps=1e-4) -> List[np.ndarray]:
    """Central finite differences of sum(f(inputs)) w.r.t. each input.

    ``f`` is probed in the inputs' OWN dtype — nothing here promotes the
    device computation. The float64 below is purely the host-side
    accumulator for the sum/difference (differencing two nearly-equal f32
    sums would lose the eps-sized signal to cancellation); like the metric
    accumulators it never enters the device. Each probe syncs the device —
    inherent to finite differencing, accepted in test-only code (hence the
    inline host-sync suppressions)."""
    grads = []
    for i, x in enumerate(inputs):
        g = np.zeros_like(x, dtype=np.float64)  # tpulint: disable=dtype-drift -- host accumulator only, never enters the device
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fplus = float(np.sum(np.asarray(f(inputs), dtype=np.float64)))  # tpulint: disable=host-sync,dtype-drift -- host-side probe, inherent to finite differences
            flat[j] = orig - eps
            fminus = float(np.sum(np.asarray(f(inputs), dtype=np.float64)))  # tpulint: disable=host-sync,dtype-drift -- host-side probe, inherent to finite differences
            flat[j] = orig
            gflat[j] = (fplus - fminus) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(fn: Callable, inputs: Sequence[np.ndarray],
                           eps=1e-3, rtol=1e-2, atol=1e-4, ctx=None):
    """Compare autograd gradients of `fn` (NDArray -> NDArray) against finite
    differences (reference test_utils.check_numeric_gradient).

    Inputs are promoted to float64 ONLY when jax x64 mode is explicitly
    enabled. TPUs have no native f64: with x64 off, ``array()`` silently
    downcasts f64 to f32, so an unconditional promotion (the reference's
    default) would claim f64 precision while the device computes f32 — the
    check would run a different program than the one being validated."""
    promote = _x64_enabled()
    if promote:
        inputs = [x.astype(np.float64) for x in inputs]  # tpulint: disable=dtype-drift -- explicitly x64-guarded
    # array() downcasts f64 by default; pass the dtype explicitly so the
    # x64 promotion actually reaches the device.
    nd_inputs = [array(x, ctx=ctx, dtype=x.dtype if promote else None)
                 for x in inputs]
    for nd in nd_inputs:
        nd.attach_grad()
    with autograd.record():
        out = fn(*nd_inputs)
        loss = out.sum() if isinstance(out, NDArray) else sum(o.sum() for o in out)
    loss.backward()
    analytic = [nd.grad.asnumpy() for nd in nd_inputs]

    def np_f(xs):
        nds = [array(x, ctx=ctx, dtype=x.dtype if promote else None) for x in xs]
        o = fn(*nds)
        return o.asnumpy() if isinstance(o, NDArray) else np.concatenate([v.asnumpy().reshape(-1) for v in o])

    numeric = numeric_grad(np_f, [x.copy() for x in inputs], eps=eps)
    for i, (a, n) in enumerate(zip(analytic, numeric)):
        np.testing.assert_allclose(a, n, rtol=rtol, atol=atol,
                                   err_msg="gradient mismatch for input %d" % i)


def check_consistency(fn: Callable, inputs: Sequence[np.ndarray], ctx_list: Sequence[Context],
                      rtol=1e-4, atol=1e-5):
    """Run `fn` under each context and compare outputs (reference
    check_consistency, cpu-vs-gpu -> cpu-vs-tpu)."""
    outs = []
    for ctx in ctx_list:
        with ctx:
            nds = [array(x, ctx=ctx) for x in inputs]
            o = fn(*nds)
            outs.append(o.asnumpy() if isinstance(o, NDArray) else [v.asnumpy() for v in o])
    ref = outs[0]
    for o in outs[1:]:
        if isinstance(ref, list):
            for r, v in zip(ref, o):
                np.testing.assert_allclose(r, v, rtol=rtol, atol=atol)
        else:
            np.testing.assert_allclose(ref, o, rtol=rtol, atol=atol)


def check_symbolic_forward(sym, location, expected, rtol=1e-5, atol=1e-8,
                           aux_states=None, ctx=None):
    """Bind a symbol, run forward, compare outputs to expectations
    (reference test_utils.check_symbolic_forward). ``location`` /
    ``expected`` are lists (positional by arg/output order) or name dicts."""
    from .ndarray import ndarray as nd_mod

    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    shapes = {k: np.asarray(v).shape for k, v in location.items()}
    ex = sym.simple_bind(ctx, grad_req="null", **shapes)
    ex.copy_params_from({k: nd_mod.array(np.asarray(v))
                         for k, v in location.items()},
                        {k: nd_mod.array(np.asarray(v))
                         for k, v in (aux_states or {}).items()} or None,
                        allow_extra_params=True)
    outputs = ex.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[n] for n in sym.list_outputs()]
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out.asnumpy(), np.asarray(exp), rtol, atol)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-5,
                            atol=1e-8, aux_states=None, grad_req="write",
                            ctx=None):
    """Bind, forward+backward, compare input gradients
    (reference test_utils.check_symbolic_backward)."""
    from .ndarray import ndarray as nd_mod

    ctx = ctx or default_context()
    arg_names = sym.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    shapes = {k: np.asarray(v).shape for k, v in location.items()}
    ex = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
    ex.copy_params_from({k: nd_mod.array(np.asarray(v))
                         for k, v in location.items()},
                        {k: nd_mod.array(np.asarray(v))
                         for k, v in (aux_states or {}).items()} or None,
                        allow_extra_params=True)
    ex.forward(is_train=True)
    ex.backward(out_grads=[nd_mod.array(np.asarray(g)) for g in out_grads]
                if isinstance(out_grads, (list, tuple)) else
                nd_mod.array(np.asarray(out_grads)))
    for name, exp in expected.items():
        assert_almost_equal(ex.grad_dict[name].asnumpy(), np.asarray(exp),
                            rtol, atol, names=("grad(%s)" % name, "expected"))
    return ex.grad_dict


def same_array(a, b) -> bool:
    """True when two NDArrays share the same underlying buffer (reference
    test_utils.same_array — there it mutates and checks; jax buffers are
    immutable so identity of the backing array is the sharing criterion)."""
    return a._data is b._data
