"""Failure detection and elastic (checkpoint-resume) training.

TPU-native re-design of the reference's fault story (SURVEY §5.3), which
lives in ps-lite: scheduler heartbeats, ``KVStoreDist::GetDeadNodes(timeout)``
(kvstore_dist.h:121) and the ``is_recovery`` re-rendezvous flag
(kvstore_dist.h:52,138). A TPU job has no parameter server to survive a
worker — SPMD collectives fail as a unit — so the equivalent capability is:

- **liveness**: every worker heartbeats through the jax coordination
  service's key-value store; :func:`get_dead_nodes` reports ranks whose
  heartbeat went stale (the ``GetDeadNodes`` API, same timeout contract);
- **recovery**: atomic checkpoints (:class:`CheckpointManager`: tmp-file +
  rename commit, manifest last, bounded retention) plus
  :func:`run_elastic`, which restarts the training function from the last
  committed epoch after a failure — the reference's "restart worker with
  is_recovery=1" flow collapsed into one process-local harness, with the
  pod scheduler (GKE/JobSet) playing the tracker's role across hosts.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from . import resilience
from .base import MXNetError
from .resilience import chaos

__all__ = ["CheckpointManager", "run_elastic", "start_heartbeat",
           "stop_heartbeat", "get_dead_nodes"]

_LOG = logging.getLogger("mxnet_tpu.elastic")

# ---------------------------------------------------------------------------
# heartbeats over the jax coordination service
# ---------------------------------------------------------------------------

_HB_PREFIX = "mxtpu_heartbeat/"
_hb_thread: Optional[threading.Thread] = None
_hb_stop = threading.Event()


def _coord_client():
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


def start_heartbeat(interval: float = 2.0) -> bool:
    """Begin publishing this process's liveness (reference: ps-lite node
    heartbeats to the scheduler). Returns False when no distributed runtime
    is active (single-process: nothing to detect)."""
    global _hb_thread
    client = _coord_client()
    if client is None:
        return False
    import jax

    if jax.process_count() <= 1:
        return False
    if _hb_thread is not None and _hb_thread.is_alive():
        return True
    _hb_stop.clear()
    rank = jax.process_index()

    def beat():
        key = "%s%d" % (_HB_PREFIX, rank)
        while not _hb_stop.wait(interval):
            try:
                client.key_value_set(key, repr(time.time()), allow_overwrite=True)
            except Exception:  # pragma: no cover - service shutting down
                return

    client.key_value_set("%s%d" % (_HB_PREFIX, rank), repr(time.time()),
                         allow_overwrite=True)
    _hb_thread = threading.Thread(target=beat, daemon=True,
                                  name="mxtpu-heartbeat")
    _hb_thread.start()
    return True


def stop_heartbeat() -> None:
    _hb_stop.set()


def get_dead_nodes(timeout: float = 10.0) -> List[int]:
    """Ranks whose heartbeat is older than ``timeout`` seconds (reference
    ``KVStoreDist::GetDeadNodes``, kvstore_dist.h:121). Ranks that never
    published a heartbeat are reported dead too."""
    client = _coord_client()
    if client is None:
        return []
    import jax

    if jax.process_count() <= 1:
        return []
    now = time.time()
    dead = []
    for rank in range(jax.process_count()):
        try:
            raw = client.key_value_try_get("%s%d" % (_HB_PREFIX, rank))
            if now - float(raw) > timeout:
                dead.append(rank)
        except Exception:  # no heartbeat published
            dead.append(rank)
    return dead


# ---------------------------------------------------------------------------
# atomic checkpoints
# ---------------------------------------------------------------------------

def _fsync_file(path: str) -> None:
    """Flush a written file's data to stable storage before it is renamed
    into place (rename-then-crash must not expose torn contents)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Flush a directory entry (the rename itself) to stable storage.
    Directory fds are a POSIX notion; where they can't be opened (or fsync
    on them is rejected, e.g. some network filesystems) durability falls
    back to the filesystem's own ordering."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(path, flags)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs-dependent
        pass
    finally:
        os.close(fd)


class CheckpointManager(object):
    """Atomic, bounded-retention checkpoints for elastic resume.

    Artifacts per epoch mirror the reference's two-file contract
    (``prefix-####.params`` + optimizer states, model.py:383): parameters
    via ``Block.save_parameters``/raw dict save, trainer/updater states via
    ``Trainer.save_states``. Every file is written to a tmp path and
    ``os.replace``d; the manifest (JSON, listing the epoch's files) is
    committed LAST, so a crash mid-save can never leave a readable-but-torn
    checkpoint — resume only ever sees fully committed epochs.
    """

    def __init__(self, directory: str, prefix: str = "ckpt",
                 max_keep: int = 5):
        self.directory = directory
        self.prefix = prefix
        self.max_keep = max_keep
        os.makedirs(directory, exist_ok=True)
        # serializes checkpoint writes on the host dependency engine when
        # saving asynchronously (write-after-write on one var keeps commits
        # ordered; reference: checkpoint IO rides the engine like any op)
        from . import engine as _engine

        self._engine = _engine
        self._io_var = _engine.new_var()

    # -- paths -------------------------------------------------------------
    def _manifest_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.manifest.json" % (self.prefix, epoch))

    def _params_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.params" % (self.prefix, epoch))

    def _states_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.states" % (self.prefix, epoch))

    @staticmethod
    def _atomic_write(path: str, writer: Callable[[str], None]) -> None:
        """tmp + fsync + rename + directory-fsync commit. The rename alone
        (the previous implementation) is atomic against concurrent READERS
        but not crash-durable: after a power loss the file system may
        replay the rename before the tmp file's data blocks, leaving a
        committed name with torn contents — exactly the state the manifest
        protocol promises can't exist. fsync the data before the rename
        and the directory entry after it, and the commit point is real.
        A failed attempt always removes its tmp file (no stale partials
        for a retry or a later save to trip over)."""
        tmp = path + ".tmp.%d" % os.getpid()
        try:
            writer(tmp)
            _fsync_file(tmp)
            chaos.maybe_fail("ckpt.commit")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        _fsync_dir(os.path.dirname(path) or ".")

    def _commit(self, path: str, writer: Callable[[str], None]) -> None:
        """One durable file commit under the resilience retry policy: a
        transient write failure (or injected ``ckpt.commit`` fault) retries
        with backoff instead of losing the checkpoint."""
        resilience.call("ckpt.commit",
                        lambda: self._atomic_write(path, writer))

    # -- save/restore ------------------------------------------------------
    def save(self, epoch: int, net=None, trainer=None,
             params: Optional[Dict] = None,
             metadata: Optional[Dict] = None, async_save: bool = False) -> str:
        """Commit a checkpoint for ``epoch``. ``net`` is a Gluon Block (or
        pass a raw name→NDArray ``params`` dict); ``trainer`` optionally
        adds optimizer state.

        ``async_save=True`` snapshots the parameter values now (host copy)
        and performs the file writes on the host engine so training
        continues immediately; writes to this manager stay ordered, and
        :meth:`wait` / the next synchronous call joins them.
        """
        if async_save:
            # EVERYTHING is serialized to bytes NOW — params through the same
            # save_parameters/io_utils code path the sync branch uses (so
            # restore naming matches) and optimizer state through
            # trainer.save_states — because serializing later on the engine
            # thread would snapshot a LATER training step than the caller saw.
            import tempfile

            def _to_bytes(writer):
                fd, tmp = tempfile.mkstemp(suffix=".snap")
                os.close(fd)
                try:
                    writer(tmp)
                    with open(tmp, "rb") as f:
                        return f.read()
                finally:
                    os.remove(tmp)

            params_bytes = None
            if net is not None:
                params_bytes = _to_bytes(lambda p: net.save_parameters(p))
            elif params is not None:
                from .ndarray import io_utils

                snap = {k: (v.asnumpy() if hasattr(v, "asnumpy") else
                            np.asarray(v)) for k, v in params.items()}
                params_bytes = _to_bytes(lambda p: io_utils.save(p, snap))
            states_bytes = None
            if trainer is not None:
                states_bytes = _to_bytes(lambda p: trainer.save_states(p))

            def commit():
                files = {}
                if params_bytes is not None:
                    self._commit(
                        self._params_path(epoch),
                        lambda p: open(p, "wb").write(params_bytes))
                    files["params"] = os.path.basename(self._params_path(epoch))
                if states_bytes is not None:
                    self._commit(
                        self._states_path(epoch),
                        lambda p: open(p, "wb").write(states_bytes))
                    files["states"] = os.path.basename(self._states_path(epoch))
                manifest = {"epoch": epoch, "time": time.time(),
                            "files": files, "metadata": metadata or {}}
                self._commit(
                    self._manifest_path(epoch),
                    lambda p: open(p, "w").write(json.dumps(manifest)))
                self._retire_old()

            self._engine.push(commit, mutable_vars=[self._io_var])
            return self._manifest_path(epoch)
        files = {}
        if net is not None:
            self._commit(self._params_path(epoch),
                         lambda p: net.save_parameters(p))
            files["params"] = os.path.basename(self._params_path(epoch))
        elif params is not None:
            from .ndarray import io_utils

            self._commit(self._params_path(epoch),
                         lambda p: io_utils.save(p, params))
            files["params"] = os.path.basename(self._params_path(epoch))
        if trainer is not None:
            self._commit(self._states_path(epoch),
                         lambda p: trainer.save_states(p))
            files["states"] = os.path.basename(self._states_path(epoch))
        manifest = {"epoch": epoch, "time": time.time(), "files": files,
                    "metadata": metadata or {}}
        self._commit(
            self._manifest_path(epoch),
            lambda p: open(p, "w").write(json.dumps(manifest)))
        self._retire_old()
        return self._manifest_path(epoch)

    def _epochs(self) -> List[int]:
        out = []
        for f in os.listdir(self.directory):
            if f.startswith(self.prefix + "-") and f.endswith(".manifest.json"):
                try:
                    out.append(int(f[len(self.prefix) + 1:-len(".manifest.json")]))
                except ValueError:
                    continue
        return sorted(out)

    def _retire_old(self) -> None:
        epochs = self._epochs()
        for e in epochs[:-self.max_keep] if self.max_keep else []:
            for path in (self._manifest_path(e), self._params_path(e),
                         self._states_path(e)):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def wait(self) -> None:
        """Join pending async saves (re-raising any write failure)."""
        self._engine.wait_for_var(self._io_var)

    def latest_epoch(self) -> int:
        """Newest committed epoch, or -1. Joins pending async saves first."""
        self.wait()
        epochs = self._epochs()
        return epochs[-1] if epochs else -1

    def restore(self, net=None, trainer=None, epoch: Optional[int] = None):
        """Load the latest (or given) committed checkpoint into net/trainer.
        Returns the epoch restored, or -1 when none exists."""
        if epoch is None:
            epoch = self.latest_epoch()
        if epoch < 0:
            return -1
        with open(self._manifest_path(epoch)) as f:
            manifest = json.load(f)
        if net is not None and "params" in manifest["files"]:
            net.load_parameters(os.path.join(self.directory,
                                             manifest["files"]["params"]))
        if trainer is not None and "states" in manifest["files"]:
            trainer.load_states(os.path.join(self.directory,
                                             manifest["files"]["states"]))
        return epoch

    def load_params(self, epoch: Optional[int] = None) -> Dict:
        from .ndarray import io_utils

        if epoch is None:
            epoch = self.latest_epoch()
        if epoch < 0:
            raise MXNetError("no committed checkpoint to load")
        return io_utils.load(self._params_path(epoch))


# ---------------------------------------------------------------------------
# elastic run loop
# ---------------------------------------------------------------------------

def run_elastic(train_fn: Callable[[int, CheckpointManager], object],
                manager: CheckpointManager, max_restarts: int = 3,
                restart_delay: float = 1.0, restart_backoff: float = 2.0,
                max_restart_delay: float = 60.0):
    """Run ``train_fn(start_epoch, manager)`` with automatic resume.

    On an exception the function is restarted from
    ``manager.latest_epoch() + 1`` — the epoch after the last COMMITTED
    checkpoint — up to ``max_restarts`` times; the final failure is
    re-raised. This is the reference's restarted-worker recovery
    (``is_recovery``, kvstore_dist.h:52) for a checkpoint-based world.

    Restart ``n`` waits ``restart_delay * restart_backoff**(n-1)`` seconds
    (capped at ``max_restart_delay``): a deterministic early-crash (bad
    config, poisoned shard) backs off instead of spinning a tight
    crash-restart loop that hammers the checkpoint directory and floods
    logs. ``restart_delay=0`` disables the wait (tests). Each restart
    ticks ``mxnet_retries_total{site="elastic.restart",outcome="retry"}``.
    """
    restarts = resilience.policies.retries_counter()
    attempt = 0
    while True:
        start_epoch = manager.latest_epoch() + 1
        try:
            return train_fn(start_epoch, manager)
        except KeyboardInterrupt:
            raise
        except Exception as exc:  # noqa: BLE001 - the point of the harness
            attempt += 1
            if attempt > max_restarts:
                restarts.inc(site="elastic.restart", outcome="exhausted")
                raise
            restarts.inc(site="elastic.restart", outcome="retry")
            delay = min(restart_delay * (restart_backoff ** (attempt - 1)),
                        max_restart_delay) if restart_delay else 0.0
            _LOG.warning("train_fn failed (%s); restart %d/%d from epoch %d "
                         "in %.1fs", exc, attempt, max_restarts,
                         manager.latest_epoch() + 1, delay)
            if delay:
                time.sleep(delay)
