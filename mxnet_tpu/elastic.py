"""Preemption-aware elastic training: failure detection, sharded async
checkpoints, deterministic resume, and a supervised restart loop.

TPU-native re-design of the reference's fault story (SURVEY §5.3), which
lives in ps-lite: scheduler heartbeats, ``KVStoreDist::GetDeadNodes(timeout)``
(kvstore_dist.h:121) and the ``is_recovery`` re-rendezvous flag
(kvstore_dist.h:52,138). A TPU job has no parameter server to survive a
worker — SPMD collectives fail as a unit — and on preemptible slices the
dominant failure is the *scheduler taking the machine back*, so the
equivalent capability is:

- **liveness**: every worker heartbeats through the jax coordination
  service's key-value store; :func:`get_dead_nodes` reports ranks whose
  heartbeat went stale (the ``GetDeadNodes`` API, same timeout contract);
- **durability**: atomic checkpoints (:class:`CheckpointManager`:
  fsync + rename commit, per-file content hashes, manifest committed
  LAST, bounded retention that can never retire the newest committed
  epoch). A ZeRO-partitioned updater (``fastpath.zero``) saves each dp
  shard *directly* — no materialize/all-gather, no HBM spike — into
  per-shard files under a topology manifest, and restore re-buckets onto
  ANY dp size; a corrupted or missing shard falls back to the previous
  committed epoch instead of raising. ``async_save`` snapshots state to
  host bytes at the step boundary and writes/fsyncs on the host engine,
  overlapping subsequent steps, with :meth:`CheckpointManager.wait`
  barriers so a new save or a preemption flush never races a pending one;
- **determinism**: checkpoints carry the data-iterator cursor
  (``state_dict``/``set_state`` on the io iterators), the RNG streams
  (``mx.random.get_state``) and the optimizer's step counters, so a
  killed-and-resumed run is bit-identical to an uninterrupted one
  (asserted in tests/test_elastic_resume.py);
- **preemption**: a SIGTERM / ``MXNET_PREEMPTION_FILE`` watcher turns the
  eviction notice into a best-effort checkpoint-now (:func:`step_boundary`)
  and a clean :class:`Preempted` exit;
- **supervision**: :func:`run_elastic` restarts the training function
  from the last COMMITTED epoch after a crash, backs off exponentially,
  treats *no step progress within* ``MXNET_ELASTIC_STALL_SECS`` as a hang
  (restart, not an eternal wedge), resets the restart budget whenever an
  attempt commits new progress (a long run with occasional preemptions is
  not killed by ``max_restarts`` accumulated over its lifetime), and
  publishes per-restart telemetry plus the
  ``mxnet_elastic_goodput_ratio`` gauge.

The whole save→kill→resume cycle is chaos-tested through the PR-4
harness: ``action=kill`` at the ``elastic.step`` site is kill-at-step,
``action=torn-write``/``drop-shard`` at ``ckpt.shard`` corrupt or lose a
committed shard — recovery must never crash (docs/elastic.md runbook).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import resilience, telemetry
from .base import MXNetError, fetch_host, get_env
from .resilience import chaos
from .telemetry import flightrec as _flightrec

__all__ = ["CheckpointManager", "run_elastic", "start_heartbeat",
           "stop_heartbeat", "get_dead_nodes",
           "Preempted", "StallError", "step_boundary", "note_progress",
           "request_preemption", "clear_preemption", "preempt_requested",
           "start_preemption_watcher"]

_LOG = logging.getLogger("mxnet_tpu.elastic")

# ---------------------------------------------------------------------------
# heartbeats over the jax coordination service
# ---------------------------------------------------------------------------

_HB_PREFIX = "mxtpu_heartbeat/"
_hb_thread: Optional[threading.Thread] = None
_hb_stop = threading.Event()


def _coord_client():
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


def start_heartbeat(interval: float = 2.0) -> bool:
    """Begin publishing this process's liveness (reference: ps-lite node
    heartbeats to the scheduler). Returns False when no distributed runtime
    is active (single-process: nothing to detect)."""
    global _hb_thread
    client = _coord_client()
    if client is None:
        return False
    import jax

    if jax.process_count() <= 1:
        return False
    if _hb_thread is not None and _hb_thread.is_alive():
        return True
    _hb_stop.clear()
    rank = jax.process_index()

    def beat():
        key = "%s%d" % (_HB_PREFIX, rank)
        while not _hb_stop.wait(interval):
            try:
                client.key_value_set(key, repr(time.time()), allow_overwrite=True)
            except Exception:  # pragma: no cover - service shutting down
                return

    client.key_value_set("%s%d" % (_HB_PREFIX, rank), repr(time.time()),
                         allow_overwrite=True)
    _hb_thread = threading.Thread(target=beat, daemon=True,
                                  name="mxtpu-heartbeat")
    _hb_thread.start()
    return True


def stop_heartbeat() -> None:
    _hb_stop.set()


def get_dead_nodes(timeout: float = 10.0) -> List[int]:
    """Ranks whose heartbeat is older than ``timeout`` seconds (reference
    ``KVStoreDist::GetDeadNodes``, kvstore_dist.h:121). Ranks that never
    published a heartbeat are reported dead too."""
    client = _coord_client()
    if client is None:
        return []
    import jax

    if jax.process_count() <= 1:
        return []
    now = time.time()
    dead = []
    for rank in range(jax.process_count()):
        try:
            raw = client.key_value_try_get("%s%d" % (_HB_PREFIX, rank))
            if now - float(raw) > timeout:
                dead.append(rank)
        except Exception:  # no heartbeat published
            dead.append(rank)
    return dead


# ---------------------------------------------------------------------------
# preemption signal + supervision primitives
# ---------------------------------------------------------------------------


class Preempted(MXNetError):
    """The run is being evicted (SIGTERM / preemption file): state was
    flushed best-effort and the process should exit cleanly so the
    scheduler can reschedule it. :func:`run_elastic` re-raises this
    WITHOUT consuming a restart — rescheduling is the pod supervisor's
    job, not the in-process loop's."""


class StallError(MXNetError):
    """No step progress within ``MXNET_ELASTIC_STALL_SECS`` — the hang
    class of failure (wedged accelerator tunnel, deadlocked input
    pipeline) surfaced as a restartable error instead of an eternal
    wedge."""


_PREEMPT = threading.Event()
_PROGRESS_LOCK = threading.Lock()
_PROGRESS = [time.monotonic()]
_SIGTERM_INSTALLED = False
_FILE_WATCHER: Optional[threading.Thread] = None

#: per-thread attempt bookkeeping: the stall watchdog abandons a wedged
#: attempt thread by flipping its ``cancelled`` event — the zombie then
#: STOPS at its next step boundary instead of training on, so it can
#: neither feed heartbeats that mask a stall in the replacement attempt
#: nor keep drawing from the process-global RNG streams underneath it.
_ATTEMPT_TL = threading.local()


def _attempt_cancelled() -> Optional[threading.Event]:
    return getattr(_ATTEMPT_TL, "cancelled", None)


def note_progress() -> None:
    """Heartbeat for the stall watchdog: called by :func:`step_boundary`
    and by every checkpoint commit. A cancelled (watchdog-abandoned)
    attempt thread's heartbeats are dropped — only the live attempt may
    feed the watchdog."""
    ev = _attempt_cancelled()
    if ev is not None and ev.is_set():
        return
    with _PROGRESS_LOCK:
        _PROGRESS[0] = time.monotonic()


def _last_progress() -> float:
    with _PROGRESS_LOCK:
        return _PROGRESS[0]


def request_preemption() -> None:
    """Raise the preemption flag in-process (tests; ops tooling uses the
    ``MXNET_PREEMPTION_FILE`` touch-file or SIGTERM)."""
    _PREEMPT.set()


def clear_preemption() -> None:
    _PREEMPT.clear()


def _preemption_file() -> str:
    return str(get_env("MXNET_PREEMPTION_FILE", "", str, cache=False))


def preempt_requested() -> bool:
    """Whether an eviction notice is pending: the in-process flag, a
    delivered SIGTERM, or the existence of ``MXNET_PREEMPTION_FILE``
    (the file is polled here too, so the notice is seen even when the
    watcher thread was never started)."""
    if _PREEMPT.is_set():
        return True
    path = _preemption_file()
    if path and os.path.exists(path):
        _PREEMPT.set()
        return True
    return False


def start_preemption_watcher(poll_interval: float = 1.0) -> bool:
    """Install the preemption listeners: a SIGTERM handler (main thread
    only — signal delivery is a main-thread affair in CPython) and, when
    ``MXNET_PREEMPTION_FILE`` names a path, a polling thread watching for
    its appearance (the GKE/maintenance-event pattern: the node agent
    touches a file ahead of eviction). Idempotent; returns whether any
    listener is active. :func:`run_elastic` calls this on entry."""
    global _SIGTERM_INSTALLED, _FILE_WATCHER
    if not _SIGTERM_INSTALLED and \
            threading.current_thread() is threading.main_thread():
        try:
            import signal

            prev = signal.getsignal(signal.SIGTERM)

            def handler(signum, frame):
                _PREEMPT.set()
                # black box first: if the grace period is short, the dump
                # must not depend on reaching the next step boundary
                _flightrec.record("preemption.sigterm")
                _flightrec.dump("SIGTERM (preemption notice)")
                _LOG.warning("SIGTERM received: preemption checkpoint will "
                             "run at the next step boundary")
                if callable(prev):
                    try:
                        prev(signum, frame)
                    except Exception:  # noqa: BLE001 - the chained
                        # handler's failure must not lose OUR notice
                        _LOG.exception("chained SIGTERM handler failed")

            signal.signal(signal.SIGTERM, handler)
            _SIGTERM_INSTALLED = True
        except (ValueError, OSError):  # pragma: no cover - restricted env
            pass
    if (_FILE_WATCHER is None or not _FILE_WATCHER.is_alive()) \
            and _preemption_file():
        def poll():
            while not _PREEMPT.wait(poll_interval):
                path = _preemption_file()
                if path and os.path.exists(path):
                    _PREEMPT.set()
                    return

        _FILE_WATCHER = threading.Thread(target=poll, daemon=True,
                                         name="mxtpu-preempt-watch")
        _FILE_WATCHER.start()
    return _SIGTERM_INSTALLED or _FILE_WATCHER is not None


def step_boundary(manager: Optional["CheckpointManager"] = None,
                  save_fn: Optional[Callable[[], Any]] = None) -> None:
    """Per-step hook for elastic training loops (``trainplane.fit`` calls
    it per batch; hand-rolled loops should too):

    1. heartbeats the stall watchdog (:func:`note_progress`);
    2. is the ``elastic.step`` chaos site — an ``action=kill`` schedule
       simulates preemption-without-warning exactly here (kill-at-step);
    3. honors a pending graceful preemption: runs the best-effort
       ``save_fn`` (checkpoint-now), joins pending async writes on
       ``manager``, counts ``mxnet_preemptions_total`` and raises
       :class:`Preempted` for a clean exit.

    An attempt the stall watchdog already abandoned stops HERE: its next
    boundary raises :class:`StallError` so the zombie thread cannot keep
    training (committing stale epochs, consuming RNG draws) underneath
    the replacement attempt.
    """
    ev = _attempt_cancelled()
    if ev is not None and ev.is_set():
        raise StallError("attempt was abandoned by the stall watchdog; "
                         "a replacement attempt owns the run now")
    note_progress()
    chaos.maybe_fail("elastic.step")
    if not preempt_requested():
        return
    telemetry.PREEMPTIONS.inc()
    _flightrec.record("preemption.honored")
    if save_fn is not None:
        try:
            save_fn()
        except Exception:  # noqa: BLE001 - best-effort by contract: the
            # LAST committed epoch is still durable; losing the final
            # window beats dying inside the eviction grace period
            _LOG.exception("preemption checkpoint-now failed; the run will "
                           "resume from the last committed epoch")
    if manager is not None:
        try:
            manager.wait()
        except Exception:  # noqa: BLE001 - same best-effort contract
            _LOG.exception("pending async checkpoint failed during "
                           "preemption flush")
    raise Preempted("preemption requested (SIGTERM or %s)"
                    % (_preemption_file() or "request_preemption()"))


# ---------------------------------------------------------------------------
# atomic checkpoints
# ---------------------------------------------------------------------------

def _fsync_file(path: str) -> None:
    """Flush a written file's data to stable storage before it is renamed
    into place (rename-then-crash must not expose torn contents)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Flush a directory entry (the rename itself) to stable storage.
    Directory fds are a POSIX notion; where they can't be opened (or fsync
    on them is rejected, e.g. some network filesystems) durability falls
    back to the filesystem's own ordering."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(path, flags)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs-dependent
        pass
    finally:
        os.close(fd)


def _bytes_of(writer: Callable[[str], None]) -> bytes:
    """Run a path-writing serializer into memory: the snapshot half of an
    async save (serialize NOW on the caller, write later on the engine)."""
    fd, tmp = tempfile.mkstemp(suffix=".snap")
    os.close(fd)
    try:
        writer(tmp)
        with open(tmp, "rb") as f:
            return f.read()
    finally:
        os.remove(tmp)


def _write_bytes(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)


def commit_bytes(path: str, data: bytes, kind: str) -> None:
    """One durable standalone commit for callers OUTSIDE a
    :class:`CheckpointManager` (symbol/module save paths): the same
    tmp+fsync+rename atomic write, ``ckpt.commit`` retry policy,
    ``mxnet_ckpt_bytes_total`` accounting and stall-watchdog progress
    the manager's ``_commit_bytes`` gives every managed file."""
    telemetry.CKPT_BYTES.inc(len(data), kind=kind)
    resilience.call(
        "ckpt.commit",
        lambda: CheckpointManager._atomic_write(
            path, lambda p: _write_bytes(p, data)))
    _flightrec.record("ckpt.commit", file=os.path.basename(path),
                      artifact=kind, bytes=len(data))
    note_progress()


def _host_snapshot(params: Dict) -> Dict:
    """Host copies of a name→array dict in ONE batched transfer
    (``base.fetch_host``) — the save IS the host snapshot, but it needn't
    drain the device stream once per parameter the way a per-item
    ``.asnumpy()`` loop does."""
    nd_keys = [k for k, v in params.items() if hasattr(v, "asnumpy")]
    fetched = dict(zip(nd_keys, fetch_host([params[k] for k in nd_keys])
                       if nd_keys else []))
    return {k: fetched[k] if k in fetched else np.asarray(v)
            for k, v in params.items()}


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _updater_of(trainer):
    """The state-owning Updater behind either a gluon ``Trainer`` or a
    bare ``optimizer.Updater`` (both are accepted wherever checkpoints
    take a ``trainer``)."""
    if trainer is None:
        return None
    if hasattr(trainer, "_updaters"):
        ups = getattr(trainer, "_updaters") or []
        return ups[0] if ups else None
    if hasattr(trainer, "states") and hasattr(trainer, "optimizer"):
        return trainer
    return None


class _CorruptCheckpoint(MXNetError):
    """A committed-looking epoch that cannot actually be restored
    (missing referenced file, content-hash mismatch, unreadable
    manifest). Restore walks back to an older epoch instead of raising."""


class CheckpointManager(object):
    """Atomic, hashed, bounded-retention checkpoints for elastic resume.

    Artifacts per epoch extend the reference's two-file contract
    (``prefix-####.params`` + optimizer states, model.py:383):

    ========================  ============================================
    file                      contents
    ========================  ============================================
    ``*.params``              parameters (``Block.save_parameters`` / raw
                              dict via ``nd.save``)
    ``*.states``              materialized optimizer state
                              (``Trainer.save_states``) — replicated path
    ``*.shard{r}-of-{dp}``    dp rank ``r``'s piece of the ZeRO-partitioned
                              state flat buckets — sharded path (no
                              all-gather at save)
    ``*.repl``                replicated slots of a sharded save (the
                              level-1 fp32 masters)
    ``*.zmeta``               sharded-topology pickle: plan signature/
                              buckets/padding, state treedef templates,
                              the optimizer (with its step counters)
    ``*.train``               deterministic-resume pickle: data-iterator
                              cursor, RNG streams, caller extra state
    ``*.manifest.json``       the commit point: file list + sha256 per
                              file, written LAST
    ========================  ============================================

    Every file is written tmp + fsync + rename (+ directory fsync); the
    manifest commits last, so a crash mid-save can never leave a
    readable-but-torn checkpoint. Restore verifies the recorded content
    hashes and treats any mismatch or missing file as *uncommitted*,
    falling back to the previous committed epoch
    (``mxnet_ckpt_corruption_total`` counts each fallback).
    """

    def __init__(self, directory: str, prefix: str = "ckpt",
                 max_keep: int = 5):
        self.directory = directory
        self.prefix = prefix
        self.max_keep = max_keep
        self.last_restored_extra: Optional[Dict] = None
        os.makedirs(directory, exist_ok=True)
        # serializes checkpoint writes on the host dependency engine when
        # saving asynchronously (write-after-write on one var keeps commits
        # ordered; reference: checkpoint IO rides the engine like any op)
        from . import engine as _engine

        self._engine = _engine
        self._io_var = _engine.new_var()

    # -- paths -------------------------------------------------------------
    def _manifest_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.manifest.json" % (self.prefix, epoch))

    def _params_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.params" % (self.prefix, epoch))

    def _states_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.states" % (self.prefix, epoch))

    def _train_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.train" % (self.prefix, epoch))

    def _zmeta_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.zmeta" % (self.prefix, epoch))

    def _repl_path(self, epoch: int) -> str:
        return os.path.join(self.directory,
                            "%s-%04d.repl" % (self.prefix, epoch))

    def _shard_path(self, epoch: int, rank: int, dp: int) -> str:
        return os.path.join(self.directory, "%s-%04d.shard%d-of-%d"
                            % (self.prefix, epoch, rank, dp))

    @staticmethod
    def _atomic_write(path: str, writer: Callable[[str], None]) -> None:
        """tmp + fsync + rename + directory-fsync commit. The rename alone
        (the previous implementation) is atomic against concurrent READERS
        but not crash-durable: after a power loss the file system may
        replay the rename before the tmp file's data blocks, leaving a
        committed name with torn contents — exactly the state the manifest
        protocol promises can't exist. fsync the data before the rename
        and the directory entry after it, and the commit point is real.
        A failed attempt always removes its tmp file (no stale partials
        for a retry or a later save to trip over)."""
        tmp = path + ".tmp.%d" % os.getpid()
        try:
            writer(tmp)
            _fsync_file(tmp)
            chaos.maybe_fail("ckpt.commit")
            os.replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        _fsync_dir(os.path.dirname(path) or ".")

    def _commit(self, path: str, writer: Callable[[str], None]) -> None:
        """One durable file commit under the resilience retry policy: a
        transient write failure (or injected ``ckpt.commit`` fault) retries
        with backoff instead of losing the checkpoint. Every successful
        commit is step progress for the stall watchdog."""
        resilience.call("ckpt.commit",
                        lambda: self._atomic_write(path, writer))
        _flightrec.record("ckpt.commit", file=os.path.basename(path))
        note_progress()

    def _commit_bytes(self, path: str, data: bytes, kind: str) -> None:
        # one commit idiom, shared with standalone callers (symbol/module)
        commit_bytes(path, data, kind)

    @staticmethod
    def _torn_write(path: str, data: bytes) -> None:
        """Chaos ``action=torn-write``: commit a DELIBERATELY truncated
        shard under the final name — the silently-torn-write failure a
        lying fsync or bitrot produces, which the manifest's content hash
        exists to catch (restore must fall back, never crash)."""
        with open(path, "wb") as f:  # tpulint: disable=non-atomic-write - simulating the torn commit IS the test
            f.write(data[:max(1, len(data) // 2)])
        _fsync_file(path)

    # -- save (legacy two-file contract) -----------------------------------
    def save(self, epoch: int, net=None, trainer=None,
             params: Optional[Dict] = None,
             metadata: Optional[Dict] = None, async_save: bool = False) -> str:
        """Commit a checkpoint for ``epoch``. ``net`` is a Gluon Block (or
        pass a raw name→NDArray ``params`` dict); ``trainer`` optionally
        adds optimizer state (materializing any ZeRO-sharded layout —
        use :meth:`save_training` for the shard-direct path).

        ``async_save=True`` snapshots the parameter values now (host copy)
        and performs the file writes on the host engine so training
        continues immediately; writes to this manager stay ordered, and
        :meth:`wait` / the next synchronous call joins them.
        """
        if async_save:
            # EVERYTHING is serialized to bytes NOW — params through the same
            # save_parameters/io_utils code path the sync branch uses (so
            # restore naming matches) and optimizer state through
            # trainer.save_states — because serializing later on the engine
            # thread would snapshot a LATER training step than the caller saw.
            params_bytes = None
            if net is not None:
                params_bytes = _bytes_of(lambda p: net.save_parameters(p))
            elif params is not None:
                from .ndarray import io_utils

                snap = _host_snapshot(params)
                params_bytes = _bytes_of(lambda p: io_utils.save(p, snap))
            states_bytes = None
            if trainer is not None:
                states_bytes = _bytes_of(lambda p: trainer.save_states(p))

            def commit():
                files = {}
                if params_bytes is not None:
                    self._commit_bytes(self._params_path(epoch),
                                       params_bytes, "params")
                    files["params"] = os.path.basename(self._params_path(epoch))
                if states_bytes is not None:
                    self._commit_bytes(self._states_path(epoch),
                                       states_bytes, "states")
                    files["states"] = os.path.basename(self._states_path(epoch))
                manifest = {"epoch": epoch, "time": time.time(),
                            "files": files, "metadata": metadata or {}}
                self._commit_manifest(epoch, manifest)
                self._retire_old()

            self._engine.push(commit, mutable_vars=[self._io_var])
            return self._manifest_path(epoch)
        files = {}
        if net is not None:
            self._commit(self._params_path(epoch),
                         lambda p: net.save_parameters(p))
            files["params"] = os.path.basename(self._params_path(epoch))
        elif params is not None:
            from .ndarray import io_utils

            self._commit(self._params_path(epoch),
                         lambda p: io_utils.save(p, params))
            files["params"] = os.path.basename(self._params_path(epoch))
        if trainer is not None:
            self._commit(self._states_path(epoch),
                         lambda p: trainer.save_states(p))
            files["states"] = os.path.basename(self._states_path(epoch))
        manifest = {"epoch": epoch, "time": time.time(), "files": files,
                    "metadata": metadata or {}}
        self._commit_manifest(epoch, manifest)
        self._retire_old()
        return self._manifest_path(epoch)

    def _commit_manifest(self, epoch: int, manifest: Dict) -> None:
        data = json.dumps(manifest).encode("utf-8")
        self._commit_bytes(self._manifest_path(epoch), data, "manifest")

    # -- save (the full training-state contract) ---------------------------
    def save_training(self, epoch: int, net=None, trainer=None,
                      params: Optional[Dict] = None, train_iter=None,
                      metadata: Optional[Dict] = None,
                      extra: Optional[Dict] = None, save_rng: bool = True,
                      async_save: bool = False, sharded="auto") -> str:
        """One complete training checkpoint: parameters, optimizer state,
        data-iterator cursor and RNG streams — everything deterministic
        resume needs, committed manifest-last with per-file sha256.

        Optimizer state routing (``sharded``):

        * ``"auto"`` (default) — when ``trainer``'s updater carries an
          active ZeRO plane (``MXNET_ZERO`` ≥ 1), each dp shard of the
          flat state buckets is saved DIRECTLY from its device shard:
          no materialize, no all-gather, no step-long full-state HBM
          spike (``mxnet_zero_materializations_total`` provably does not
          move). Otherwise the materialized ``Trainer.save_states`` path
          runs. ``MXNET_CKPT_SHARDED=0`` forces the materialized path
          (debugging escape hatch: single mesh-independent file).
        * ``False`` — always materialize (mesh-independent single file).

        ``async_save=True`` performs ONLY the device→host snapshot on the
        caller (one 1/dp copy per shard on the sharded path), then
        writes, fsyncs and commits on the host engine overlapping
        subsequent steps. A new save first :meth:`wait`\\ s for any
        pending one — two snapshots never interleave their writes.

        ``train_iter`` is any iterator implementing the
        ``state_dict``/``set_state`` cursor protocol (io.NDArrayIter and
        the prefetch pipelines do); ``save_rng`` captures
        ``mx.random.get_state()``. Returns the manifest path.
        """
        t0 = time.perf_counter()
        self.wait()  # barrier: never race a pending async save
        payloads: List[Tuple[str, bytes, str]] = []
        files: Dict[str, str] = {}
        hashes: Dict[str, str] = {}

        def add(name: str, path: str, data: bytes, kind: str) -> None:
            payloads.append((path, data, kind))
            files[name] = os.path.basename(path)
            hashes[name] = _sha256(data)

        if net is not None:
            add("params", self._params_path(epoch),
                _bytes_of(lambda p: net.save_parameters(p)), "params")
        elif params is not None:
            from .ndarray import io_utils

            snap = _host_snapshot(params)
            add("params", self._params_path(epoch),
                _bytes_of(lambda p: io_utils.save(p, snap)), "params")

        sharded_info = None
        shard_entries: List[Dict[str, Any]] = []
        updater = _updater_of(trainer)
        if trainer is not None:
            export = None
            if sharded is not False and \
                    get_env("MXNET_CKPT_SHARDED", 1, int, cache=False):
                export = self._sharded_export(updater)
                if export is None and sharded is True:
                    _LOG.warning("save_training(sharded=True) but no active "
                                 "ZeRO plane; saving materialized state")
            if export is not None:
                meta, shards, repl = export
                add("zmeta", self._zmeta_path(epoch), pickle.dumps(meta),
                    "meta")
                dp = int(meta["dp"])
                for r in range(dp):
                    data = pickle.dumps(shards[r])
                    path = self._shard_path(epoch, r, dp)
                    shard_entries.append({"file": os.path.basename(path),
                                          "sha256": _sha256(data),
                                          "rank": r})
                    payloads.append((path, data, "shard"))
                if repl:
                    add("repl", self._repl_path(epoch), pickle.dumps(repl),
                        "repl")
                sharded_info = {"dp": dp, "level": int(meta["level"]),
                                "mesh_shape": meta["mesh_shape"]}
            elif hasattr(trainer, "save_states"):
                add("states", self._states_path(epoch),
                    _bytes_of(lambda p: trainer.save_states(p)), "states")
            elif updater is not None:
                add("states", self._states_path(epoch),
                    updater.get_states(dump_optimizer=True), "states")

        train_state: Dict[str, Any] = {}
        if train_iter is not None and hasattr(train_iter, "state_dict"):
            train_state["iter"] = train_iter.state_dict()
        if save_rng:
            from . import random as _random

            train_state["rng"] = _random.get_state()
        if extra:
            train_state["extra"] = dict(extra)
        if train_state:
            add("train", self._train_path(epoch),
                pickle.dumps(train_state), "train")

        manifest = {"epoch": epoch, "time": time.time(), "format": 2,
                    "files": files, "hashes": hashes,
                    "shards": shard_entries, "sharded": sharded_info,
                    "metadata": metadata or {}}

        def commit():
            for path, data, kind in payloads:
                if kind == "shard":
                    try:
                        chaos.maybe_fail("ckpt.shard")
                    except chaos.TornWrite:
                        self._torn_write(path, data)
                        continue
                    except chaos.DropShard:
                        continue
                self._commit_bytes(path, data, kind)
            self._commit_manifest(epoch, manifest)
            self._retire_old()

        if async_save:
            self._engine.push(commit, mutable_vars=[self._io_var])
        else:
            commit()
        telemetry.CKPT_SAVE_MS.observe(
            (time.perf_counter() - t0) * 1e3,
            mode="async" if async_save else "sync")
        return self._manifest_path(epoch)

    def _sharded_export(self, updater):
        """The ZeRO plane's shard-direct snapshot, or ``None`` when the
        materialized path must run (no plane, plane without live buckets,
        buckets donated into a step that then failed)."""
        if updater is None:
            return None
        from .fastpath import zero

        plane = zero.plane_of(updater)
        if plane is None or plane.buckets is None:
            return None
        import jax

        for leaf in jax.tree_util.tree_leaves(plane.buckets):
            if getattr(leaf, "is_deleted", lambda: False)():
                return None
        try:
            meta, shards, repl = plane.shard_export()
        except Exception:  # noqa: BLE001 - never-a-crash: a failed shard
            # read degrades to the materialized save, not a lost epoch
            _LOG.exception("sharded state export failed; saving "
                           "materialized state instead")
            return None
        meta["optimizer"] = updater.optimizer
        return meta, shards, repl

    # -- manifest bookkeeping ----------------------------------------------
    def _epochs(self) -> List[int]:
        out = []
        for f in os.listdir(self.directory):
            if f.startswith(self.prefix + "-") and f.endswith(".manifest.json"):
                try:
                    out.append(int(f[len(self.prefix) + 1:-len(".manifest.json")]))
                except ValueError:
                    continue
        return sorted(out)

    def _read_manifest(self, epoch: int) -> Dict:
        try:
            with open(self._manifest_path(epoch)) as f:
                return json.load(f)
        except (OSError, ValueError) as exc:
            raise _CorruptCheckpoint("manifest for epoch %d unreadable: %s"
                                     % (epoch, exc))

    @staticmethod
    def _manifest_files(manifest: Dict) -> List[str]:
        """Every file basename a manifest commits to (legacy str values
        and format-2 alike, shard entries included)."""
        out = []
        for v in (manifest.get("files") or {}).values():
            out.append(v["file"] if isinstance(v, dict) else v)
        for s in manifest.get("shards") or []:
            out.append(s["file"])
        return out

    def _is_committed(self, epoch: int) -> bool:
        """A manifest whose referenced shard/param files are missing is
        NOT a committed checkpoint — resume must not anchor on it (the
        drop-one-shard failure mode, and half-retired epochs)."""
        try:
            manifest = self._read_manifest(epoch)
        except _CorruptCheckpoint:
            return False
        return all(os.path.isfile(os.path.join(self.directory, f))
                   for f in self._manifest_files(manifest))

    def _retire_old(self) -> None:
        """Bounded retention. ``max_keep <= 0``/None disables GC; any
        other value keeps AT LEAST one epoch, and the newest COMMITTED
        manifest is never retired regardless of how retention is
        (mis)configured — the last restorable state outranks the quota."""
        if not self.max_keep:
            return
        keep = max(1, int(self.max_keep))
        epochs = self._epochs()
        committed = [e for e in epochs if self._is_committed(e)]
        protect = {committed[-1]} if committed else set()
        for e in epochs[:-keep]:
            if e in protect:
                continue
            self._remove_epoch(e)

    def _remove_epoch(self, epoch: int) -> None:
        # the manifest goes FIRST so a crash mid-retire leaves the epoch
        # reading as uncommitted, never as committed-but-holey
        try:
            os.remove(self._manifest_path(epoch))
        except OSError:
            pass
        stem = "%s-%04d." % (self.prefix, epoch)
        for f in os.listdir(self.directory):
            if f.startswith(stem):
                try:
                    os.remove(os.path.join(self.directory, f))
                except OSError:
                    pass

    def wait(self) -> None:
        """Join pending async saves (re-raising any write failure) — the
        barrier new saves and preemption flushes take before touching the
        directory."""
        self._engine.wait_for_var(self._io_var)

    def flush(self) -> None:
        """Alias of :meth:`wait` — the preemption-path name."""
        self.wait()

    def latest_epoch(self) -> int:
        """Newest COMMITTED epoch (manifest readable and every referenced
        file present), or -1. Joins pending async saves first."""
        self.wait()
        for e in reversed(self._epochs()):
            if self._is_committed(e):
                return e
        return -1

    # -- restore ------------------------------------------------------------
    def restore(self, net=None, trainer=None, epoch: Optional[int] = None):
        """Load the latest (or given) committed checkpoint into net/trainer.
        Returns the epoch restored, or -1 when none exists. Corrupt epochs
        (hash mismatch, missing file) fall back to older ones."""
        return self.restore_training(net=net, trainer=trainer, epoch=epoch,
                                     restore_rng=False)

    def restore_training(self, net=None, trainer=None, train_iter=None,
                         epoch: Optional[int] = None,
                         restore_rng: bool = True) -> int:
        """Restore the full training state saved by :meth:`save_training`
        (or :meth:`save`): parameters into ``net``, optimizer state into
        ``trainer`` (sharded checkpoints are re-bucketed through the flat
        plan — the target mesh's dp size need not match the one saved),
        the data-iterator cursor into ``train_iter`` and the RNG streams.

        Walks committed epochs newest-first: an epoch whose content
        hashes mismatch or whose files vanished counts
        ``mxnet_ckpt_corruption_total`` and FALLS BACK to the previous
        committed epoch — corruption costs a window of training, never
        the run. Returns the epoch restored (-1 when none); the saved
        ``extra`` dict lands in :attr:`last_restored_extra`."""
        t0 = time.perf_counter()
        self.wait()
        self.last_restored_extra = None
        explicit = epoch is not None
        candidates = [epoch] if explicit else list(reversed(self._epochs()))
        for e in candidates:
            try:
                extra = self._restore_epoch(e, net, trainer, train_iter,
                                            restore_rng)
            except _CorruptCheckpoint as exc:
                telemetry.CKPT_CORRUPTION.inc()
                if explicit:
                    raise MXNetError("checkpoint epoch %d unusable: %s"
                                     % (e, exc))
                _LOG.warning("checkpoint epoch %d unusable (%s); falling "
                             "back to the previous committed epoch", e, exc)
                continue
            self.last_restored_extra = extra
            telemetry.CKPT_RESTORE_MS.observe(
                (time.perf_counter() - t0) * 1e3)
            return e
        return -1

    @staticmethod
    def _want_hash(manifest: Dict, name: str, fname: str) -> Optional[str]:
        want = (manifest.get("hashes") or {}).get(name)
        if want is None and name == "shard":
            want = next((s["sha256"] for s in manifest.get("shards") or []
                         if s["file"] == fname), None)
        return want

    def _verified_read(self, manifest: Dict, name: str,
                       fname: str) -> bytes:
        """Read an artifact that is CONSUMED from memory (shards, zmeta,
        repl, train), verifying its recorded hash on the way."""
        path = os.path.join(self.directory, fname)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as exc:
            raise _CorruptCheckpoint("missing %s file %s: %s"
                                     % (name, fname, exc))
        want = self._want_hash(manifest, name, fname)
        if want is not None and _sha256(data) != want:
            raise _CorruptCheckpoint("content hash mismatch on %s (%s)"
                                     % (fname, name))
        return data

    def _verify_file(self, manifest: Dict, name: str, fname: str) -> None:
        """Stream-verify an artifact that is loaded from DISK by its
        consumer (params, states): a multi-GB params file must not be
        held in host memory just to hash it."""
        want = self._want_hash(manifest, name, fname)
        if want is None:
            return
        path = os.path.join(self.directory, fname)
        digest = hashlib.sha256()
        try:
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    digest.update(chunk)
        except OSError as exc:
            raise _CorruptCheckpoint("missing %s file %s: %s"
                                     % (name, fname, exc))
        if digest.hexdigest() != want:
            raise _CorruptCheckpoint("content hash mismatch on %s (%s)"
                                     % (fname, name))

    def _restore_epoch(self, epoch: int, net, trainer, train_iter,
                       restore_rng) -> Optional[Dict]:
        manifest = self._read_manifest(epoch)
        missing = [f for f in self._manifest_files(manifest)
                   if not os.path.isfile(os.path.join(self.directory, f))]
        if missing:
            raise _CorruptCheckpoint("missing files: %s" % ", ".join(missing))
        raw_files = manifest.get("files") or {}
        fnames = {n: (v["file"] if isinstance(v, dict) else v)
                  for n, v in raw_files.items()}
        # verify hashes BEFORE mutating anything: a half-applied restore
        # would be worse than the corruption it detected. params/states
        # are stream-verified (their consumers load from disk); the
        # memory-consumed artifacts are read-and-verified in one pass
        blobs: Dict[str, bytes] = {}
        for name, fname in fnames.items():
            if name in ("params", "states"):
                self._verify_file(manifest, name, fname)
            else:
                blobs[name] = self._verified_read(manifest, name, fname)
        shard_blobs: List[Tuple[int, bytes]] = []
        for s in manifest.get("shards") or []:
            rank = int(s.get("rank", len(shard_blobs)))  # tpulint: disable=host-sync - manifest JSON int, no device value
            shard_blobs.append((rank,
                                self._verified_read(manifest, "shard",
                                                    s["file"])))

        if net is not None and "params" in fnames:
            net.load_parameters(os.path.join(self.directory,
                                             fnames["params"]))
        if trainer is not None:
            if manifest.get("sharded"):
                self._restore_sharded(trainer, blobs, shard_blobs)
            elif "states" in fnames:
                states_path = os.path.join(self.directory,
                                           fnames["states"])
                if hasattr(trainer, "load_states"):
                    trainer.load_states(states_path)
                else:
                    with open(states_path, "rb") as f:
                        _updater_of(trainer).set_states(f.read())

        train_state: Dict[str, Any] = {}
        if "train" in blobs:
            try:
                train_state = pickle.loads(blobs["train"])
            except Exception as exc:  # noqa: BLE001 - treat as corruption
                raise _CorruptCheckpoint("train-state pickle unreadable: %s"
                                         % exc)
        if train_iter is not None and hasattr(train_iter, "set_state") \
                and "iter" in train_state:
            train_iter.set_state(train_state["iter"])
        if restore_rng and "rng" in train_state:
            from . import random as _random

            _random.set_state(train_state["rng"])
        return train_state.get("extra")

    def _restore_sharded(self, trainer, blobs: Dict[str, bytes],
                         shard_blobs: List[Tuple[int, bytes]]) -> None:
        """Rebuild plain per-parameter states from the per-rank shard
        files (concatenate rank pieces → strip via the saved flat-plan
        layout) and adopt them into the updater. The NEXT sharded step
        re-packs onto whatever mesh is live (``bucketing.flat_plan``
        with the new dp), which is how restore onto a different dp size
        round-trips exactly."""
        from .fastpath import zero

        try:
            meta = pickle.loads(blobs["zmeta"])
        except Exception as exc:  # noqa: BLE001 - treat as corruption
            raise _CorruptCheckpoint("zmeta pickle unreadable: %s" % exc)
        pieces: Dict[str, List[Tuple[int, np.ndarray]]] = {}
        for rank, data in shard_blobs:
            try:
                shard = pickle.loads(data)
            except Exception as exc:  # noqa: BLE001 - treat as corruption
                raise _CorruptCheckpoint("shard %d unreadable: %s"
                                         % (rank, exc))
            for key, arr in shard.items():
                pieces.setdefault(key, []).append((rank, arr))
        slot_arrays: Dict[str, np.ndarray] = {}
        for key, parts in pieces.items():
            parts.sort(key=lambda p: p[0])
            slot_arrays[key] = np.concatenate([a for _, a in parts]) \
                if len(parts) > 1 else parts[0][1]
        if "repl" in blobs:
            try:
                slot_arrays.update(pickle.loads(blobs["repl"]))
            except Exception as exc:  # noqa: BLE001 - treat as corruption
                raise _CorruptCheckpoint("repl pickle unreadable: %s" % exc)
        try:
            trees = zero.states_from_export(meta, slot_arrays)
        except (KeyError, ValueError) as exc:
            raise _CorruptCheckpoint("sharded state incomplete: %s" % exc)
        states = {idx: tree
                  for idx, tree in zip(meta["indices"], trees)}
        optimizer = meta.get("optimizer")
        updater = _updater_of(trainer)
        updater.adopt_states(states, optimizer=optimizer)
        if hasattr(trainer, "_updaters") and optimizer is not None:
            trainer._optimizer = optimizer
            for u in trainer._updaters:
                u.optimizer = optimizer

    def load_params(self, epoch: Optional[int] = None) -> Dict:
        from .ndarray import io_utils

        if epoch is None:
            epoch = self.latest_epoch()
        if epoch < 0:
            raise MXNetError("no committed checkpoint to load")
        return io_utils.load(self._params_path(epoch))


# ---------------------------------------------------------------------------
# elastic run loop
# ---------------------------------------------------------------------------


def _invoke_attempt(train_fn, start_epoch: int, manager: CheckpointManager,
                    stall_timeout: float):
    """Run one attempt. With a stall timeout, the attempt runs on a
    worker thread and the supervisor watches the progress heartbeat
    (:func:`note_progress` — fed by :func:`step_boundary` and every
    checkpoint commit): silence longer than the timeout raises
    :class:`StallError` and the wedged thread is abandoned — its
    ``cancelled`` event flips, so if it ever wakes it dies at its next
    step boundary (and its heartbeats are dropped meanwhile). A thread
    hung in a device wait cannot be interrupted from Python, but a
    never-waking thread also never touches RNG or disk; late commits
    from the abandonment window stay harmless behind the atomic-commit
    protocol (worst case: a hash-mismatch fallback)."""
    if stall_timeout <= 0:
        return train_fn(start_epoch, manager)
    box: Dict[str, Any] = {}
    done = threading.Event()
    cancelled = threading.Event()

    def runner():
        _ATTEMPT_TL.cancelled = cancelled
        try:
            box["result"] = train_fn(start_epoch, manager)
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            box["exc"] = exc
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name="mxtpu-elastic-train")
    note_progress()
    t.start()
    poll = max(0.01, min(0.25, stall_timeout / 4.0))
    while not done.wait(poll):
        if time.monotonic() - _last_progress() > stall_timeout:
            cancelled.set()
            # the hang class of death: dump the black box BEFORE the
            # restart machinery tears state down, so "what was the run
            # doing when it wedged" survives even if the restart also dies
            _flightrec.record("elastic.stall",
                              stall_timeout_s=stall_timeout)
            _flightrec.dump("elastic stall watchdog (no progress in "
                            "%.1fs)" % stall_timeout)
            raise StallError(
                "no step progress in %.1fs (MXNET_ELASTIC_STALL_SECS); "
                "treating the attempt as hung" % stall_timeout)
    if "exc" in box:
        raise box["exc"]
    return box["result"]


def run_elastic(train_fn: Callable[[int, CheckpointManager], object],
                manager: CheckpointManager, max_restarts: int = 3,
                restart_delay: float = 1.0, restart_backoff: float = 2.0,
                max_restart_delay: float = 60.0,
                stall_timeout: Optional[float] = None,
                watch_preemption: bool = True):
    """Run ``train_fn(start_epoch, manager)`` with automatic resume.

    On an exception the function is restarted from
    ``manager.latest_epoch() + 1`` — the epoch after the last COMMITTED
    checkpoint — and the final failure is re-raised. This is the
    reference's restarted-worker recovery (``is_recovery``,
    kvstore_dist.h:52) for a checkpoint-based world. Supervision rules:

    * the restart budget is ``max_restarts`` CONSECUTIVE unproductive
      attempts: any attempt that commits a newer epoch before failing
      resets the counter, so a week-long run with occasional preemptions
      is not killed by failures accumulated across its lifetime;
    * restart ``n`` waits ``restart_delay * restart_backoff**(n-1)``
      seconds (capped at ``max_restart_delay``); ``restart_delay=0``
      disables the wait (tests);
    * ``stall_timeout`` (default: ``MXNET_ELASTIC_STALL_SECS``, 0 = off)
      arms the hang watchdog: an attempt with no step progress for that
      long restarts instead of wedging forever;
    * :class:`Preempted` (the graceful-eviction exit from
      :func:`step_boundary`) flushes pending saves and re-raises WITHOUT
      consuming a restart — rescheduling belongs to the pod supervisor;
    * telemetry: ``mxnet_elastic_restarts_total{reason}`` per restart,
      ``mxnet_retries_total{site="elastic.restart"}`` (the PR-4 series),
      and ``mxnet_elastic_goodput_ratio`` — productive attempt time over
      wall time — updated at every transition.
    """
    if stall_timeout is None:
        stall_timeout = float(get_env("MXNET_ELASTIC_STALL_SECS", 0.0,
                                      float, cache=False))
    if watch_preemption:
        start_preemption_watcher()
    restarts = resilience.policies.retries_counter()
    attempt = 0
    wall0 = time.monotonic()
    productive = 0.0

    def goodput() -> None:
        wall = time.monotonic() - wall0
        if wall > 0:
            telemetry.ELASTIC_GOODPUT.set(min(1.0, productive / wall))

    while True:
        start_epoch = manager.latest_epoch() + 1
        committed_before = start_epoch - 1
        t_attempt = time.monotonic()
        try:
            result = _invoke_attempt(train_fn, start_epoch, manager,
                                     stall_timeout)
        except KeyboardInterrupt:
            raise
        except Preempted:
            try:
                manager.wait()
            except Exception:  # noqa: BLE001 - exiting anyway; the last
                # committed epoch is what the rescheduled pod resumes from
                _LOG.exception("pending async checkpoint failed during "
                               "preemption exit")
            # productive only if the attempt actually committed progress:
            # an attempt evicted before its first commit is pure replay
            # for the rescheduled pod, and the goodput gauge exists to
            # price exactly that
            try:
                if manager.latest_epoch() > committed_before:
                    productive += time.monotonic() - t_attempt
            except Exception:  # noqa: BLE001 - gauge accounting must not
                # mask the preemption exit
                _LOG.exception("goodput accounting failed during "
                               "preemption exit")
            goodput()
            raise
        except Exception as exc:  # noqa: BLE001 - the point of the harness
            duration = time.monotonic() - t_attempt
            try:
                committed_now = manager.latest_epoch()
            except Exception:  # noqa: BLE001 - a failed async save joined
                # here must not mask the restart decision
                _LOG.exception("joining pending saves after a crash failed")
                committed_now = committed_before
            made_progress = committed_now > committed_before
            if made_progress:
                productive += duration
                attempt = 1  # progress resets the consecutive-failure budget
            else:
                attempt += 1
            reason = "stall" if isinstance(exc, StallError) else "exception"
            telemetry.ELASTIC_RESTARTS.inc(reason=reason)
            _flightrec.record("elastic.restart", reason=reason,
                              attempt=attempt, error=repr(exc))
            goodput()
            if attempt > max_restarts:
                restarts.inc(site="elastic.restart", outcome="exhausted")
                raise
            restarts.inc(site="elastic.restart", outcome="retry")
            delay = min(restart_delay * (restart_backoff ** (attempt - 1)),
                        max_restart_delay) if restart_delay else 0.0
            _LOG.warning("train_fn failed (%s); restart %d/%d from epoch %d "
                         "in %.1fs", exc, attempt, max_restarts,
                         committed_now + 1, delay)
            if delay:
                time.sleep(delay)
        else:
            productive += time.monotonic() - t_attempt
            goodput()
            return result
