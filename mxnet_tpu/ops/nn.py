"""Neural-network ops: FullyConnected, Convolution, Pooling, normalization,
activations, softmax family, Dropout, RNN, sequence ops, loss outputs.

Capability parity with reference `src/operator/nn/` + the legacy loss/output
ops (`src/operator/softmax_output*.cc`, `regression_output*.cc`,
`src/operator/rnn-inl.h`, `sequence_*.cc` — SURVEY.md §2.1). All compute is
jax/lax so the MXU gets large fused matmuls/convs; layout defaults to the
reference's NCHW but NHWC is supported (Convolution/Pooling `layout` attr)
because channels-last tiles better onto TPU.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import _global
from ..base import MXNetError
from .registry import REQUIRED, register

# ---------------------------------------------------------------------------
# FullyConnected (reference src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------


@register(
    "FullyConnected",
    params={"num_hidden": (int, REQUIRED), "no_bias": (bool, False), "flatten": (bool, True)},
    inputs=lambda attrs: ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"],
)
def fully_connected(attrs, data, weight, *rest):
    """out = data @ weight.T + bias; weight is (num_hidden, in_units) like the
    reference so saved .params files transfer."""
    if attrs.flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = jnp.matmul(data, weight.T)
    if rest and rest[0] is not None:
        out = out + rest[0]
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution (reference src/operator/nn/convolution.cc)
# ---------------------------------------------------------------------------


def _conv_dims(kernel_ndim, layout):
    if layout in (None, "", "NCHW", "NCW", "NCDHW"):
        spatial = "DHW"[-kernel_ndim:]
        lhs = "NC" + spatial
        out = lhs
    else:  # NHWC family
        spatial = "DHW"[-kernel_ndim:]
        lhs = "N" + spatial + "C"
        out = lhs
    rhs = "OI" + "DHW"[-kernel_ndim:]
    return (lhs, rhs, out)


@register(
    "Convolution",
    params={
        "kernel": (tuple, REQUIRED),
        "stride": (tuple, None),
        "dilate": (tuple, None),
        "pad": (tuple, None),
        "num_filter": (int, REQUIRED),
        "num_group": (int, 1),
        "workspace": (int, 1024),
        "no_bias": (bool, False),
        "cudnn_tune": (str, None),
        "cudnn_off": (bool, False),
        "layout": (str, None),
    },
    inputs=lambda attrs: ["data", "weight"] if attrs.get("no_bias") else ["data", "weight", "bias"],
)
def convolution(attrs, data, weight, *rest):
    k = attrs.kernel
    nd = len(k)
    stride = attrs.stride or (1,) * nd
    dilate = attrs.dilate or (1,) * nd
    pad = attrs.pad or (0,) * nd
    layout = attrs.layout or ("NCW" if nd == 1 else ("NCHW" if nd == 2 else "NCDHW"))
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _conv_dims(nd, layout))
    out = lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=attrs.num_group,
        preferred_element_type=None,
    )
    if rest and rest[0] is not None:
        bias = rest[0]
        if layout.startswith("NC"):
            out = out + bias.reshape((1, -1) + (1,) * nd)
        else:
            out = out + bias
    return out


@register(
    "Deconvolution",
    params={
        "kernel": (tuple, REQUIRED),
        "stride": (tuple, None),
        "dilate": (tuple, None),
        "pad": (tuple, None),
        "adj": (tuple, None),
        "target_shape": (tuple, None),
        "num_filter": (int, REQUIRED),
        "num_group": (int, 1),
        "workspace": (int, 512),
        "no_bias": (bool, True),
        "cudnn_tune": (str, None),
        "cudnn_off": (bool, False),
        "layout": (str, None),
    },
    inputs=lambda attrs: ["data", "weight"] if attrs.get("no_bias", True) else ["data", "weight", "bias"],
)
def deconvolution(attrs, data, weight, *rest):
    """Transposed convolution (gradient of Convolution w.r.t. its input).

    Weight layout matches the reference: (in_channels, out_channels/group, *k).
    Implemented as an input-dilated forward convolution with a spatially
    flipped, transposed kernel — the standard XLA lowering.
    """
    k = attrs.kernel
    nd = len(k)
    stride = attrs.stride or (1,) * nd
    pad = attrs.pad or (0,) * nd
    dilate = attrs.dilate or (1,) * nd
    adj = attrs.adj or (0,) * nd
    g = attrs.num_group

    # (I, O/g, *k) -> (O, I/g, *k) with spatial flip, respecting groups
    w = weight.reshape((g, weight.shape[0] // g) + tuple(weight.shape[1:]))
    w = jnp.swapaxes(w, 1, 2)  # (g, O/g, I/g, *k)
    w = w.reshape((weight.shape[1] * g, weight.shape[0] // g) + tuple(weight.shape[2:]))
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))

    dn = lax.conv_dimension_numbers(
        data.shape,
        w.shape,
        _conv_dims(nd, attrs.layout or ("NCW" if nd == 1 else ("NCHW" if nd == 2 else "NCDHW"))),
    )
    out = lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * nd,
        padding=[
            (d * (kk - 1) - p, d * (kk - 1) - p + a)
            for kk, p, d, a in zip(k, pad, dilate, adj)
        ],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=g,
    )
    if rest and rest[0] is not None:
        out = out + rest[0].reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling (reference src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------


@register(
    "Pooling",
    params={
        "kernel": (tuple, None),
        "pool_type": (str, "max"),
        "global_pool": (bool, False),
        "cudnn_off": (bool, False),
        "pooling_convention": (str, "valid"),
        "stride": (tuple, None),
        "pad": (tuple, None),
        "p_value": (int, 2),
        "count_include_pad": (bool, True),
        "layout": (str, None),
    },
)
def pooling(attrs, data):
    nd = data.ndim - 2
    layout = attrs.layout or ("NCW" if nd == 1 else ("NCHW" if nd == 2 else "NCDHW"))
    channels_first = layout.startswith("NC")
    if channels_first:
        spatial_axes = tuple(range(2, 2 + nd))
    else:
        spatial_axes = tuple(range(1, 1 + nd))

    if attrs.global_pool:
        if attrs.pool_type == "max":
            return jnp.max(data, axis=spatial_axes, keepdims=True)
        if attrs.pool_type in ("avg", "sum"):
            red = jnp.mean if attrs.pool_type == "avg" else jnp.sum
            return red(data, axis=spatial_axes, keepdims=True)
        raise MXNetError("unsupported global pool_type %r" % attrs.pool_type)

    kernel = attrs.kernel
    stride = attrs.stride or (1,) * nd
    pad = attrs.pad or (0,) * nd

    window = [1] * data.ndim
    strides = [1] * data.ndim
    padding = [(0, 0)] * data.ndim
    for i, ax in enumerate(spatial_axes):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        lo = pad[i]
        hi = pad[i]
        if attrs.pooling_convention == "full":
            # ceil division output: add extra high padding when needed
            size = data.shape[ax] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem != 0:
                hi += stride[i] - rem
        padding[ax] = (lo, hi)

    if attrs.pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if attrs.pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if attrs.pool_type == "sum":
            return summed
        if attrs.count_include_pad:
            denom = 1
            for i in range(nd):
                denom *= kernel[i]
            return summed / denom
        ones = jnp.ones(data.shape, dtype=data.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if attrs.pool_type == "lp":
        p = float(attrs.p_value)
        summed = lax.reduce_window(jnp.abs(data) ** p, 0.0, lax.add, window, strides, padding)
        return summed ** (1.0 / p)
    raise MXNetError("unsupported pool_type %r" % attrs.pool_type)


# ---------------------------------------------------------------------------
# Normalization (reference src/operator/nn/batch_norm.cc, layer_norm.cc,
# instance_norm.cc, lrn.cc, l2_normalization.cc)
# ---------------------------------------------------------------------------


@register(
    "BatchNorm",
    params={
        "eps": (float, 1e-3),
        "momentum": (float, 0.9),
        "fix_gamma": (bool, True),
        "use_global_stats": (bool, False),
        "output_mean_var": (bool, False),
        "axis": (int, 1),
        "cudnn_off": (bool, False),
    },
    inputs=("data", "gamma", "beta", "moving_mean", "moving_var"),
    num_outputs=3,
)
def batch_norm(attrs, data, gamma, beta, moving_mean, moving_var):
    """Returns (out, batch_mean, batch_var). Moving-stat updates are handled
    by the caller (Gluon layer / executor aux-state machinery), keeping this a
    pure function for XLA. Reference semantics: train uses batch stats unless
    use_global_stats; fix_gamma pins gamma to 1."""
    ax = attrs.axis % data.ndim
    red_axes = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    g = jnp.ones_like(gamma) if attrs.fix_gamma else gamma
    use_batch = _global.is_train() and not attrs.use_global_stats
    if use_batch:
        mean = jnp.mean(data, axis=red_axes)
        var = jnp.var(data, axis=red_axes)
    else:
        mean, var = moving_mean, moving_var
    inv = lax.rsqrt(var + attrs.eps)
    out = (data - mean.reshape(bshape)) * (inv * g).reshape(bshape) + beta.reshape(bshape)
    return out, mean, var


@register(
    "LayerNorm",
    params={"axis": (int, -1), "eps": (float, 1e-5), "output_mean_var": (bool, False)},
    inputs=("data", "gamma", "beta"),
    num_outputs=3,
)
def layer_norm(attrs, data, gamma, beta):
    ax = attrs.axis % data.ndim
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    inv = lax.rsqrt(var + attrs.eps)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)


@register(
    "InstanceNorm",
    params={"eps": (float, 1e-3)},
    inputs=("data", "gamma", "beta"),
)
def instance_norm(attrs, data, gamma, beta):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + attrs.eps) * gamma.reshape(bshape) + beta.reshape(bshape)


@register(
    "L2Normalization",
    params={"eps": (float, 1e-10), "mode": (str, "instance")},
)
def l2_normalization(attrs, data):
    if attrs.mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif attrs.mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + attrs.eps)
    return data / norm


@register(
    "LRN",
    params={"alpha": (float, 1e-4), "beta": (float, 0.75), "knorm": (float, 2.0), "nsize": (int, REQUIRED)},
)
def lrn(attrs, data):
    sq = jnp.square(data)
    half = attrs.nsize // 2
    c = data.shape[1]
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    window = jnp.stack([padded[:, i : i + c] for i in range(attrs.nsize)], axis=0).sum(axis=0)
    return data / jnp.power(attrs.knorm + attrs.alpha * window / attrs.nsize, attrs.beta)


# ---------------------------------------------------------------------------
# Activations (reference src/operator/nn/activation.cc, leaky_relu.cc)
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
}


@register("Activation", params={"act_type": (str, REQUIRED)})
def activation(attrs, data):
    try:
        return _ACTS[attrs.act_type](data)
    except KeyError:
        raise MXNetError("unknown act_type %r" % attrs.act_type)


@register(
    "LeakyReLU",
    params={
        "act_type": (str, "leaky"),
        "slope": (float, 0.25),
        "lower_bound": (float, 0.125),
        "upper_bound": (float, 0.334),
    },
    inputs=lambda attrs: ["data", "gamma"] if attrs.get("act_type") == "prelu" else ["data"],
)
def leaky_relu(attrs, data, *rest):
    t = attrs.act_type
    if t == "leaky":
        return jnp.where(data >= 0, data, attrs.slope * data)
    if t == "elu":
        return jnp.where(data >= 0, data, attrs.slope * jnp.expm1(data))
    if t == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * jnp.expm1(data))
    if t == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if t == "prelu":
        gamma = rest[0]
        bshape = (1, -1) + (1,) * (data.ndim - 2) if data.ndim > 1 else (-1,)
        return jnp.where(data >= 0, data, gamma.reshape(bshape) * data)
    if t == "rrelu":
        if _global.is_train():
            key = _global.next_key()
            slope = jax.random.uniform(
                key, data.shape, minval=attrs.lower_bound, maxval=attrs.upper_bound, dtype=data.dtype
            )
        else:
            slope = (attrs.lower_bound + attrs.upper_bound) / 2.0
        return jnp.where(data >= 0, data, slope * data)
    raise MXNetError("unknown LeakyReLU act_type %r" % t)


# ---------------------------------------------------------------------------
# Softmax family (reference src/operator/nn/softmax.cc:70-152)
# ---------------------------------------------------------------------------


def _softmax_impl(attrs, data, log=False, neg=False):
    ax = attrs.axis
    x = -data if neg else data
    if attrs.temperature is not None and attrs.temperature != 1.0:
        x = x / attrs.temperature
    fn = jax.nn.log_softmax if log else jax.nn.softmax
    out = fn(x, axis=ax)
    if attrs.dtype is not None:
        out = out.astype(attrs.dtype)
    return out


_SOFTMAX_PARAMS = {"axis": (int, -1), "temperature": (float, None), "dtype": ("dtype", None)}


@register("softmax", params=dict(_SOFTMAX_PARAMS))
def softmax(attrs, data):
    return _softmax_impl(attrs, data)


@register("softmin", params=dict(_SOFTMAX_PARAMS))
def softmin(attrs, data):
    return _softmax_impl(attrs, data, neg=True)


@register("log_softmax", params=dict(_SOFTMAX_PARAMS))
def log_softmax(attrs, data):
    return _softmax_impl(attrs, data, log=True)


@register("SoftmaxActivation", params={"mode": (str, "instance")})
def softmax_activation(attrs, data):
    if attrs.mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_output_fwd(data, label, attrs_tuple):
    (grad_scale, ignore_label, use_ignore, multi_output, normalization,
     smooth_alpha, out_grad_flag, preserve_shape) = attrs_tuple
    ax = 1 if (multi_output or preserve_shape) else -1
    if multi_output:
        prob = jax.nn.softmax(data, axis=1)
    elif preserve_shape:
        prob = jax.nn.softmax(data, axis=-1)
    else:
        prob = jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)
    return prob


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_output(data, label, attrs_tuple):
    return _softmax_output_fwd(data, label, attrs_tuple)


def _so_fwd(data, label, attrs_tuple):
    prob = _softmax_output_fwd(data, label, attrs_tuple)
    return prob, (prob, label)


def _so_bwd(attrs_tuple, res, g):
    """Reference semantics (`src/operator/softmax_output-inl.h`): the backward
    of SoftmaxOutput ignores incoming gradient and emits
    (prob - smoothed_onehot(label)) * grad_scale, where label smoothing
    replaces onehot with (1-alpha)*onehot + alpha/(k-1)*(1-onehot), plus
    ignore_label masking and normalization."""
    prob, label = res
    (grad_scale, ignore_label, use_ignore, multi_output, normalization,
     smooth_alpha, out_grad_flag, preserve_shape) = attrs_tuple

    def smoothed(onehot, k):
        if smooth_alpha > 0:
            return onehot * (1.0 - smooth_alpha) + (1.0 - onehot) * (smooth_alpha / (k - 1))
        return onehot

    if multi_output:
        nclass = prob.shape[1]
        lab = label.astype(jnp.int32)
        onehot = smoothed(jax.nn.one_hot(lab, nclass, dtype=prob.dtype, axis=1), nclass)
        grad = prob - onehot
        if use_ignore:
            mask = (label != ignore_label).astype(prob.dtype)
            grad = grad * jnp.expand_dims(mask, 1)
    else:
        flat = prob.reshape(prob.shape[0], -1) if not preserve_shape else prob
        lab = label.astype(jnp.int32).reshape(-1) if not preserve_shape else label.astype(jnp.int32)
        if preserve_shape:
            onehot = smoothed(jax.nn.one_hot(lab, prob.shape[-1], dtype=prob.dtype), prob.shape[-1])
            grad = prob - onehot
            if use_ignore:
                mask = (label != ignore_label).astype(prob.dtype)[..., None]
                grad = grad * mask
        else:
            onehot = smoothed(jax.nn.one_hot(lab, flat.shape[-1], dtype=prob.dtype), flat.shape[-1])
            grad = (flat - onehot).reshape(prob.shape)
            if use_ignore:
                mask = (label.reshape(-1) != ignore_label).astype(prob.dtype)
                grad = grad * mask.reshape((-1,) + (1,) * (prob.ndim - 1))
    scale = grad_scale
    if normalization == "batch":
        scale = scale / prob.shape[0]
    elif normalization == "valid" and use_ignore:
        valid = jnp.maximum(jnp.sum((label != ignore_label).astype(prob.dtype)), 1.0)
        scale = scale / valid
    return (grad * scale).astype(prob.dtype), jnp.zeros_like(label)


_softmax_output.defvjp(_so_fwd, _so_bwd)


@register(
    "SoftmaxOutput",
    params={
        "grad_scale": (float, 1.0),
        "ignore_label": (float, -1.0),
        "multi_output": (bool, False),
        "use_ignore": (bool, False),
        "preserve_shape": (bool, False),
        "normalization": (str, "null"),
        "out_grad": (bool, False),
        "smooth_alpha": (float, 0.0),
    },
    inputs=("data", "label"),
    aliases=("Softmax",),
)
def softmax_output(attrs, data, label):
    at = (
        attrs.grad_scale,
        attrs.ignore_label,
        attrs.use_ignore,
        attrs.multi_output,
        attrs.normalization,
        attrs.smooth_alpha,
        attrs.out_grad,
        attrs.preserve_shape,
    )
    return _softmax_output(data, label, at)


@register(
    "softmax_cross_entropy",
    inputs=("data", "label"),
)
def softmax_cross_entropy(attrs, data, label):
    logprob = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logprob, lab[:, None], axis=-1)
    return -jnp.sum(picked)


# regression outputs: forward=identity-ish, backward=(pred-label)*scale
def _make_regression(name, link, grad_fn):
    from functools import partial as _partial

    @_partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _core(data, label, scale):
        return link(data)

    def _fwd(data, label, scale):
        out = link(data)
        return out, (out, label)

    def _bwd(scale, res, g):
        # reference regression_output-inl.h normalizes by outputs-per-sample
        out, label = res
        n = 1
        for d in out.shape[1:]:
            n *= d
        grad = grad_fn(out, label.reshape(out.shape)) * (scale / n)
        return grad, jnp.zeros_like(label)

    _core.defvjp(_fwd, _bwd)

    @register(name, params={"grad_scale": (float, 1.0)}, inputs=("data", "label"))
    def _op(attrs, data, label, _core=_core):
        return _core(data, label, attrs.grad_scale)

    return _op


_make_regression("LinearRegressionOutput", lambda x: x, lambda o, l: (o - l))
_make_regression("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: (o - l))
_make_regression("MAERegressionOutput", lambda x: x, lambda o, l: jnp.sign(o - l))


def _svm_bwd_core(out, label, margin, reg, use_linear):
    """reference svm_output.cc L1_SVM/L2_SVM: for the label class k,
    grad = -[margin > s_k]*reg (L1) or -2*reg*max(0, margin - s_k) (L2);
    for other classes x, grad = [margin > -s_x]*reg (L1) or
    2*reg*max(0, margin + s_x) (L2)."""
    flat = out.reshape(out.shape[0], -1)
    k = label.astype(jnp.int32).reshape(-1)
    onehot = jax.nn.one_hot(k, flat.shape[-1], dtype=flat.dtype)
    if use_linear:
        g_target = -(margin > flat).astype(flat.dtype) * reg
        g_other = (margin > -flat).astype(flat.dtype) * reg
    else:
        g_target = -2.0 * reg * jnp.maximum(0.0, margin - flat)
        g_other = 2.0 * reg * jnp.maximum(0.0, margin + flat)
    grad = onehot * g_target + (1.0 - onehot) * g_other
    return grad.reshape(out.shape)


from functools import partial as _svm_partial


@_svm_partial(jax.custom_vjp, nondiff_argnums=(2,))
def _svm_output_core(data, label, attrs_tuple):
    return data


def _svm_fwd(data, label, attrs_tuple):
    return data, (data, label)


def _svm_bwd(attrs_tuple, res, g):
    out, label = res
    margin, reg, use_linear = attrs_tuple
    return _svm_bwd_core(out, label, margin, reg, use_linear), jnp.zeros_like(label)


_svm_output_core.defvjp(_svm_fwd, _svm_bwd)


@register(
    "SVMOutput",
    params={"margin": (float, 1.0), "regularization_coefficient": (float, 1.0), "use_linear": (bool, False)},
    inputs=("data", "label"),
)
def svm_output(attrs, data, label):
    return _svm_output_core(data, label, (attrs.margin, attrs.regularization_coefficient, attrs.use_linear))


# ---------------------------------------------------------------------------
# Dropout (reference src/operator/nn/dropout.cc)
# ---------------------------------------------------------------------------


@register(
    "Dropout",
    params={"p": (float, 0.5), "mode": (str, "training"), "axes": (tuple, None), "cudnn_off": (bool, False)},
)
def dropout(attrs, data):
    if attrs.p <= 0 or (not _global.is_train() and attrs.mode != "always"):
        return data
    key = _global.next_key()
    shape = data.shape
    if attrs.axes:
        shape = tuple(1 if i in attrs.axes else s for i, s in enumerate(data.shape))
    keep = 1.0 - attrs.p
    mask = jax.random.bernoulli(key, keep, shape)
    return jnp.where(mask, data / keep, jnp.zeros((), dtype=data.dtype))


# ---------------------------------------------------------------------------
# UpSampling / grid ops
# ---------------------------------------------------------------------------


@register(
    "UpSampling",
    params={
        "scale": (int, REQUIRED),
        "num_filter": (int, 0),
        "sample_type": (str, "nearest"),
        "multi_input_mode": (str, "concat"),
        "num_args": (int, 1),
        "workspace": (int, 512),
    },
    inputs=lambda attrs: ["arg%d" % i for i in range(attrs.get("num_args", 1))],
)
def upsampling(attrs, *xs):
    s = attrs.scale
    outs = []
    for x in xs:
        n, c, h, w = x.shape
        out = jax.image.resize(x, (n, c, h * s, w * s), method="nearest" if attrs.sample_type == "nearest" else "bilinear")
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=1) if attrs.multi_input_mode == "concat" else sum(outs[1:], outs[0])


# ---------------------------------------------------------------------------
# Sequence ops (reference src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------


def _seq_len_mask(seq_len, maxlen, batch, dtype):
    steps = jnp.arange(maxlen, dtype=jnp.float32)[:, None]
    return (steps < seq_len.astype(jnp.float32)[None, :]).astype(dtype)


@register(
    "SequenceMask",
    params={"use_sequence_length": (bool, False), "value": (float, 0.0), "axis": (int, 0)},
    inputs=lambda attrs: ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"],
)
def sequence_mask(attrs, data, *rest):
    if not attrs.use_sequence_length:
        return data
    seq_len = rest[0]
    if attrs.axis == 0:
        maxlen, batch = data.shape[0], data.shape[1]
        mask = _seq_len_mask(seq_len, maxlen, batch, data.dtype)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:
        batch, maxlen = data.shape[0], data.shape[1]
        mask = _seq_len_mask(seq_len, maxlen, batch, data.dtype).T
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return data * mask + attrs.value * (1 - mask)


@register(
    "SequenceLast",
    params={"use_sequence_length": (bool, False), "axis": (int, 0)},
    inputs=lambda attrs: ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"],
)
def sequence_last(attrs, data, *rest):
    ax = attrs.axis
    if not attrs.use_sequence_length:
        return jnp.take(data, data.shape[ax] - 1, axis=ax)
    seq_len = rest[0].astype(jnp.int32) - 1
    if ax == 0:
        batch = data.shape[1]
        return data[seq_len, jnp.arange(batch)]
    batch = data.shape[0]
    return data[jnp.arange(batch), seq_len]


@register(
    "SequenceReverse",
    params={"use_sequence_length": (bool, False), "axis": (int, 0)},
    inputs=lambda attrs: ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"],
)
def sequence_reverse(attrs, data, *rest):
    if not attrs.use_sequence_length:
        return jnp.flip(data, axis=0)
    seq_len = rest[0].astype(jnp.int32)
    maxlen = data.shape[0]
    idx = jnp.arange(maxlen)[:, None]
    rev_idx = jnp.where(idx < seq_len[None, :], seq_len[None, :] - 1 - idx, idx)
    return jnp.take_along_axis(data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)).astype(jnp.int32), axis=0) if data.ndim > 2 else jnp.take_along_axis(data, rev_idx, axis=0)


# ---------------------------------------------------------------------------
# Fused RNN (reference src/operator/rnn-inl.h, cudnn_rnn-inl.h) — implemented
# as lax.scan over fused per-step matmuls so XLA pipelines the MXU.
# ---------------------------------------------------------------------------


def _gru_scan(x_seq, h0, wx, wh, bx, bh):
    x_proj = jnp.einsum("tbi,gi->tbg", x_seq, wx) + bx

    def step(h, xp):
        rx, zx, nx = jnp.split(xp, 3, axis=-1)
        hproj = jnp.matmul(h, wh.T) + bh
        rh, zh, nh = jnp.split(hproj, 3, axis=-1)
        r = jax.nn.sigmoid(rx + rh)
        z = jax.nn.sigmoid(zx + zh)
        n = jnp.tanh(nx + r * nh)
        h_new = (1 - z) * n + z * h
        return h_new, h_new

    hT, ys = lax.scan(step, h0, x_proj)
    return ys, hT


def _rnn_layer_scan(mode, x_seq, h0, c0, wx, wh, bx, bh):
    """One direction of one layer. x_seq (T,B,I); returns (ys, hT, cT)."""
    if mode == "gru":
        ys, hT = _gru_scan(x_seq, h0, wx, wh, bx, bh)
        return ys, hT, c0
    x_proj = jnp.einsum("tbi,gi->tbg", x_seq, wx) + bx

    if mode == "lstm":
        def step(carry, xp):
            h, c = carry
            gates = xp + jnp.matmul(h, wh.T) + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        (hT, cT), ys = lax.scan(step, (h0, c0), x_proj)
        return ys, hT, cT

    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(h, xp):
        h_new = act(xp + jnp.matmul(h, wh.T) + bh)
        return h_new, h_new

    hT, ys = lax.scan(step, h0, x_proj)
    return ys, hT, c0


def rnn_forward(mode, data, params_flat, state, state_cell, num_layers, state_size,
                bidirectional=False, p_dropout=0.0, train=False):
    """Fused multi-layer RNN matching reference parameter packing
    (`src/operator/rnn-inl.h` — per layer/direction: W_x then W_h then b_x, b_h).

    data: (T, B, I). state: (L*D, B, H). Returns (out, hT, cT).
    """
    ngates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    D = 2 if bidirectional else 1
    T, B, I = data.shape
    H = state_size
    offset = 0
    x = data
    h_outs = []
    c_outs = []

    def take(n):
        nonlocal offset
        out = lax.dynamic_slice(params_flat, (offset,), (n,))
        offset += n
        return out

    # weights for all layers/directions first, then biases (cuDNN packing)
    weights = []
    for layer in range(num_layers):
        in_size = I if layer == 0 else H * D
        per_dir = []
        for d in range(D):
            wx = take(ngates * H * in_size).reshape(ngates * H, in_size)
            wh = take(ngates * H * H).reshape(ngates * H, H)
            per_dir.append((wx, wh))
        weights.append(per_dir)
    biases = []
    for layer in range(num_layers):
        per_dir = []
        for d in range(D):
            bx = take(ngates * H)
            bh = take(ngates * H)
            per_dir.append((bx, bh))
        biases.append(per_dir)

    for layer in range(num_layers):
        dir_outs = []
        for d in range(D):
            wx, wh = weights[layer][d]
            bx, bh = biases[layer][d]
            h0 = state[layer * D + d]
            c0 = state_cell[layer * D + d] if state_cell is not None else jnp.zeros_like(h0)
            xs = jnp.flip(x, axis=0) if d == 1 else x
            ys, hT, cT = _rnn_layer_scan(mode, xs, h0, c0, wx, wh, bx, bh)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            dir_outs.append(ys)
            h_outs.append(hT)
            c_outs.append(cT)
        x = dir_outs[0] if D == 1 else jnp.concatenate(dir_outs, axis=-1)
        if p_dropout > 0 and train and layer < num_layers - 1:
            key = _global.next_key()
            keep = 1.0 - p_dropout
            mask = jax.random.bernoulli(key, keep, x.shape)
            x = jnp.where(mask, x / keep, jnp.zeros((), dtype=x.dtype))
    hT = jnp.stack(h_outs, axis=0)
    cT = jnp.stack(c_outs, axis=0) if mode == "lstm" else None
    return x, hT, cT


@register(
    "RNN",
    params={
        "state_size": (int, REQUIRED),
        "num_layers": (int, REQUIRED),
        "bidirectional": (bool, False),
        "mode": (str, REQUIRED),
        "p": (float, 0.0),
        "state_outputs": (bool, False),
        "projection_size": (int, None),
        "lstm_state_clip_min": (float, None),
        "lstm_state_clip_max": (float, None),
        "lstm_state_clip_nan": (bool, False),
    },
    inputs=lambda attrs: ["data", "parameters", "state", "state_cell"]
    if attrs.get("mode") == "lstm"
    else ["data", "parameters", "state"],
    num_outputs=lambda attrs: (3 if attrs.get("mode") == "lstm" else 2) if attrs.get("state_outputs") else 1,
)
def rnn(attrs, data, parameters, state, *rest):
    state_cell = rest[0] if rest else None
    out, hT, cT = rnn_forward(
        attrs.mode,
        data,
        parameters,
        state,
        state_cell,
        attrs.num_layers,
        attrs.state_size,
        bidirectional=attrs.bidirectional,
        p_dropout=attrs.p,
        train=_global.is_train(),
    )
    if attrs.mode == "lstm":
        return (out, hT, cT) if attrs.state_outputs else out
    return (out, hT) if attrs.state_outputs else out


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional=False):
    """Total packed parameter count (mirrors reference rnn-inl.h GetParamSize)."""
    ngates = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    D = 2 if bidirectional else 1
    H = state_size
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else H * D
        size += D * (ngates * H * in_size + ngates * H * H)
    size += num_layers * D * 2 * ngates * H
    return size


@register(
    "_rnn_param_concat",
    params={"num_args": (int, 1), "dim": (int, 0)},
    inputs=lambda attrs: ["arg%d" % i for i in range(attrs.get("num_args", 1))],
)
def rnn_param_concat(attrs, *xs):
    return jnp.concatenate([x.reshape(-1) for x in xs], axis=0)


# ---------------------------------------------------------------------------
# CTC loss (reference src/operator/contrib/ctc_loss.cc / warpctc) — log-space
# alpha recursion over lax.scan; one XLA while loop, batched lattice.
# ---------------------------------------------------------------------------

_CTC_NEG = -1e30


def _ctc_logaddexp(a, b):
    m = jnp.maximum(a, b)
    return m + jnp.log1p(jnp.exp(-jnp.abs(a - b)))


def _ctc_forward(logp, lab, pl, ll):
    """logp (B,T,C) log-probs; lab (B,L) labels (blank=0); pl,(B,) input
    lengths; ll (B,) label lengths. Returns per-sample -log p(l|x)."""
    B, T, C = logp.shape
    L = lab.shape[1]
    S = 2 * L + 1

    ext = jnp.zeros((B, S), dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    ext_len = 2 * ll + 1

    alpha0 = jnp.full((B, S), _CTC_NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, 0])
    first_lab = ext[:, 1]
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(ll > 0,
                  jnp.take_along_axis(logp[:, 0, :], first_lab[:, None], axis=1)[:, 0],
                  _CTC_NEG))

    same_as_two_back = jnp.concatenate(
        [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, t):
        stay = alpha
        one = jnp.concatenate([jnp.full((B, 1), _CTC_NEG), alpha[:, :-1]], axis=1)
        two = jnp.concatenate([jnp.full((B, 2), _CTC_NEG), alpha[:, :-2]], axis=1)
        two = jnp.where(same_as_two_back, _CTC_NEG, two)
        merged = _ctc_logaddexp(_ctc_logaddexp(stay, one), two)
        emit = jnp.take_along_axis(logp[:, t, :], ext, axis=1)
        new_alpha = merged + emit
        active = (t < pl)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    alphaT, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    idx_last = jnp.clip(ext_len - 1, 0, S - 1)
    idx_prev = jnp.clip(ext_len - 2, 0, S - 1)
    a_last = jnp.take_along_axis(alphaT, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alphaT, idx_prev[:, None], axis=1)[:, 0]
    return -_ctc_logaddexp(a_last, a_prev)


@register(
    "CTCLoss",
    params={
        "use_data_lengths": (bool, False),
        "use_label_lengths": (bool, False),
        "blank_label": (str, "first"),
    },
    inputs=lambda attrs: ["data", "label"]
    + (["data_lengths"] if attrs.get("use_data_lengths") else [])
    + (["label_lengths"] if attrs.get("use_label_lengths") else []),
    aliases=("_contrib_CTCLoss", "ctc_loss", "_contrib_ctc_loss"),
)
def ctc_loss(attrs, data, label, *rest):
    """data (B,T,C) unnormalized activations; label (B,L). blank_label
    'first' means blank=0 (reference contrib.CTCLoss semantics)."""
    B, T, C = data.shape
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    i = 0
    if attrs.use_data_lengths:
        pl = rest[i].astype(jnp.int32)
        i += 1
    else:
        pl = jnp.full((B,), T, dtype=jnp.int32)
    if attrs.use_label_lengths:
        ll = rest[i].astype(jnp.int32)
    else:
        # padding convention: 0 for blank_label='first', -1 for 'last'
        pad_val = -1 if attrs.blank_label == "last" else 0
        ll = jnp.sum((lab != pad_val).astype(jnp.int32), axis=1)
    if attrs.blank_label == "last":
        # rotate so blank becomes index 0; -1 padding maps onto blank
        logp = jnp.concatenate([logp[..., -1:], logp[..., :-1]], axis=-1)
        lab = jnp.where(lab < 0, -1, lab) + 1
    return _ctc_forward(logp, lab, pl, ll)
