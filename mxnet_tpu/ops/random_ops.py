"""Random sampling ops (reference `src/operator/random/sample_op.cc`,
`multisample_op.cc`, `unique_sample_op.h`).

Keys come from the global/traced RNG stream (see `mxnet_tpu/_global.py`):
eager calls advance a process-global key; inside a jitted executor the key is
an input to the compiled program, mirroring how the reference hands each op a
per-op `kRandom`/`kParallelRandom` Resource (`src/resource.cc`).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import _global
from .registry import REQUIRED, register

_SHAPE_PARAMS = {
    "shape": (tuple, None),
    "dtype": ("dtype", None),
    "ctx": (str, ""),
}


def _shape_dtype(attrs):
    return tuple(attrs.shape or ()), attrs.dtype or jnp.float32


@register("_random_uniform", params={"low": (float, 0.0), "high": (float, 1.0), **_SHAPE_PARAMS}, inputs=())
def random_uniform(attrs):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.uniform(_global.next_key(), shape, dtype=dtype, minval=attrs.low, maxval=attrs.high)


@register("_random_normal", params={"loc": (float, 0.0), "scale": (float, 1.0), **_SHAPE_PARAMS}, inputs=())
def random_normal(attrs):
    shape, dtype = _shape_dtype(attrs)
    return attrs.loc + attrs.scale * jax.random.normal(_global.next_key(), shape, dtype=dtype)


@register("_random_exponential", params={"lam": (float, 1.0), **_SHAPE_PARAMS}, inputs=())
def random_exponential(attrs):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.exponential(_global.next_key(), shape, dtype=dtype) / attrs.lam


@register("_random_gamma", params={"alpha": (float, 1.0), "beta": (float, 1.0), **_SHAPE_PARAMS}, inputs=())
def random_gamma(attrs):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.gamma(_global.next_key(), attrs.alpha, shape, dtype=dtype) * attrs.beta


@register("_random_poisson", params={"lam": (float, 1.0), **_SHAPE_PARAMS}, inputs=())
def random_poisson(attrs):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.poisson(_global.next_key(), attrs.lam, shape).astype(dtype)


@register(
    "_random_negative_binomial",
    params={"k": (int, 1), "p": (float, 1.0), **_SHAPE_PARAMS},
    inputs=(),
)
def random_negative_binomial(attrs):
    shape, dtype = _shape_dtype(attrs)
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    lam = jax.random.gamma(_global.next_key(), attrs.k, shape) * ((1 - attrs.p) / attrs.p)
    return jax.random.poisson(_global.next_key(), lam, shape).astype(dtype)


@register(
    "_random_generalized_negative_binomial",
    params={"mu": (float, 1.0), "alpha": (float, 1.0), **_SHAPE_PARAMS},
    inputs=(),
)
def random_gen_negative_binomial(attrs):
    shape, dtype = _shape_dtype(attrs)
    if attrs.alpha <= 0:
        return jax.random.poisson(_global.next_key(), attrs.mu, shape).astype(dtype)
    k = 1.0 / attrs.alpha
    p = k / (k + attrs.mu)
    lam = jax.random.gamma(_global.next_key(), k, shape) * ((1 - p) / p)
    return jax.random.poisson(_global.next_key(), lam, shape).astype(dtype)


@register("_random_randint", params={"low": (int, 0), "high": (int, REQUIRED), **_SHAPE_PARAMS}, inputs=())
def random_randint(attrs):
    shape, dtype = _shape_dtype(attrs)
    if dtype == jnp.float32:
        dtype = jnp.int32
    return jax.random.randint(_global.next_key(), shape, attrs.low, attrs.high, dtype=dtype)


# tensor-parameter multisample variants (reference multisample_op.cc):
# sample one draw per row of the parameter tensors.


def _multisample(sampler_inputs):
    def deco(name, inputs, fn):
        @register(name, params={"shape": (tuple, None), "dtype": ("dtype", None)}, inputs=inputs)
        def _op(attrs, *params, _fn=fn):
            shape = tuple(attrs.shape or ())
            out_shape = params[0].shape + shape
            return _fn(_global.next_key(), out_shape, attrs.dtype or jnp.float32, *[
                p.reshape(p.shape + (1,) * len(shape)) for p in params
            ])

    return deco


_ms = _multisample(None)
_ms("_sample_uniform", ("low", "high"), lambda k, s, d, lo, hi: lo + (hi - lo) * jax.random.uniform(k, s, dtype=d))
_ms("_sample_normal", ("mu", "sigma"), lambda k, s, d, mu, sg: mu + sg * jax.random.normal(k, s, dtype=d))
_ms("_sample_exponential", ("lam",), lambda k, s, d, lam: jax.random.exponential(k, s, dtype=d) / lam)
_ms("_sample_gamma", ("alpha", "beta"), lambda k, s, d, a, b: jax.random.gamma(k, a, s, dtype=d) * b)
_ms("_sample_poisson", ("lam",), lambda k, s, d, lam: jax.random.poisson(k, lam, s).astype(d))
_ms(
    "_sample_negative_binomial",
    ("k", "p"),
    lambda key, s, d, k, p: jax.random.poisson(
        key, jax.random.gamma(jax.random.fold_in(key, 1), k, s) * ((1 - p) / p), s
    ).astype(d),
)
_ms(
    "_sample_generalized_negative_binomial",
    ("mu", "alpha"),
    lambda key, s, d, mu, alpha: jax.random.poisson(
        key,
        jax.random.gamma(jax.random.fold_in(key, 1), 1.0 / jnp.maximum(alpha, 1e-12), s)
        * (mu * alpha),
        s,
    ).astype(d),
)


@register(
    "_sample_multinomial",
    params={"shape": (tuple, None), "get_prob": (bool, False), "dtype": ("dtype", None)},
    inputs=("data",),
    num_outputs=lambda attrs: 2 if attrs.get("get_prob") else 1,
)
def sample_multinomial(attrs, data):
    """data: (..., k) probabilities; draws `shape` samples per distribution."""
    n = 1
    for s in attrs.shape or (1,):
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    flat = logits.reshape(-1, logits.shape[-1])
    samples = jax.random.categorical(_global.next_key(), flat[:, None, :], axis=-1, shape=(flat.shape[0], n))
    out_shape = data.shape[:-1] + tuple(attrs.shape or ())
    samples = samples.reshape(out_shape if out_shape else (1,)).astype(attrs.dtype or jnp.int32)
    if attrs.get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(flat, axis=-1),
            samples.reshape(flat.shape[0], n).astype(jnp.int32),
            axis=-1,
        ).reshape(samples.shape)
        return samples, lp
    return samples


@register("_shuffle", inputs=("data",))
def shuffle(attrs, data):
    """Shuffle along the first axis (reference _shuffle semantics)."""
    idx = jax.random.permutation(_global.next_key(), data.shape[0])
    return jnp.take(data, idx, axis=0)


@register(
    "_sample_unique_zipfian",
    params={"range_max": (int, REQUIRED), "shape": (tuple, None)},
    inputs=(),
    num_outputs=2,
)
def sample_unique_zipfian(attrs):
    """Approximate log-uniform (zipfian) candidate sampler used by sampled
    softmax (reference unique_sample_op.h). Dedup is approximated by
    rejection-free sampling; counts returned for expected-count correction."""
    shape = tuple(attrs.shape or (1,))
    n = 1
    for s in shape:
        n *= s
    u = jax.random.uniform(_global.next_key(), (n,))
    rng = attrs.range_max
    samples = (jnp.exp(u * jnp.log(rng + 1.0)) - 1.0).astype(jnp.int64)
    samples = jnp.clip(samples, 0, rng - 1)
    counts = jnp.ones((n,), dtype=jnp.int64)
    return samples.reshape(shape), counts.reshape(shape)
