"""Detection operator family (SSD/R-CNN tail).

Reference: ``src/operator/contrib/bounding_box.cc`` (box_nms/box_iou/
bipartite_matching), ``multibox_prior.cc``, ``multibox_target.cc``,
``multibox_detection.cc``, ``roi_align.cc``. The reference implements these
as custom CPU/CUDA kernels with data-dependent loops; here everything is
padded, masked, vectorized XLA — except the NMS suppression loop, which is
a first-party Pallas TPU kernel (``pallas_kernels.nms_keep``). Suppressed/
invalid slots carry -1 exactly like the reference, so downstream consumers
see identical semantics with static shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import REQUIRED, register
from . import pallas_kernels


def _floats(v):
    if isinstance(v, str):
        s = v.strip().lstrip("([").rstrip(")]")
        return tuple(float(x) for x in s.split(",") if x.strip())
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


_FMT = {"corner": 0, "center": 1, 0: 0, 1: 1, "0": 0, "1": 1}


def _to_corner(boxes, fmt):
    if _FMT[fmt] == 0:
        return boxes
    x, y, w, h = (boxes[..., i] for i in range(4))
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _from_corner(boxes, fmt):
    if _FMT[fmt] == 0:
        return boxes
    x1, y1, x2, y2 = (boxes[..., i] for i in range(4))
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1],
                     axis=-1)


def _pair_iou(a, b):
    """IoU matrix between corner boxes a (..., N, 4) and b (..., M, 4)."""
    a = a[..., :, None, :]
    b = b[..., None, :, :]
    iw = jnp.maximum(jnp.minimum(a[..., 2], b[..., 2])
                     - jnp.maximum(a[..., 0], b[..., 0]), 0.0)
    ih = jnp.maximum(jnp.minimum(a[..., 3], b[..., 3])
                     - jnp.maximum(a[..., 1], b[..., 1]), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


# ---------------------------------------------------------------------------
# box_iou
# ---------------------------------------------------------------------------


@register("_contrib_box_iou",
          params={"format": (str, "corner")},
          inputs=("lhs", "rhs"))
def _box_iou(attrs, lhs, rhs):
    """IoU between every pair (reference bounding_box.cc box_iou)."""
    return _pair_iou(_to_corner(lhs, attrs.format),
                     _to_corner(rhs, attrs.format))


# ---------------------------------------------------------------------------
# box_nms
# ---------------------------------------------------------------------------


_NMS_PARAMS = {
    "overlap_thresh": (float, 0.5),
    "valid_thresh": (float, 0.0),
    "topk": (int, -1),
    "coord_start": (int, 2),
    "score_index": (int, 1),
    "id_index": (int, -1),
    "force_suppress": (bool, False),
    "in_format": (str, "corner"),
    "out_format": (str, "corner"),
}


def _nms_one(flat, attrs):
    """NMS over one (N, K) box table; returns (N, K) with suppressed rows
    -1, remaining rows sorted by descending score (reference semantics)."""
    n, k = flat.shape
    cs, si, ii = attrs.coord_start, attrs.score_index, attrs.id_index
    scores = flat[:, si]
    valid = scores > attrs.valid_thresh
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
    rank = jnp.arange(n)
    if attrs.topk > 0:
        in_topk = rank < attrs.topk
    else:
        in_topk = jnp.ones((n,), bool)
    sorted_rows = flat[order]
    boxes = _to_corner(sorted_rows[:, cs:cs + 4], attrs.in_format)
    cls_ids = sorted_rows[:, ii] if ii >= 0 else jnp.full((n,), -1.0)
    valid_sorted = jnp.logical_and(valid[order], in_topk)
    keep = pallas_kernels.nms_keep(
        boxes, cls_ids, valid_sorted, attrs.overlap_thresh,
        attrs.force_suppress or ii < 0)
    out_rows = sorted_rows
    if attrs.out_format != attrs.in_format:
        conv = _from_corner(boxes, attrs.out_format)
        out_rows = out_rows.at[:, cs:cs + 4].set(conv)
    return jnp.where(keep[:, None], out_rows, -jnp.ones_like(out_rows))


@register("_contrib_box_nms", params=_NMS_PARAMS,
          aliases=("_contrib_box_non_maximum_suppression",))
def _box_nms(attrs, data):
    """Non-maximum suppression (reference bounding_box.cc BoxNMSForward →
    Pallas suppression kernel, vmapped over batch). Output keeps the input
    shape; suppressed and invalid entries are -1; survivors are sorted by
    score."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])
    return jax.vmap(lambda f: _nms_one(f, attrs))(flat).reshape(shape)


# ---------------------------------------------------------------------------
# bipartite_matching
# ---------------------------------------------------------------------------


@register("_contrib_bipartite_matching",
          params={"is_ascend": (bool, False), "threshold": (float, REQUIRED),
                  "topk": (int, -1)},
          num_outputs=2)
def _bipartite_matching(attrs, data):
    """Greedy bipartite matching on a score matrix (reference
    bounding_box.cc BipartiteMatchingForward): repeatedly take the globally
    best unmatched pair while it passes ``threshold``. Returns (row_match,
    col_match) with -1 for unmatched."""
    shape = data.shape
    n, m = shape[-2], shape[-1]
    flat = data.reshape((-1, n, m))
    sign = 1.0 if attrs.is_ascend else -1.0
    limit = n if attrs.topk < 0 else min(attrs.topk, n)

    def one(mat):
        def body(_, state):
            mat, row, col = state
            idx = jnp.argmin(sign * mat)
            r, c = idx // m, idx % m
            v = mat[r, c]
            ok = (v <= attrs.threshold) if attrs.is_ascend \
                else (v >= attrs.threshold)
            row = jnp.where(ok, row.at[r].set(c.astype(jnp.float32)), row)
            col = jnp.where(ok, col.at[c].set(r.astype(jnp.float32)), col)
            fill = jnp.inf * sign
            mat = jnp.where(ok, mat.at[r, :].set(fill).at[:, c].set(fill), mat)
            return mat, row, col

        row0 = jnp.full((n,), -1.0)
        col0 = jnp.full((m,), -1.0)
        _, row, col = lax.fori_loop(0, min(limit, m), body, (mat, row0, col0))
        return row, col

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(shape[:-1]), cols.reshape(shape[:-2] + (m,)))


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------


@register("_contrib_MultiBoxPrior",
          params={"sizes": (_floats, (1.0,)), "ratios": (_floats, (1.0,)),
                  "clip": (bool, False), "steps": (_floats, (-1.0, -1.0)),
                  "offsets": (_floats, (0.5, 0.5))},
          aliases=("MultiBoxPrior",))
def _multibox_prior(attrs, data):
    """Anchor boxes per feature-map location (reference
    multibox_prior.cc:40-78, fully vectorized). Output (1, H*W*A, 4)."""
    h, w = data.shape[2], data.shape[3]
    sizes, ratios = attrs.sizes, attrs.ratios
    step_y = attrs.steps[0] if attrs.steps[0] > 0 else 1.0 / h
    step_x = attrs.steps[1] if attrs.steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + attrs.offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + attrs.offsets[1]) * step_x
    # per-location half-extents, order: sizes first (ratio 1), then
    # ratios[1:] at sizes[0] — reference multibox_prior.cc:46-69
    half = []
    for s in sizes:
        half.append((s * h / w / 2.0, s / 2.0))
    for r in ratios[1:]:
        sr = float(np.sqrt(r))
        half.append((sizes[0] * h / w * sr / 2.0, sizes[0] / sr / 2.0))
    hw = jnp.asarray([p[0] for p in half], jnp.float32)  # (A,)
    hh = jnp.asarray([p[1] for p in half], jnp.float32)
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")      # (H, W)
    cyg = cyg[:, :, None]
    cxg = cxg[:, :, None]
    out = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh], axis=-1)
    out = out.reshape(1, h * w * len(half), 4)
    if attrs.clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------


def _encode_loc(anchors, gt, variances):
    """SSD box encoding (reference multibox_target.cc TargetEncoding)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) / 2
    ay = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-12)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
    gx = (gt[:, 0] + gt[:, 2]) / 2
    gy = (gt[:, 1] + gt[:, 3]) / 2
    v0, v1, v2, v3 = variances
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, 1e-12) / v0,
        (gy - ay) / jnp.maximum(ah, 1e-12) / v1,
        jnp.log(gw / jnp.maximum(aw, 1e-12)) / v2,
        jnp.log(gh / jnp.maximum(ah, 1e-12)) / v3,
    ], axis=-1)


@register("_contrib_MultiBoxTarget",
          params={"overlap_threshold": (float, 0.5),
                  "ignore_label": (float, -1.0),
                  "negative_mining_ratio": (float, -1.0),
                  "negative_mining_thresh": (float, 0.5),
                  "minimum_negative_samples": (int, 0),
                  "variances": (_floats, (0.1, 0.1, 0.2, 0.2))},
          inputs=("anchor", "label", "cls_pred"), num_outputs=3)
def _multibox_target(attrs, anchor, label, cls_pred):
    """Training targets for SSD (reference multibox_target.cc): greedy
    bipartite anchor-GT matching + per-anchor threshold matching, encoded
    location targets, and optional hard-negative mining ranked by the
    anchors' max non-background class probability.
    Outputs: loc_target (B, N*4), loc_mask (B, N*4), cls_target (B, N)."""
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    m = label.shape[1]

    def one(lab, pred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = jnp.where(gt_valid[None, :],
                        _pair_iou(anchors, gt_boxes), 0.0)  # (N, M)

        # greedy bipartite: best anchor for each gt, globally ordered
        def body(_, state):
            mat, match = state
            idx = jnp.argmax(mat)
            a, g = idx // m, idx % m
            ok = mat[a, g] > 1e-12
            match = jnp.where(ok, match.at[a].set(g), match)
            mat = jnp.where(ok, mat.at[a, :].set(-1.0).at[:, g].set(-1.0),
                            mat)
            return mat, match

        match0 = jnp.full((n,), -1, jnp.int32)
        _, match = lax.fori_loop(0, m, body, (iou, match0))

        # threshold matching for the rest
        best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
        best_iou = jnp.max(iou, axis=1)
        thresh_ok = jnp.logical_and(match < 0,
                                    best_iou >= attrs.overlap_threshold)
        match = jnp.where(thresh_ok, best_gt, match)
        matched = match >= 0
        safe = jnp.maximum(match, 0)

        loc_t = _encode_loc(anchors, gt_boxes[safe], attrs.variances)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0).reshape(-1)
        loc_m = jnp.broadcast_to(matched[:, None], (n, 4)) \
            .astype(jnp.float32).reshape(-1)

        cls_t = jnp.where(matched, lab[safe, 0] + 1.0, 0.0)
        if attrs.negative_mining_ratio > 0:
            num_pos = jnp.sum(matched)
            max_neg = jnp.maximum(
                (attrs.negative_mining_ratio * num_pos).astype(jnp.int32),
                attrs.minimum_negative_samples)
            neg_cand = jnp.logical_and(
                ~matched, best_iou < attrs.negative_mining_thresh)
            # rank negatives by max non-background confidence (hardest first)
            conf = jnp.max(pred[1:, :], axis=0) if pred.shape[0] > 1 \
                else pred[0]
            score = jnp.where(neg_cand, conf, -jnp.inf)
            order = jnp.argsort(-score)
            neg_rank = jnp.zeros((n,), jnp.int32).at[order].set(
                jnp.arange(n, dtype=jnp.int32))
            keep_neg = jnp.logical_and(neg_cand, neg_rank < max_neg)
            cls_t = jnp.where(jnp.logical_or(matched, keep_neg),
                              cls_t, attrs.ignore_label)
        return loc_t, loc_m, cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------


@register("_contrib_MultiBoxDetection",
          params={"clip": (bool, True), "threshold": (float, 0.01),
                  "background_id": (int, 0), "nms_threshold": (float, 0.5),
                  "force_suppress": (bool, False),
                  "variances": (_floats, (0.1, 0.1, 0.2, 0.2)),
                  "nms_topk": (int, -1)},
          inputs=("cls_prob", "loc_pred", "anchor"))
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """Decode + per-class NMS (reference multibox_detection.cc).
    Output (B, N, 6): [class_id, score, xmin, ymin, xmax, ymax]; invalid
    entries -1. class_id skips the background class."""
    # Detections are non-differentiable (argmax/NMS); cut tangents here so a
    # whole-graph vjp (training symbol with a monitoring detection head)
    # never tries to linearize the Pallas NMS kernel.
    cls_prob = jax.lax.stop_gradient(cls_prob)
    loc_pred = jax.lax.stop_gradient(loc_pred)
    anchor = jax.lax.stop_gradient(anchor)
    b, _, n = cls_prob.shape
    anchors = anchor.reshape(-1, 4)
    if anchors.shape[0] != n or loc_pred.shape[-1] != n * 4:
        from ..base import MXNetError

        raise MXNetError(
            "MultiBoxDetection: cls_prob has %d anchors but anchor/loc_pred "
            "carry %d/%d" % (n, anchors.shape[0], loc_pred.shape[-1] // 4))
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) / 2
    ay = (anchors[:, 1] + anchors[:, 3]) / 2
    v0, v1, v2, v3 = attrs.variances

    def one(probs, locs):
        p = locs.reshape(n, 4)
        ox = p[:, 0] * v0 * aw + ax
        oy = p[:, 1] * v1 * ah + ay
        hw = jnp.exp(p[:, 2] * v2) * aw / 2
        hh = jnp.exp(p[:, 3] * v3) * ah / 2
        boxes = jnp.stack([ox - hw, oy - hh, ox + hw, oy + hh], axis=-1)
        if attrs.clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        bg = attrs.background_id
        masked = probs.at[bg, :].set(-1.0)
        best = jnp.argmax(masked, axis=0)
        score = jnp.max(masked, axis=0)
        cls_id = jnp.where(best > bg, best - 1, best).astype(jnp.float32)
        valid = score > attrs.threshold
        cls_id = jnp.where(valid, cls_id, -1.0)
        score = jnp.where(valid, score, -1.0)
        table = jnp.concatenate(
            [cls_id[:, None], score[:, None], boxes], axis=-1)
        return _nms_one(table, nms_attrs)

    from .registry import AttrDict

    nms_attrs = AttrDict(
        overlap_thresh=attrs.nms_threshold, valid_thresh=0.0,
        topk=attrs.nms_topk, coord_start=2, score_index=1, id_index=0,
        force_suppress=attrs.force_suppress, in_format="corner",
        out_format="corner")
    return jax.vmap(one)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROIAlign
# ---------------------------------------------------------------------------


@register("_contrib_ROIAlign",
          params={"pooled_size": (tuple, REQUIRED),
                  "spatial_scale": (float, REQUIRED),
                  "sample_ratio": (int, -1)},
          inputs=("data", "rois"), aliases=("ROIAlign",))
def _roi_align(attrs, data, rois):
    """RoI Align with bilinear sampling (reference roi_align.cc, Mask R-CNN
    semantics: no coordinate rounding). rois (R, 5) = [batch_idx, x1, y1,
    x2, y2]; output (R, C, PH, PW). Differentiable through XLA gather —
    the reference needs a hand-written backward kernel.

    Deviation: with sample_ratio<=0 the reference adapts the tap grid per
    RoI (ceil(roi_size/pooled_size)); XLA needs static shapes, so a fixed
    2x2 grid per bin is used instead. Large RoIs pool slightly differently
    than the reference — pass an explicit sample_ratio for exact-grid
    parity when porting fine-tuned weights."""
    ph, pw = attrs.pooled_size
    sr = attrs.sample_ratio if attrs.sample_ratio > 0 else 2
    b, c, h, w = data.shape

    def one(roi):
        bi = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = (roi[i] * attrs.spatial_scale for i in range(1, 5))
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: (PH*sr, PW*sr) bilinear taps, averaged per bin
        gy = y1 + (jnp.arange(ph * sr, dtype=jnp.float32) + 0.5) * (bin_h / sr)
        gx = x1 + (jnp.arange(pw * sr, dtype=jnp.float32) + 0.5) * (bin_w / sr)

        def bilinear(img, ys, xs):
            y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            ly = jnp.clip(ys - y0, 0.0, 1.0)[:, None]
            lx = jnp.clip(xs - x0, 0.0, 1.0)[None, :]
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            top = v00 * (1 - lx) + v01 * lx
            bot = v10 * (1 - lx) + v11 * lx
            return top * (1 - ly) + bot * ly  # (C, PH*sr, PW*sr)

        samp = bilinear(data[bi], gy, gx)
        samp = samp.reshape(c, ph, sr, pw, sr)
        return samp.mean(axis=(2, 4))

    return jax.vmap(one)(rois)
