"""Sparse operator family — storage-type dispatch (FComputeEx analogue).

Reference: the sparse compute kernels under ``src/operator/tensor/`` —
``dot-inl.h`` (DotCsrDnsDnsImpl / DotCsrTransDnsImpl),
``cast_storage-inl.h``, ``sparse_retain-inl.h``, ``square_sum-inl.h`` — and
``_contrib_SparseEmbedding`` (indexing_op.h). XLA has no sparse storage
(SURVEY §7.3), so the TPU-idiomatic lowering is index arithmetic +
``segment_sum`` over the nnz vector: static shapes (nnz is fixed per
concrete input), MXU-friendly broadcasting, and no host loops.

Dispatch: :func:`mxnet_tpu.ndarray.ndarray.invoke` routes a call here when
any input is a :class:`BaseSparseNDArray` (or the op sets
``dispatch_ex_always``, e.g. ``cast_storage`` whose *output* storage is the
sparse one). Sparse inputs arrive as :class:`SparseRep` views; dense inputs
as jax arrays. Gradients: ex kernels marked ``differentiable`` are
jax.vjp'd w.r.t. their **dense** inputs only — the sparse argument gets
``grad_req=null`` exactly as the reference's sparse dot does.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import REQUIRED, register, register_ex

__all__ = ["SparseRep", "csr_row_ids"]


class SparseRep(NamedTuple):
    """Functional view of a sparse NDArray's components (jax arrays)."""

    stype: str                 # "csr" | "row_sparse"
    data: Any                  # csr: (nnz,) values; rsp: (nnz_rows, *row)
    indices: Any               # csr: (nnz,) col ids; rsp: (nnz_rows,) row ids
    indptr: Optional[Any]      # csr only: (rows+1,) offsets
    shape: Tuple[int, ...]     # logical dense shape


def csr_row_ids(rep: SparseRep):
    """Expand csr indptr to one row id per nnz element.

    ``searchsorted`` over the static-length indptr keeps the whole op inside
    XLA (vs the reference's per-row OMP loop, dot-inl.h DotCsrDnsDnsByRow).
    """
    nnz = rep.data.shape[0]
    return jnp.searchsorted(rep.indptr[1:], jnp.arange(nnz), side="right")


def _seg_sum(vals, ids, num):
    return jax.ops.segment_sum(vals, ids.astype(jnp.int32), num_segments=num)


# ---------------------------------------------------------------------------
# dot(csr, dense) / dot(csr.T, dense)  — reference dot-inl.h
# ---------------------------------------------------------------------------


@register_ex("dot", differentiable=True)
def _dot_ex(attrs, lhs, rhs):
    """Sparse matrix × dense matrix.

    Supported storage combinations (the ones the reference's sparse-FM and
    embedding workloads use): lhs=csr rhs=dense, with either transpose_a.
    Each nnz element (r, c, v) contributes v·rhs[c] to out[r] (plain) or
    v·rhs[r] to out[c] (transposed) — one gather + one segment_sum.
    """
    if not isinstance(lhs, SparseRep) or isinstance(rhs, SparseRep):
        raise MXNetError(
            "sparse dot supports dot(csr, dense); got lhs=%s rhs=%s"
            % (getattr(lhs, "stype", "default"), getattr(rhs, "stype", "default")))
    if lhs.stype != "csr":
        raise MXNetError("sparse dot lhs must be csr, got %s" % lhs.stype)
    if attrs.transpose_b and rhs.ndim > 1:
        # (vector rhs: transpose is a no-op, numpy-style)
        rhs = jnp.swapaxes(rhs, 0, 1)
    rows = csr_row_ids(lhs)
    cols = lhs.indices.astype(jnp.int32)
    vec = rhs.ndim == 1
    v = lhs.data if vec else lhs.data[:, None]
    if attrs.transpose_a:
        gathered = jnp.take(rhs, rows, axis=0)
        out = _seg_sum(v * gathered, cols, lhs.shape[1])
    else:
        gathered = jnp.take(rhs, cols, axis=0)
        out = _seg_sum(v * gathered, rows, lhs.shape[0])
    return out


# ---------------------------------------------------------------------------
# cast_storage — reference cast_storage-inl.h
# ---------------------------------------------------------------------------


@register("cast_storage", params={"stype": (str, REQUIRED)},
          inputs=("data",))
def _cast_storage_dense(attrs, x):
    # dense→dense identity; sparse targets go through the ex kernel
    if attrs.stype != "default":
        raise MXNetError("cast_storage to %r dispatches FComputeEx"
                         % attrs.stype)
    return x


@register_ex("cast_storage", always=True)
def _cast_storage_ex(attrs, x):
    stype = attrs.stype
    if isinstance(x, SparseRep):
        if stype == x.stype:
            return x
        x = _densify(x)          # sparse→sparse goes through dense
    if stype == "default":
        return x
    # dense→sparse has a data-dependent nnz: eager-only, computed on host
    # (the reference's CastStorageDnsRspImpl is likewise a non-jittable
    # kernel — it allocates by counted nnz)
    a = np.asarray(x)
    if stype == "row_sparse":
        nz = np.where(np.any(a.reshape(a.shape[0], -1) != 0, axis=1))[0]
        return SparseRep("row_sparse", jnp.asarray(a[nz]),
                         jnp.asarray(nz.astype(np.int64)), None, a.shape)
    if stype == "csr":
        if a.ndim != 2:
            raise MXNetError("cast_storage to csr requires 2-D input")
        r, c = np.nonzero(a)
        indptr = np.zeros(a.shape[0] + 1, np.int64)
        np.add.at(indptr, r + 1, 1)
        indptr = np.cumsum(indptr)
        return SparseRep("csr", jnp.asarray(a[r, c]),
                         jnp.asarray(c.astype(np.int64)),
                         jnp.asarray(indptr), a.shape)
    raise MXNetError("cast_storage: unknown stype %r" % stype)


def _densify(rep: SparseRep):
    if rep.stype == "row_sparse":
        return (jnp.zeros(rep.shape, rep.data.dtype)
                .at[rep.indices.astype(jnp.int32)].set(rep.data))
    rows = csr_row_ids(rep)
    return (jnp.zeros(rep.shape, rep.data.dtype)
            .at[rows, rep.indices.astype(jnp.int32)].set(rep.data))


# ---------------------------------------------------------------------------
# _sparse_retain — reference sparse_retain-inl.h
# ---------------------------------------------------------------------------


@register("_sparse_retain", inputs=("data", "indices"))
def _sparse_retain_dense(attrs, data, indices):
    raise MXNetError("_sparse_retain requires a row_sparse input")


@register_ex("_sparse_retain")
def _sparse_retain_ex(attrs, data, indices):
    """Keep only the requested rows of a row_sparse array. Rows asked for
    but absent from ``data`` come back zero (reference SparseRetainOpForwardRspImpl).
    """
    if not isinstance(data, SparseRep) or data.stype != "row_sparse":
        raise MXNetError("_sparse_retain data must be row_sparse")
    ids = (indices.data if isinstance(indices, SparseRep) else indices)
    ids = jnp.sort(ids.astype(jnp.int64))
    # binary-search each requested id among the stored rows; miss → zero row
    pos = jnp.searchsorted(data.indices.astype(jnp.int64), ids)
    pos = jnp.clip(pos, 0, data.indices.shape[0] - 1)
    hit = jnp.take(data.indices.astype(jnp.int64), pos) == ids
    vals = jnp.take(data.data, pos.astype(jnp.int32), axis=0)
    mask = hit.reshape((-1,) + (1,) * (vals.ndim - 1))
    return SparseRep("row_sparse", jnp.where(mask, vals, 0), ids, None,
                     data.shape)


# ---------------------------------------------------------------------------
# _square_sum (rsp path) — reference square_sum-inl.h
# ---------------------------------------------------------------------------


@register_ex("_square_sum")
def _square_sum_ex(attrs, x):
    """sum(x^2) over a row_sparse input without densifying. axis=1 with
    keepdims returns a row_sparse result sharing the input's row indices —
    the layout the reference's lazy AdaGrad consumes."""
    if not isinstance(x, SparseRep) or x.stype != "row_sparse":
        raise MXNetError("_square_sum ex kernel expects row_sparse input")
    axes = attrs.axis
    if isinstance(axes, tuple) and len(axes) == 1:
        axes = axes[0]
    sq = jnp.square(x.data)
    if axes is None or axes == ():
        return jnp.sum(sq)  # full reduction
    if axes == 1 and x.data.ndim == 2:
        vals = jnp.sum(sq, axis=1, keepdims=attrs.keepdims)
        if attrs.keepdims:
            return SparseRep("row_sparse", vals, x.indices, None,
                             (x.shape[0], 1))
        return _seg_sum(vals, x.indices, x.shape[0])
    if axes == 0:
        # absent rows are zero, so summing the stored rows IS the column sum
        return jnp.sum(sq, axis=0, keepdims=attrs.keepdims)
    raise MXNetError(
        "_square_sum on row_sparse supports axis=None/0/1 with 2-D values; "
        "got axis=%r for values of rank %d (cast_storage to default for "
        "general reductions)" % (attrs.axis, x.data.ndim))


# ---------------------------------------------------------------------------
# _contrib_SparseEmbedding — reference indexing_op.h SparseEmbedding
# ---------------------------------------------------------------------------


@register("_contrib_SparseEmbedding",
          params={"input_dim": (int, REQUIRED),
                  "output_dim": (int, REQUIRED),
                  "dtype": ("dtype", None)},
          inputs=("data", "weight"))
def _sparse_embedding(attrs, data, weight):
    """Embedding lookup whose weight gradient is row-sparse by construction
    (only looked-up rows receive non-zero grad — the optimizer's lazy
    row_sparse update path skips the rest; reference _contrib_SparseEmbedding
    + sparse sgd/adagrad kernels)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


# ---------------------------------------------------------------------------
# sparse elemwise add/sub — reference elemwise_binary_op_basic.cc FComputeEx
# (rsp+rsp stays rsp: the gradient-accumulation path for sparse grads)
# ---------------------------------------------------------------------------


def _rsp_union_addsub(lhs: SparseRep, rhs: SparseRep, sign: float):
    """Union-of-rows add/sub on two row_sparse inputs (eager: the output
    nnz is data-dependent, like the reference's FComputeEx kernels)."""
    li = np.asarray(lhs.indices).astype(np.int64)
    ri = np.asarray(rhs.indices).astype(np.int64)
    if ri.size == 0:
        return lhs
    if li.size == 0:
        rv = rhs.data if sign > 0 else -rhs.data
        return SparseRep("row_sparse", rv, rhs.indices, None, rhs.shape)
    union = np.union1d(li, ri)
    lpos = np.minimum(np.searchsorted(li, union), li.size - 1)
    rpos = np.minimum(np.searchsorted(ri, union), ri.size - 1)
    lhit = li[lpos] == union
    rhit = ri[rpos] == union
    lv = jnp.take(lhs.data, jnp.asarray(lpos), axis=0) \
        * jnp.asarray(lhit, lhs.data.dtype).reshape(
            (-1,) + (1,) * (lhs.data.ndim - 1))
    rv = jnp.take(rhs.data, jnp.asarray(rpos), axis=0) \
        * jnp.asarray(rhit, rhs.data.dtype).reshape(
            (-1,) + (1,) * (rhs.data.ndim - 1))
    out = lv + rv if sign > 0 else lv - rv   # keeps integer dtypes intact
    return SparseRep("row_sparse", out, jnp.asarray(union), None, lhs.shape)


def _binary_ex(sign):
    def ex(attrs, lhs, rhs):
        if isinstance(lhs, SparseRep) and isinstance(rhs, SparseRep) \
                and lhs.stype == rhs.stype == "row_sparse":
            return _rsp_union_addsub(lhs, rhs, sign)
        l = _densify(lhs) if isinstance(lhs, SparseRep) else lhs
        r = _densify(rhs) if isinstance(rhs, SparseRep) else rhs
        return l + r if sign > 0 else l - r

    return ex


register_ex("elemwise_add", grad_fallback=True)(_binary_ex(1.0))
register_ex("elemwise_sub", grad_fallback=True)(_binary_ex(-1.0))
