"""Vision / legacy operator tail.

Reference: ``src/operator/spatial_transformer.cc``, ``grid_generator.cc``,
``bilinear_sampler.cc``, ``correlation.cc``, ``roi_pooling.cc``,
``crop.cc``, ``src/operator/contrib/{fft,ifft,adaptive_avg_pooling,
bilinear_resize,proposal}``. All implemented as vectorized XLA (gathers,
einsum pooling matrices, static displacement loops) — differentiable where
the reference registers a backward; NMS inside Proposal rides the Pallas
suppression kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from .registry import REQUIRED, register
from . import pallas_kernels


def _floats(v):
    if isinstance(v, str):
        s = v.strip().lstrip("([").rstrip(")]")
        return tuple(float(x) for x in s.split(",") if x.strip())
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


# ---------------------------------------------------------------------------
# bilinear sampling family (SpatialTransformer / GridGenerator /
# BilinearSampler — the STN trio, reference spatial_transformer-inl.h)
# ---------------------------------------------------------------------------


def _bilinear_sample_2d(img, xs, ys):
    """Sample img (C, H, W) at float pixel coords xs/ys (...,) with zero
    padding outside (reference BilinearSamplerForward)."""
    c, h, w = img.shape
    x0 = jnp.floor(xs)
    y0 = jnp.floor(ys)
    lx = xs - x0
    ly = ys - y0

    def tap(yi, xi):
        inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = img[:, yc, xc]  # (C, ...)
        return jnp.where(inside, v, 0.0)

    v00 = tap(y0, x0)
    v01 = tap(y0, x0 + 1)
    v10 = tap(y0 + 1, x0)
    v11 = tap(y0 + 1, x0 + 1)
    top = v00 * (1 - lx) + v01 * lx
    bot = v10 * (1 - lx) + v11 * lx
    return top * (1 - ly) + bot * ly


def _affine_grid(theta, h, w):
    """(6,) affine params -> (2, H, W) normalized target coords
    (reference grid_generator.cc affine branch)."""
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(xg)
    src = jnp.stack([xg, yg, ones], axis=0).reshape(3, -1)  # (3, H*W)
    out = theta.reshape(2, 3) @ src                         # (2, H*W)
    return out.reshape(2, h, w)


@register("GridGenerator",
          params={"transform_type": (str, REQUIRED),
                  "target_shape": (tuple, (0, 0))})
def _grid_generator(attrs, data):
    """Generate sampling grids (reference grid_generator.cc): 'affine'
    takes (B, 6) params; 'warp' takes (B, 2, H, W) flow added to the
    identity grid, normalized to [-1, 1]."""
    if attrs.transform_type == "affine":
        h, w = attrs.target_shape
        return jax.vmap(lambda t: _affine_grid(t, h, w))(data)
    if attrs.transform_type == "warp":
        b, _, h, w = data.shape
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        yg, xg = jnp.meshgrid(ys, xs, indexing="ij")
        x_new = (data[:, 0] + xg) * (2.0 / max(w - 1, 1)) - 1.0
        y_new = (data[:, 1] + yg) * (2.0 / max(h - 1, 1)) - 1.0
        return jnp.stack([x_new, y_new], axis=1)
    raise ValueError("unknown transform_type %r" % attrs.transform_type)


@register("BilinearSampler", inputs=("data", "grid"))
def _bilinear_sampler(attrs, data, grid):
    """Sample data (B,C,H,W) at grid (B,2,Ho,Wo) in [-1,1] coords
    (reference bilinear_sampler.cc; zero padding outside)."""
    _, _, h, w = data.shape

    def one(img, g):
        xs = (g[0] + 1.0) * (w - 1) / 2.0
        ys = (g[1] + 1.0) * (h - 1) / 2.0
        return _bilinear_sample_2d(img, xs, ys)

    return jax.vmap(one)(data, grid)


@register("SpatialTransformer",
          params={"target_shape": (tuple, (0, 0)),
                  "transform_type": (str, REQUIRED),
                  "sampler_type": (str, REQUIRED)},
          inputs=("data", "loc"))
def _spatial_transformer(attrs, data, loc):
    """STN: affine grid from loc + bilinear sampling (reference
    spatial_transformer.cc; only affine/bilinear exist there too)."""
    h, w = attrs.target_shape
    _, _, ih, iw = data.shape

    def one(img, theta):
        g = _affine_grid(theta, h, w)
        xs = (g[0] + 1.0) * (iw - 1) / 2.0
        ys = (g[1] + 1.0) * (ih - 1) / 2.0
        return _bilinear_sample_2d(img, xs, ys)

    return jax.vmap(one)(data, loc)


# ---------------------------------------------------------------------------
# Correlation (FlowNet, reference correlation.cc)
# ---------------------------------------------------------------------------


@register("Correlation",
          params={"kernel_size": (int, 1), "max_displacement": (int, 1),
                  "stride1": (int, 1), "stride2": (int, 1),
                  "pad_size": (int, 0), "is_multiply": (bool, True)},
          inputs=("data1", "data2"))
def _correlation(attrs, data1, data2):
    """Correlation volume between two feature maps: for each displacement
    in a (2d/s2+1)^2 grid, the kernel-window mean of the per-channel
    product (or absolute difference). Static loop over displacements,
    vectorized spatial math (reference correlation.cc CorrelationForward)."""
    b, c, h, w = data1.shape
    k, md = attrs.kernel_size, attrs.max_displacement
    s1, s2, pad = attrs.stride1, attrs.stride2, attrs.pad_size
    d = 2 * md // s2 + 1
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    ph, pw = h + 2 * pad, w + 2 * pad
    kr = k // 2
    out_h = (ph - 2 * (kr + md) + s1 - 1) // s1
    out_w = (pw - 2 * (kr + md) + s1 - 1) // s1
    base = kr + md  # first window center
    ys = base + s1 * jnp.arange(out_h)
    xs = base + s1 * jnp.arange(out_w)
    norm = float(k * k * c)
    planes = []
    for dy in range(-md, md + 1, s2):
        for dx in range(-md, md + 1, s2):
            if attrs.is_multiply:
                prod = p1 * jnp.roll(p2, (-dy, -dx), axis=(2, 3))
            else:
                prod = jnp.abs(p1 - jnp.roll(p2, (-dy, -dx), axis=(2, 3)))
            # kernel-window sum via cumulative window reduce
            win = lax.reduce_window(
                prod, 0.0, lax.add, (1, 1, k, k), (1, 1, 1, 1), "SAME")
            plane = win.sum(axis=1) / norm      # (B, PH, PW)
            planes.append(plane[:, ys][:, :, xs])
    return jnp.stack(planes, axis=1)  # (B, D*D, out_h, out_w)


# ---------------------------------------------------------------------------
# ROIPooling / Crop
# ---------------------------------------------------------------------------


@register("ROIPooling",
          params={"pooled_size": (tuple, REQUIRED),
                  "spatial_scale": (float, REQUIRED)},
          inputs=("data", "rois"))
def _roi_pooling(attrs, data, rois):
    """Max-pool RoIs into a fixed grid with rounded bin edges (reference
    roi_pooling.cc — the Fast R-CNN op; ROIAlign is the non-rounded
    variant)."""
    ph, pw = attrs.pooled_size
    b, c, h, w = data.shape
    ycoord = jnp.arange(h)
    xcoord = jnp.arange(w)

    def one(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * attrs.spatial_scale)
        y1 = jnp.round(roi[2] * attrs.spatial_scale)
        x2 = jnp.round(roi[3] * attrs.spatial_scale)
        y2 = jnp.round(roi[4] * attrs.spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = data[bi]

        def bin_val(py, px):
            hs = jnp.floor(py * bin_h) + y1
            he = jnp.ceil((py + 1) * bin_h) + y1
            ws = jnp.floor(px * bin_w) + x1
            we = jnp.ceil((px + 1) * bin_w) + x1
            mask = ((ycoord >= hs) & (ycoord < he))[:, None] & \
                   ((xcoord >= ws) & (xcoord < we))[None, :]
            sel = jnp.where(mask[None], img, -jnp.inf)
            v = sel.max(axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)

        grid = [[bin_val(py, px) for px in range(pw)] for py in range(ph)]
        return jnp.stack([jnp.stack(r, axis=-1) for r in grid], axis=-2)

    return jax.vmap(one)(rois)


@register("Crop",
          params={"offset": (tuple, (0, 0)), "h_w": (tuple, (0, 0)),
                  "num_args": (int, REQUIRED), "center_crop": (bool, False)},
          inputs=lambda a: ["data", "crop_like"][:a["num_args"]])
def _crop(attrs, data, *rest):
    """Crop H/W to h_w (or to crop_like's shape), at offset or centered
    (reference crop.cc)."""
    if rest:
        th, tw = rest[0].shape[2], rest[0].shape[3]
    else:
        th, tw = attrs.h_w
    h, w = data.shape[2], data.shape[3]
    if attrs.center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = attrs.offset
    return data[:, :, oy:oy + th, ox:ox + tw]


# ---------------------------------------------------------------------------
# adaptive pooling / bilinear resize (reference contrib)
# ---------------------------------------------------------------------------


def _adaptive_matrix(in_size, out_size):
    """(out, in) averaging matrix with floor/ceil bin edges (reference
    adaptive_avg_pooling.cc bin convention)."""
    m = np.zeros((out_size, in_size), np.float32)
    for i in range(out_size):
        start = int(np.floor(i * in_size / out_size))
        end = int(np.ceil((i + 1) * in_size / out_size))
        m[i, start:end] = 1.0 / (end - start)
    return jnp.asarray(m)


@register("_contrib_AdaptiveAvgPooling2D",
          params={"output_size": (tuple, None)})
def _adaptive_avg_pool(attrs, data):
    """Pool to a fixed (Ho, Wo) regardless of input size; bins follow the
    reference floor/ceil convention. Expressed as two matmuls so the MXU
    does the averaging."""
    h, w = data.shape[2], data.shape[3]
    if not attrs.output_size:
        oh, ow = 1, 1
    elif len(attrs.output_size) == 1:
        oh = ow = attrs.output_size[0]
    else:
        oh, ow = attrs.output_size
    mh = _adaptive_matrix(h, oh)
    mw = _adaptive_matrix(w, ow)
    return jnp.einsum("oh,bchw,pw->bcop", mh, data, mw)


@register("_contrib_BilinearResize2D",
          params={"height": (int, REQUIRED), "width": (int, REQUIRED)})
def _bilinear_resize(attrs, data):
    """Bilinear resize with align_corners=True (reference
    bilinear_resize.cc uses the caffe/align-corners convention)."""
    b, c, h, w = data.shape
    oh, ow = attrs.height, attrs.width
    ys = jnp.linspace(0.0, h - 1, oh)
    xs = jnp.linspace(0.0, w - 1, ow)
    yg, xg = jnp.meshgrid(ys, xs, indexing="ij")

    def one(img):
        return _bilinear_sample_2d(img, xg, yg)

    return jax.vmap(one)(data)


# ---------------------------------------------------------------------------
# fft / ifft (reference contrib/fft.cc — interleaved real/imag packing)
# ---------------------------------------------------------------------------


@register("_contrib_fft", params={"compute_size": (int, 128)})
def _fft(attrs, data):
    """FFT over the last axis; complex packed as interleaved [re, im]
    doubling the last dim (reference fft-inl.h)."""
    spec = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register("_contrib_ifft", params={"compute_size": (int, 128)})
def _ifft(attrs, data):
    """Inverse of _contrib_fft: interleaved complex -> UNNORMALIZED real
    inverse FFT (reference ifft-inl.h: out = ifft(in) * size)."""
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    spec = pairs[..., 0] + 1j * pairs[..., 1]
    return (jnp.fft.ifft(spec, axis=-1).real * n).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Proposal (RPN, reference contrib/proposal.cc)
# ---------------------------------------------------------------------------


def _gen_base_anchors(stride, scales, ratios):
    """(A, 4) base anchors centered on one stride cell (reference
    proposal.cc GenerateAnchors convention)."""
    base = np.array([0, 0, stride - 1, stride - 1], np.float32)
    cw = (base[0] + base[2]) / 2
    ch = (base[1] + base[3]) / 2
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    anchors = []
    size = w * h
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cw - (wss - 1) / 2, ch - (hss - 1) / 2,
                            cw + (wss - 1) / 2, ch + (hss - 1) / 2])
    return np.asarray(anchors, np.float32)


@register("Proposal",
          params={"rpn_pre_nms_top_n": (int, 6000),
                  "rpn_post_nms_top_n": (int, 300),
                  "threshold": (float, 0.7),
                  "rpn_min_size": (int, 16),
                  "scales": (_floats, (4.0, 8.0, 16.0, 32.0)),
                  "ratios": (_floats, (0.5, 1.0, 2.0)),
                  "feature_stride": (int, 16),
                  "output_score": (bool, False),
                  "iou_loss": (bool, False)},
          inputs=("cls_prob", "bbox_pred", "im_info"),
          num_outputs=lambda a: 2 if a["output_score"] else 1,
          aliases=("_contrib_Proposal", "_contrib_MultiProposal",
                   "MultiProposal"))
def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposal generation: anchor decode + clip + min-size filter +
    pre-NMS topk + NMS (Pallas kernel) + post-NMS pad (reference
    proposal.cc / multi_proposal.cc). Output (B*post, 5) rois
    [batch_idx, x1, y1, x2, y2]."""
    b, twice_a, h, w = cls_prob.shape
    a = twice_a // 2
    stride = attrs.feature_stride
    base = jnp.asarray(_gen_base_anchors(stride, attrs.scales, attrs.ratios))
    shift_x = jnp.arange(w, dtype=jnp.float32) * stride
    shift_y = jnp.arange(h, dtype=jnp.float32) * stride
    sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 4)
    anchors = (base[None, :, :] + shifts[:, None, :]).reshape(-1, 4)  # (HWA,4)
    n = anchors.shape[0]
    pre = min(attrs.rpn_pre_nms_top_n, n)
    post = attrs.rpn_post_nms_top_n

    def one(probs, deltas, info):
        score = probs[a:].transpose(1, 2, 0).reshape(-1)     # fg scores
        d = deltas.transpose(1, 2, 0).reshape(-1, 4)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        ax = anchors[:, 0] + aw * 0.5
        ay = anchors[:, 1] + ah * 0.5
        cx = d[:, 0] * aw + ax
        cy = d[:, 1] * ah + ay
        nw = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * aw
        nh = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * ah
        x1 = jnp.clip(cx - nw * 0.5, 0, info[1] - 1)
        y1 = jnp.clip(cy - nh * 0.5, 0, info[0] - 1)
        x2 = jnp.clip(cx + nw * 0.5, 0, info[1] - 1)
        y2 = jnp.clip(cy + nh * 0.5, 0, info[0] - 1)
        min_size = attrs.rpn_min_size * info[2]
        keep_sz = ((x2 - x1 + 1) >= min_size) & ((y2 - y1 + 1) >= min_size)
        score = jnp.where(keep_sz, score, -jnp.inf)
        order = jnp.argsort(-score)[:pre]
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)[order]
        s_sorted = score[order]
        keep = pallas_kernels.nms_keep(
            boxes, jnp.full((pre,), -1.0), jnp.isfinite(s_sorted),
            attrs.threshold, True)
        # compact kept boxes to the front (stable), take `post`, pad with
        # the top box (the reference pads by repeating)
        kept_first = jnp.argsort(~keep, stable=True)[:post]
        rows = boxes[kept_first]
        scores_out = s_sorted[kept_first]
        n_kept = jnp.minimum(jnp.sum(keep), post)
        live = jnp.arange(post) < n_kept
        rows = jnp.where(live[:, None], rows, boxes[0])
        scores_out = jnp.where(live, scores_out, s_sorted[0])
        return rows, scores_out

    rois_list, score_list = [], []
    for i in range(b):
        rows, scores = one(cls_prob[i], bbox_pred[i], im_info[i])
        idx = jnp.full((post, 1), float(i))
        rois_list.append(jnp.concatenate([idx, rows], axis=-1))
        score_list.append(scores.reshape(-1, 1))
    rois = jnp.concatenate(rois_list, axis=0)
    if attrs.output_score:
        return rois, jnp.concatenate(score_list, axis=0)
    return rois


@register("IdentityAttachKLSparseReg",
          params={"sparseness_target": (float, 0.1),
                  "penalty": (float, 0.001), "momentum": (float, 0.9)})
def _identity_kl_sparse(attrs, data):
    """Identity forward; backward adds a KL-sparsity penalty gradient
    toward the target mean activation (reference
    identity_attach_KL_sparse_reg.cc)."""
    rho = attrs.sparseness_target
    penalty = attrs.penalty

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, x

    def bwd(x, g):
        rho_hat = jnp.clip(jnp.mean(jax.nn.sigmoid(x)), 1e-6, 1 - 1e-6)
        reg = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + reg * jnp.ones_like(x),)

    f.defvjp(fwd, bwd)
    return f(data)


# ---------------------------------------------------------------------------
# Deformable ConvNets family (reference src/operator/contrib/
# deformable_convolution.cc, deformable_psroi_pooling.cc, psroi_pooling.cc)
# ---------------------------------------------------------------------------


def _bilinear_sample_chw(img, ys, xs):
    """Sample img (C,H,W) at float coords ys/xs (...,) with zero padding
    outside — the deformable-conv sampling kernel, vectorized."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = ys - y0
    dx = xs - x0

    def tap(yi, xi):
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        v = img[:, yc, xc]  # (C, ...)
        return jnp.where(valid[None], v, 0.0)

    v00 = tap(y0, x0)
    v01 = tap(y0, x0 + 1)
    v10 = tap(y0 + 1, x0)
    v11 = tap(y0 + 1, x0 + 1)
    return (v00 * ((1 - dy) * (1 - dx))[None] + v01 * ((1 - dy) * dx)[None]
            + v10 * (dy * (1 - dx))[None] + v11 * (dy * dx)[None])


def _deform_conv_inputs(attrs):
    return ["data", "offset", "weight"] if attrs.get("no_bias") else \
        ["data", "offset", "weight", "bias"]


@register("_contrib_DeformableConvolution",
          params={"kernel": (tuple, REQUIRED), "stride": (tuple, (1, 1)),
                  "dilate": (tuple, (1, 1)), "pad": (tuple, (0, 0)),
                  "num_filter": (int, REQUIRED), "num_group": (int, 1),
                  "num_deformable_group": (int, 1), "no_bias": (bool, False),
                  "workspace": (int, 1024), "layout": (str, "NCHW")},
          inputs=_deform_conv_inputs,
          aliases=("DeformableConvolution",))
def _deformable_convolution(attrs, data, offset, weight, bias=None):
    """Deformable convolution v1 (reference deformable_convolution-inl.h):
    each kernel tap samples the input at a learned offset via bilinear
    interpolation, then an ordinary weighted reduction runs over the taps.
    offset: (B, 2*KH*KW*num_deformable_group, OH, OW), ordered (dy, dx) per
    tap."""
    kh, kw = attrs.kernel
    sh, sw = attrs.stride
    dh, dw = attrs.dilate
    ph, pw = attrs.pad
    b, c, h, w = data.shape
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    ndg = attrs.num_deformable_group
    off = offset.reshape(b, ndg, kh * kw, 2, oh, ow)

    base_y = (jnp.arange(oh) * sh - ph)[:, None]  # (OH, 1)
    base_x = (jnp.arange(ow) * sw - pw)[None, :]  # (1, OW)

    def one(img, offs):
        # img (C,H,W), offs (ndg, KH*KW, 2, OH, OW)
        groups = jnp.split(img, ndg, axis=0)
        cols = []
        for g, gimg in enumerate(groups):
            taps = []
            for k in range(kh * kw):
                ky, kx = divmod(k, kw)
                ys = base_y + ky * dh + offs[g, k, 0]
                xs = base_x + kx * dw + offs[g, k, 1]
                taps.append(_bilinear_sample_chw(gimg, ys, xs))  # (C/ndg,OH,OW)
            cols.append(jnp.stack(taps, axis=1))  # (C/ndg, KH*KW, OH, OW)
        return jnp.concatenate(cols, axis=0)  # (C, KH*KW, OH, OW)

    sampled = jax.vmap(one)(data, off)  # (B, C, KH*KW, OH, OW)
    ng = attrs.num_group
    wg = weight.reshape(ng, attrs.num_filter // ng, c // ng, kh * kw)
    sg = sampled.reshape(b, ng, c // ng, kh * kw, oh, ow)
    out = jnp.einsum("gock,bgckhw->bgohw", wg, sg, optimize=True)
    out = out.reshape(b, attrs.num_filter, oh, ow)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


@register("_contrib_PSROIPooling",
          params={"spatial_scale": (float, REQUIRED),
                  "output_dim": (int, REQUIRED),
                  "pooled_size": (int, REQUIRED),
                  "group_size": (int, 0)},
          inputs=("data", "rois"),
          aliases=("PSROIPooling",))
def _psroi_pooling(attrs, data, rois):
    """Position-sensitive RoI average pooling (reference psroi_pooling.cc,
    R-FCN): bin (i,j) of output channel c pools from input channel
    c*group^2 + i*group + j, so each spatial bin looks at its own score
    map."""
    group = attrs.group_size or attrs.pooled_size
    p = attrs.pooled_size
    odim = attrs.output_dim
    _b, c, h, w = data.shape
    ycoord = jnp.arange(h, dtype=jnp.float32)
    xcoord = jnp.arange(w, dtype=jnp.float32)

    def one(roi):
        bi = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * attrs.spatial_scale)
        y1 = jnp.round(roi[2] * attrs.spatial_scale)
        x2 = jnp.round(roi[3] * attrs.spatial_scale) + 1.0
        y2 = jnp.round(roi[4] * attrs.spatial_scale) + 1.0
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh, bw = rh / p, rw / p
        img = data[bi]  # (C, H, W)

        outs = []
        for py in range(p):
            row = []
            for px in range(p):
                hs = y1 + py * bh
                he = y1 + (py + 1) * bh
                ws = x1 + px * bw
                we = x1 + (px + 1) * bw
                mask = ((ycoord >= jnp.floor(hs)) & (ycoord < jnp.ceil(he)))[:, None] & \
                       ((xcoord >= jnp.floor(ws)) & (xcoord < jnp.ceil(we)))[None, :]
                area = jnp.maximum(mask.sum(), 1)
                gy = min(py * group // p, group - 1)
                gx = min(px * group // p, group - 1)
                chans = jnp.arange(odim) * group * group + gy * group + gx
                maps = img[chans]  # (odim, H, W)
                row.append((maps * mask[None]).sum(axis=(1, 2)) / area)
            outs.append(jnp.stack(row, axis=-1))  # (odim, P)
        return jnp.stack(outs, axis=-2)  # (odim, P, P)

    return jax.vmap(one)(rois)


@register("_contrib_DeformablePSROIPooling",
          params={"spatial_scale": (float, REQUIRED),
                  "output_dim": (int, REQUIRED),
                  "group_size": (int, REQUIRED),
                  "pooled_size": (int, REQUIRED),
                  "part_size": (int, 0),
                  "sample_per_part": (int, 1),
                  "trans_std": (float, 0.0),
                  "no_trans": (bool, False)},
          inputs=lambda a: ["data", "rois"]
          + ([] if a.get("no_trans") else ["trans"]),
          aliases=("DeformablePSROIPooling",))
def _deformable_psroi_pooling(attrs, data, rois, trans=None):
    """Deformable position-sensitive RoI pooling (reference
    src/operator/contrib/deformable_psroi_pooling.cc, Deformable R-FCN):
    PSROIPooling whose bin (py, px) is shifted by a learned offset
    ``trans[r, 2k:2k+2, py', px'] * trans_std * (roi w, h)`` — class-aware
    when trans carries ``2*num_classes`` channels (class k owns output
    channels ``[k*output_dim/num_classes, ...)``) — and sampled bilinearly
    at ``sample_per_part``² points. All static loops, so the whole op
    lowers to one fused XLA module of gathers."""
    p = attrs.pooled_size
    group = attrs.group_size or p
    part = attrs.part_size or p
    spp = attrs.sample_per_part
    odim = attrs.output_dim
    _b, c, h, w = data.shape

    def bilinear(img, y, x):
        """img (C,H,W); y,x per-channel vectors (C,) — bilinear sample.
        Valid window is [-0.5, size-0.5] with edge clamping, matching the
        reference kernel (deformable_psroi_pooling.cc: continue outside,
        clamp inside)."""
        ok = (y >= -0.5) & (y <= h - 0.5) & (x >= -0.5) & (x <= w - 0.5)
        y = jnp.clip(y, 0.0, h - 1.0)
        x = jnp.clip(x, 0.0, w - 1.0)
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy1 = y - y0
        wx1 = x - x0
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        ci = jnp.arange(img.shape[0])
        v = (img[ci, y0i, x0i] * (1 - wy1) * (1 - wx1)
             + img[ci, y1i, x0i] * wy1 * (1 - wx1)
             + img[ci, y0i, x1i] * (1 - wy1) * wx1
             + img[ci, y1i, x1i] * wy1 * wx1)
        return jnp.where(ok, v, 0.0), ok

    # class-aware offsets (reference: num_classes = trans_ch/2,
    # channels_each_class = output_dim/num_classes)
    if trans is not None:
        n_cls = max(1, trans.shape[1] // 2)
        if odim % n_cls:
            raise MXNetError(
                "DeformablePSROIPooling: output_dim %d not divisible by "
                "num_classes %d (trans has %d channels)"
                % (odim, n_cls, trans.shape[1]))
        class_of = jnp.arange(odim) // (odim // n_cls)  # (odim,)

    def one(roi, tr):
        bi = roi[0].astype(jnp.int32)
        # reference uses half-pixel roi corners (round - 0.5 semantics)
        x1 = jnp.round(roi[1]) * attrs.spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * attrs.spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * attrs.spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * attrs.spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / p, rh / p
        sub_w, sub_h = bw / spp, bh / spp
        img = data[bi]

        rows = []
        for py in range(p):
            cols = []
            for px in range(p):
                part_y = min(py * part // p, part - 1)
                part_x = min(px * part // p, part - 1)
                if tr is None:
                    dy = dx = jnp.zeros((odim,))
                else:
                    dx = tr[class_of * 2, part_y, part_x] \
                        * attrs.trans_std * rw
                    dy = tr[class_of * 2 + 1, part_y, part_x] \
                        * attrs.trans_std * rh
                gy = min(py * group // p, group - 1)
                gx = min(px * group // p, group - 1)
                chans = jnp.arange(odim) * group * group + gy * group + gx
                maps = img[chans]
                acc = jnp.zeros((odim,), data.dtype)
                cnt = jnp.zeros((), data.dtype)
                for iy in range(spp):
                    for ix in range(spp):
                        sy = y1 + py * bh + dy + (iy + 0.5) * sub_h
                        sx = x1 + px * bw + dx + (ix + 0.5) * sub_w
                        val, ok = bilinear(maps, sy, sx)
                        acc = acc + val
                        cnt = cnt + ok.astype(data.dtype)
                cols.append(acc / jnp.maximum(cnt, 1.0))
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)  # (odim, P, P)

    if attrs.no_trans or trans is None:
        return jax.vmap(lambda r: one(r, None))(rois)
    return jax.vmap(one)(rois, trans)


@register("_contrib_count_sketch",
          params={"out_dim": (int, REQUIRED),
                  "processing_batch_size": (int, 32)},
          inputs=("data", "h", "s"),
          aliases=("count_sketch",))
def _count_sketch(attrs, data, h, s):
    """Count-sketch projection (reference count_sketch.cc, used by compact
    bilinear pooling): out[b, h[i]] += s[i] * data[b, i] — a scatter-add
    over hashed feature indices."""
    b = data.shape[0]
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    out = jnp.zeros((b, attrs.out_dim), dtype=data.dtype)
    return out.at[:, hh].add(data * ss[None, :])


# ---------------------------------------------------------------------------
# SyncBatchNorm + legacy v1 op names
# ---------------------------------------------------------------------------


def _register_aliases():
    """Legacy/alias op names resolving to their modern implementations.

    - ``_contrib_SyncBatchNorm`` (reference sync_batch_norm-inl.h): under
      GSPMD the batch axis of a sharded tensor is ONE logical axis, so
      BatchNorm's mean/var reductions already span every device — XLA
      inserts the cross-replica psums the reference implements by hand
      (verified by tests/test_sync_bn.py against per-device baselines).
      The alias makes that contract explicit and keeps symbol JSON
      compatibility.
    - ``*_v1`` ops (reference batch_norm_v1.cc, convolution_v1.cc,
      pooling_v1.cc): pre-NNVM implementations whose semantics the modern
      ops cover; kept as loadable names for old model-zoo JSON.
    - ``fft``/``ifft``: short names for the contrib FFT pair.
    """
    from .registry import OP_REGISTRY

    for legacy, modern in [
        ("_contrib_SyncBatchNorm", "BatchNorm"),
        ("SyncBatchNorm", "BatchNorm"),
        ("BatchNorm_v1", "BatchNorm"),
        ("Convolution_v1", "Convolution"),
        ("Pooling_v1", "Pooling"),
        ("fft", "_contrib_fft"),
        ("ifft", "_contrib_ifft"),
    ]:
        OP_REGISTRY.setdefault(legacy, OP_REGISTRY[modern])


_register_aliases()
