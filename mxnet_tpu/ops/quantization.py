"""INT8 quantization operators.

Reference ``src/operator/quantization/`` (quantize/dequantize/requantize,
quantized_conv, quantized_fully_connected, quantized_pooling,
quantized_flatten; 21 files). TPU-native design: int8 matmuls/convs feed
the MXU directly via ``lax.dot_general``/``conv_general_dilated`` with
``preferred_element_type=int32`` — the int8 tile shape (32, 128) doubles
MXU throughput versus bf16, which is the whole point of the exercise.

Quantization scheme matches the reference: int8 is SYMMETRIC
(quantized_range=127, real range max(|min|,|max|)), uint8 is affine over
[min, max]; quantized compute ops take int8 data + the float min/max pair
per input and return int32 + the output's float range (int32 extremes map
onto the product of input scales — quantize-inl.h GetQuantizedRange /
quantized_fully_connected.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import REQUIRED, register

INT8_RANGE = 127.0
UINT8_RANGE = 255.0
INT32_RANGE = float(2 ** 31 - 1)


def _real_range(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


@register("_contrib_quantize",
          params={"out_type": (str, "uint8")},
          inputs=("data", "min_range", "max_range"), num_outputs=3)
def _quantize(attrs, data, min_range, max_range):
    """float -> int8/uint8 (reference quantize-inl.h QuantizeCompute)."""
    mn = jnp.reshape(min_range, ()).astype(jnp.float32)
    mx = jnp.reshape(max_range, ()).astype(jnp.float32)
    if attrs.out_type == "int8":
        r = _real_range(mn, mx)
        scale = INT8_RANGE / jnp.maximum(r, 1e-30)
        q = jnp.clip(jnp.round(data * scale), -INT8_RANGE, INT8_RANGE)
        return q.astype(jnp.int8), -r, r
    if attrs.out_type == "uint8":
        scale = UINT8_RANGE / jnp.maximum(mx - mn, 1e-30)
        q = jnp.clip(jnp.round((data - mn) * scale), 0.0, UINT8_RANGE)
        return q.astype(jnp.uint8), mn, mx
    raise ValueError("unsupported out_type %r" % attrs.out_type)


@register("_contrib_dequantize",
          params={"out_type": (str, "float32")},
          inputs=("data", "min_range", "max_range"))
def _dequantize(attrs, data, min_range, max_range):
    """int8/uint8/int32 -> float (reference dequantize-inl.h)."""
    mn = jnp.reshape(min_range, ()).astype(jnp.float32)
    mx = jnp.reshape(max_range, ()).astype(jnp.float32)
    if data.dtype == jnp.uint8:
        scale = (mx - mn) / UINT8_RANGE
        return data.astype(jnp.float32) * scale + mn
    quant_range = INT8_RANGE if data.dtype == jnp.int8 else INT32_RANGE
    scale = _real_range(mn, mx) / quant_range
    return data.astype(jnp.float32) * scale


@register("_contrib_requantize",
          params={"min_calib_range": (float, None),
                  "max_calib_range": (float, None)},
          inputs=("data", "min_range", "max_range"), num_outputs=3)
def _requantize(attrs, data, min_range, max_range):
    """int32 accumulator -> int8 with a (calibrated) narrower range
    (reference requantize-inl.h)."""
    mn = jnp.reshape(min_range, ()).astype(jnp.float32)
    mx = jnp.reshape(max_range, ()).astype(jnp.float32)
    in_scale = _real_range(mn, mx) / INT32_RANGE
    real = data.astype(jnp.float32) * in_scale
    if attrs.min_calib_range is not None and attrs.max_calib_range is not None:
        out_r = max(abs(attrs.min_calib_range), abs(attrs.max_calib_range))
        out_r = jnp.float32(out_r)
    else:
        out_r = jnp.maximum(jnp.max(jnp.abs(real)), 1e-30)
    q = jnp.clip(jnp.round(real * (INT8_RANGE / out_r)),
                 -INT8_RANGE, INT8_RANGE)
    return q.astype(jnp.int8), -out_r, out_r


def _i8(x):
    return x.astype(jnp.int8) if x.dtype != jnp.int8 else x


def _qfc_inputs(attrs):
    if attrs.get("no_bias"):
        return ["data", "weight", "min_data", "max_data",
                "min_weight", "max_weight"]
    return ["data", "weight", "bias", "min_data", "max_data",
            "min_weight", "max_weight", "min_bias", "max_bias"]


@register("_contrib_quantized_fully_connected",
          params={"num_hidden": (int, REQUIRED), "no_bias": (bool, False),
                  "flatten": (bool, True)},
          inputs=_qfc_inputs, num_outputs=3)
def _quantized_fc(attrs, data, weight, *rest):
    """int8 x int8 -> int32 FC on the MXU (reference
    quantized_fully_connected.cc). Output range: int32 extremes map to the
    product of the input scales."""
    if attrs.no_bias:
        min_d, max_d, min_w, max_w = rest
        bias = None
    else:
        bias, min_d, max_d, min_w, max_w, min_b, max_b = rest
    x = data.reshape(data.shape[0], -1) if attrs.flatten else data
    acc = lax.dot_general(
        _i8(x), _i8(weight),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    d_scale = _real_range(jnp.reshape(min_d, ()), jnp.reshape(max_d, ())) \
        / INT8_RANGE
    w_scale = _real_range(jnp.reshape(min_w, ()), jnp.reshape(max_w, ())) \
        / INT8_RANGE
    out_scale = d_scale * w_scale
    if bias is not None:
        b_scale = _real_range(jnp.reshape(min_b, ()),
                              jnp.reshape(max_b, ())) / INT8_RANGE
        # rescale bias quanta into the accumulator's scale
        b32 = jnp.round(bias.astype(jnp.float32) * b_scale
                        / jnp.maximum(out_scale, 1e-30)).astype(jnp.int32)
        acc = acc + b32
    return acc, -INT32_RANGE * out_scale, INT32_RANGE * out_scale


def _qconv_inputs(attrs):
    if attrs.get("no_bias"):
        return ["data", "weight", "min_data", "max_data",
                "min_weight", "max_weight"]
    return ["data", "weight", "bias", "min_data", "max_data",
            "min_weight", "max_weight", "min_bias", "max_bias"]


@register("_contrib_quantized_conv",
          params={"kernel": (tuple, REQUIRED), "stride": (tuple, None),
                  "pad": (tuple, None), "dilate": (tuple, None),
                  "num_filter": (int, REQUIRED), "num_group": (int, 1),
                  "no_bias": (bool, False), "layout": (str, "NCHW")},
          inputs=_qconv_inputs, num_outputs=3)
def _quantized_conv(attrs, data, weight, *rest):
    """int8 convolution with int32 accumulation (reference
    quantized_conv.cc)."""
    if attrs.no_bias:
        min_d, max_d, min_w, max_w = rest
        bias = None
    else:
        bias, min_d, max_d, min_w, max_w, min_b, max_b = rest
    k = len(attrs.kernel)
    stride = attrs.stride or (1,) * k
    pad = attrs.pad or (0,) * k
    dilate = attrs.dilate or (1,) * k
    if k != 2:
        raise ValueError("quantized_conv supports 2D kernels only")
    acc = lax.conv_general_dilated(
        _i8(data), _i8(weight), window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        feature_group_count=attrs.num_group,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.int32)
    d_scale = _real_range(jnp.reshape(min_d, ()), jnp.reshape(max_d, ())) \
        / INT8_RANGE
    w_scale = _real_range(jnp.reshape(min_w, ()), jnp.reshape(max_w, ())) \
        / INT8_RANGE
    out_scale = d_scale * w_scale
    if bias is not None:
        b_scale = _real_range(jnp.reshape(min_b, ()),
                              jnp.reshape(max_b, ())) / INT8_RANGE
        b32 = jnp.round(bias.astype(jnp.float32) * b_scale
                        / jnp.maximum(out_scale, 1e-30)).astype(jnp.int32)
        acc = acc + b32.reshape(1, -1, *([1] * (acc.ndim - 2)))
    return acc, -INT32_RANGE * out_scale, INT32_RANGE * out_scale


@register("_contrib_quantized_pooling",
          params={"kernel": (tuple, None), "pool_type": (str, "max"),
                  "stride": (tuple, None), "pad": (tuple, None),
                  "global_pool": (bool, False),
                  "pooling_convention": (str, "valid")},
          inputs=("data", "min_data", "max_data"), num_outputs=3)
def _quantized_pooling(attrs, data, min_data, max_data):
    """int8 pooling; ranges pass through (reference quantized_pooling.cc
    — max/avg pooling is scale-invariant)."""
    from .registry import OP_REGISTRY

    pool = OP_REGISTRY["Pooling"]
    p_attrs = pool.parse_attrs({
        "kernel": attrs.kernel, "pool_type": attrs.pool_type,
        "stride": attrs.stride, "pad": attrs.pad,
        "global_pool": attrs.global_pool,
        "pooling_convention": attrs.pooling_convention})
    out = pool.fcompute(p_attrs, data.astype(jnp.float32))
    if isinstance(out, (tuple, list)):
        out = out[0]
    if attrs.pool_type == "max":
        out = out.astype(data.dtype)
    else:
        out = jnp.round(out).astype(data.dtype)
    return out, jnp.reshape(min_data, ()), jnp.reshape(max_data, ())


@register("_contrib_quantized_flatten",
          inputs=("data", "min_data", "max_data"), num_outputs=3)
def _quantized_flatten(attrs, data, min_data, max_data):
    return (data.reshape(data.shape[0], -1),
            jnp.reshape(min_data, ()), jnp.reshape(max_data, ()))
