"""Control-flow operators lowered to XLA structured control flow.

Reference: ``_foreach`` (src/operator/control_flow.cc:35-73), ``_while_loop``
and ``_cond`` (subgraph ops, src/operator/subgraph_op_common.cc). The
reference interprets a captured subgraph once per iteration through its
dependency engine; here each op compiles into ONE XLA construct —
``lax.scan`` for ``_foreach``, a masked ``lax.scan`` with a static trip
count for ``_while_loop`` (predicated state updates keep it reverse-mode
differentiable, which raw ``lax.while_loop`` is not), and ``lax.cond`` for
``_cond``. Gradients come free from whole-graph ``jax.vjp`` like every
other op (registry docstring).

Subgraphs are stored in node attrs as Symbol objects (serialized to nested
graph JSON by ``OpDef.serialize_attrs``, parsed back on load); the op's
positional inputs bind to the subgraph's named variables through the
``*_names`` attrs.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import REQUIRED, register


def _subgraph(v):
    if isinstance(v, str):
        from ..symbol import load_json

        return load_json(v)
    return v


def _names(v):
    if isinstance(v, str):
        v = v.strip().lstrip("(").rstrip(")")  # empty lists serialize as "()"
        return tuple(x for x in (p.strip() for p in v.split(",")) if x)
    return tuple(v)


def _scalar_bool(x):
    return jnp.reshape(x, ()).astype(bool)


# ---------------------------------------------------------------------------
# _foreach → lax.scan
# ---------------------------------------------------------------------------


def _foreach_inputs(attrs):
    return (list(attrs["data_names"]) + list(attrs["state_names"])
            + list(attrs["free_names"]))


@register(
    "_foreach",
    params={
        "__subgraph__": (_subgraph, REQUIRED),
        "data_names": (_names, REQUIRED),
        "state_names": (_names, REQUIRED),
        "free_names": (_names, ()),
        "num_out_data": (int, REQUIRED),
        "remat": (bool, False),
    },
    inputs=_foreach_inputs,
    num_outputs=lambda a: a["num_out_data"] + len(a["state_names"]),
)
def _foreach(attrs, *inputs):
    """scan the subgraph over axis 0 of each data input; subgraph outputs
    are [step outputs..., new states...] (reference control_flow.cc:35).

    ``remat=True`` wraps the scan body in ``jax.checkpoint``: each step's
    internal activations are recomputed in the backward instead of stored
    — scan-granular rematerialization, the sublinear-memory recipe of the
    reference's memonger (example/memcost). Whole-graph remat cannot
    shrink a fused fwd+bwd module; per-step remat can (see
    example/memcost/memonger.py for compiler-measured numbers)."""
    sub = attrs["__subgraph__"]
    dn, sn = attrs["data_names"], attrs["state_names"]
    fn = attrs["free_names"]
    nd_, ns, nod = len(dn), len(sn), attrs["num_out_data"]
    data = tuple(inputs[:nd_])
    states = tuple(inputs[nd_:nd_ + ns])
    free = dict(zip(fn, inputs[nd_ + ns:]))

    def step(carry, xs):
        vm = dict(zip(sn, carry))
        vm.update(zip(dn, xs))
        vm.update(free)
        outs = sub.eval_jax(vm)
        return tuple(outs[nod:]), tuple(outs[:nod])

    if attrs.get("remat"):
        import jax

        step = jax.checkpoint(step)
    final_states, stacked = lax.scan(step, states, data)
    return tuple(stacked) + tuple(final_states)


# ---------------------------------------------------------------------------
# _while_loop → masked lax.scan (static trip count)
# ---------------------------------------------------------------------------


def _while_inputs(attrs):
    return list(attrs["loop_var_names"]) + list(attrs["free_names"])


@register(
    "_while_loop",
    params={
        "__cond__": (_subgraph, REQUIRED),
        "__func__": (_subgraph, REQUIRED),
        "loop_var_names": (_names, REQUIRED),
        "free_names": (_names, ()),
        "num_out_data": (int, REQUIRED),
        "max_iterations": (int, REQUIRED),
    },
    inputs=_while_inputs,
    num_outputs=lambda a: a["num_out_data"] + len(a["loop_var_names"]),
)
def _while_loop(attrs, *inputs):
    """Run the func subgraph while the cond subgraph is true, at most
    ``max_iterations`` times. Step outputs are stacked into buffers of
    leading size max_iterations (rows past the final step are zero —
    reference while_loop leaves them undefined); final loop vars follow.
    Lowered as a scan with predicated updates: both subgraphs are evaluated
    every iteration and results are selected by the live mask, trading
    wasted FLOPs for a static schedule the MXU can run."""
    cond_g, func_g = attrs["__cond__"], attrs["__func__"]
    vn, fn = attrs["loop_var_names"], attrs["free_names"]
    nv, nod = len(vn), attrs["num_out_data"]
    loop_vars = tuple(inputs[:nv])
    free = dict(zip(fn, inputs[nv:]))

    def step(carry, _):
        active, vars_ = carry
        vm = dict(zip(vn, vars_))
        vm.update(free)
        do = jnp.logical_and(active, _scalar_bool(cond_g.eval_jax(vm)[0]))
        outs = func_g.eval_jax(vm)
        step_out = tuple(jnp.where(do, o, jnp.zeros_like(o))
                         for o in outs[:nod])
        new_vars = tuple(jnp.where(do, n, v)
                         for n, v in zip(outs[nod:], vars_))
        return (do, new_vars), step_out

    (_, final_vars), stacked = lax.scan(
        step, (jnp.bool_(True), loop_vars), None,
        length=attrs["max_iterations"])
    return tuple(stacked) + tuple(final_vars)


# ---------------------------------------------------------------------------
# _cond → lax.cond
# ---------------------------------------------------------------------------


def _cond_inputs(attrs):
    return list(attrs["input_names"])


@register(
    "_cond",
    params={
        "__pred__": (_subgraph, REQUIRED),
        "__then__": (_subgraph, REQUIRED),
        "__else__": (_subgraph, REQUIRED),
        "input_names": (_names, REQUIRED),
        "num_out": (int, REQUIRED),
    },
    inputs=_cond_inputs,
    num_outputs=lambda a: a["num_out"],
)
def _cond(attrs, *inputs):
    """Branch between the then/else subgraphs on the pred subgraph's scalar
    output; both branches must yield identical shapes/dtypes (reference
    contract and an XLA requirement alike)."""
    vm = dict(zip(attrs["input_names"], inputs))
    pred = _scalar_bool(attrs["__pred__"].eval_jax(vm)[0])

    def then_fn(_):
        return tuple(attrs["__then__"].eval_jax(vm))

    def else_fn(_):
        return tuple(attrs["__else__"].eval_jax(vm))

    return lax.cond(pred, then_fn, else_fn, None)
