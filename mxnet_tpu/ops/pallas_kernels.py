"""First-party Pallas TPU kernels.

The detection tail is where XLA's stock ops stop being enough: NMS is a
sequential, data-dependent suppression loop the reference implements as a
custom CUDA kernel (``src/operator/contrib/bounding_box.cu``). Here it is a
Pallas TPU kernel: boxes live in VMEM as (8, N) lane-major rows, the
suppression loop is a ``fori_loop`` whose body is pure VPU work (8x128
vector compare/select — no scalar gather), and N is padded to the 128-lane
boundary. On non-TPU backends (the CPU test mesh) the same kernel runs in
Pallas interpret mode, so correctness is tested everywhere while the TPU
path compiles to a real kernel.

Layout notes (see /opt/skills/guides/pallas_guide.md):
- float32 min tile is (8, 128): inputs are packed into an (8, Np) matrix —
  rows x1,y1,x2,y2,class,keep and two zero rows of padding.
- iota must be >=2D on TPU: all row vectors are kept (1, Np).
- scalar extraction from a lane vector uses a masked sum instead of a
  dynamic gather (VPU-friendly, no SMEM round-trip).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

LANES = 128
_ROW_X1, _ROW_Y1, _ROW_X2, _ROW_Y2, _ROW_CLS, _ROW_KEEP = range(6)
_PACK_ROWS = 8  # float32 sublane tile


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _nms_kernel(packed_ref, out_ref, *, n_boxes, overlap_thresh,
                force_suppress):
    """Greedy NMS over score-sorted boxes.

    packed_ref: (8, Np) f32 — rows x1,y1,x2,y2,class,keep(1/0 valid).
    out_ref:    (8, Np) f32 — row 0 is the final keep mask.
    """
    x1 = packed_ref[_ROW_X1:_ROW_X1 + 1, :]
    y1 = packed_ref[_ROW_Y1:_ROW_Y1 + 1, :]
    x2 = packed_ref[_ROW_X2:_ROW_X2 + 1, :]
    y2 = packed_ref[_ROW_Y2:_ROW_Y2 + 1, :]
    cls = packed_ref[_ROW_CLS:_ROW_CLS + 1, :]
    keep0 = packed_ref[_ROW_KEEP:_ROW_KEEP + 1, :]
    np_ = x1.shape[1]
    lane = lax.broadcasted_iota(jnp.int32, (1, np_), 1)
    area = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)

    def sel(vec, i):
        # masked-sum scalar extraction: one VPU pass, no dynamic gather
        return jnp.sum(jnp.where(lane == i, vec, 0.0))

    def body(i, keep):
        keep_i = sel(keep, i)
        xi1, yi1 = sel(x1, i), sel(y1, i)
        xi2, yi2 = sel(x2, i), sel(y2, i)
        ci = sel(cls, i)
        ai = jnp.maximum(xi2 - xi1, 0.0) * jnp.maximum(yi2 - yi1, 0.0)
        iw = jnp.maximum(jnp.minimum(x2, xi2) - jnp.maximum(x1, xi1), 0.0)
        ih = jnp.maximum(jnp.minimum(y2, yi2) - jnp.maximum(y1, yi1), 0.0)
        inter = iw * ih
        iou = inter / jnp.maximum(area + ai - inter, 1e-12)
        same = jnp.logical_or(bool(force_suppress), cls == ci)
        suppress = jnp.logical_and(
            jnp.logical_and(keep_i > 0.5, lane > i),
            jnp.logical_and(same, iou > overlap_thresh))
        return jnp.where(suppress, 0.0, keep)

    keep = lax.fori_loop(0, n_boxes, body, keep0)
    out_ref[:, :] = jnp.broadcast_to(keep, out_ref.shape)


def nms_keep(boxes, cls_ids, valid, overlap_thresh, force_suppress):
    """Keep mask for greedy NMS over boxes ALREADY sorted by score desc.

    boxes: (N, 4) corner-format f32; cls_ids: (N,) f32 (-1 = no class);
    valid: (N,) bool. Returns (N,) bool.
    """
    n = boxes.shape[0]
    np_ = _pad_up(max(n, LANES), LANES)
    pad = np_ - n

    packed = jnp.zeros((_PACK_ROWS, np_), jnp.float32)
    for row, col in ((_ROW_X1, 0), (_ROW_Y1, 1), (_ROW_X2, 2), (_ROW_Y2, 3)):
        packed = packed.at[row, :n].set(boxes[:, col].astype(jnp.float32))
    packed = packed.at[_ROW_CLS, :n].set(cls_ids.astype(jnp.float32))
    packed = packed.at[_ROW_CLS, n:].set(-2.0)  # padding matches no class
    packed = packed.at[_ROW_KEEP, :n].set(valid.astype(jnp.float32))

    kernel = functools.partial(
        _nms_kernel, n_boxes=n, overlap_thresh=float(overlap_thresh),
        force_suppress=bool(force_suppress))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((_PACK_ROWS, np_), jnp.float32),
        interpret=_interpret(),
    )(packed)
    return out[0, :n] > 0.5
