"""First-party Pallas TPU kernels.

The detection tail is where XLA's stock ops stop being enough: NMS is a
sequential, data-dependent suppression loop the reference implements as a
custom CUDA kernel (``src/operator/contrib/bounding_box.cu``). Here it is a
Pallas TPU kernel: boxes live in VMEM as (8, N) lane-major rows, the
suppression loop is a ``fori_loop`` whose body is pure VPU work (8x128
vector compare/select — no scalar gather), and N is padded to the 128-lane
boundary. On non-TPU backends (the CPU test mesh) the same kernel runs in
Pallas interpret mode, so correctness is tested everywhere while the TPU
path compiles to a real kernel.

Layout notes (see /opt/skills/guides/pallas_guide.md):
- float32 min tile is (8, 128): inputs are packed into an (8, Np) matrix —
  rows x1,y1,x2,y2,class,keep and two zero rows of padding.
- iota must be >=2D on TPU: all row vectors are kept (1, Np).
- scalar extraction from a lane vector uses a masked sum instead of a
  dynamic gather (VPU-friendly, no SMEM round-trip).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

LANES = 128
_ROW_X1, _ROW_Y1, _ROW_X2, _ROW_Y2, _ROW_CLS, _ROW_KEEP = range(6)
_PACK_ROWS = 8  # float32 sublane tile


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across the rename: jax >= 0.5 calls it
    ``CompilerParams``, 0.4.x ``TPUCompilerParams`` — same fields."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def _pad_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _nms_kernel(packed_ref, out_ref, *, n_boxes, overlap_thresh,
                force_suppress):
    """Greedy NMS over score-sorted boxes.

    packed_ref: (8, Np) f32 — rows x1,y1,x2,y2,class,keep(1/0 valid).
    out_ref:    (8, Np) f32 — row 0 is the final keep mask.
    """
    x1 = packed_ref[_ROW_X1:_ROW_X1 + 1, :]
    y1 = packed_ref[_ROW_Y1:_ROW_Y1 + 1, :]
    x2 = packed_ref[_ROW_X2:_ROW_X2 + 1, :]
    y2 = packed_ref[_ROW_Y2:_ROW_Y2 + 1, :]
    cls = packed_ref[_ROW_CLS:_ROW_CLS + 1, :]
    keep0 = packed_ref[_ROW_KEEP:_ROW_KEEP + 1, :]
    np_ = x1.shape[1]
    lane = lax.broadcasted_iota(jnp.int32, (1, np_), 1)
    area = jnp.maximum(x2 - x1, 0.0) * jnp.maximum(y2 - y1, 0.0)

    def sel(vec, i):
        # masked-sum scalar extraction: one VPU pass, no dynamic gather
        return jnp.sum(jnp.where(lane == i, vec, 0.0))

    def body(i, keep):
        keep_i = sel(keep, i)
        xi1, yi1 = sel(x1, i), sel(y1, i)
        xi2, yi2 = sel(x2, i), sel(y2, i)
        ci = sel(cls, i)
        ai = jnp.maximum(xi2 - xi1, 0.0) * jnp.maximum(yi2 - yi1, 0.0)
        iw = jnp.maximum(jnp.minimum(x2, xi2) - jnp.maximum(x1, xi1), 0.0)
        ih = jnp.maximum(jnp.minimum(y2, yi2) - jnp.maximum(y1, yi1), 0.0)
        inter = iw * ih
        iou = inter / jnp.maximum(area + ai - inter, 1e-12)
        same = jnp.logical_or(bool(force_suppress), cls == ci)
        suppress = jnp.logical_and(
            jnp.logical_and(keep_i > 0.5, lane > i),
            jnp.logical_and(same, iou > overlap_thresh))
        return jnp.where(suppress, 0.0, keep)

    keep = lax.fori_loop(0, n_boxes, body, keep0)
    out_ref[:, :] = jnp.broadcast_to(keep, out_ref.shape)


def nms_keep(boxes, cls_ids, valid, overlap_thresh, force_suppress):
    """Keep mask for greedy NMS over boxes ALREADY sorted by score desc.

    boxes: (N, 4) corner-format f32; cls_ids: (N,) f32 (-1 = no class);
    valid: (N,) bool. Returns (N,) bool.
    """
    n = boxes.shape[0]
    np_ = _pad_up(max(n, LANES), LANES)
    pad = np_ - n

    packed = jnp.zeros((_PACK_ROWS, np_), jnp.float32)
    for row, col in ((_ROW_X1, 0), (_ROW_Y1, 1), (_ROW_X2, 2), (_ROW_Y2, 3)):
        packed = packed.at[row, :n].set(boxes[:, col].astype(jnp.float32))
    packed = packed.at[_ROW_CLS, :n].set(cls_ids.astype(jnp.float32))
    packed = packed.at[_ROW_CLS, n:].set(-2.0)  # padding matches no class
    packed = packed.at[_ROW_KEEP, :n].set(valid.astype(jnp.float32))

    kernel = functools.partial(
        _nms_kernel, n_boxes=n, overlap_thresh=float(overlap_thresh),
        force_suppress=bool(force_suppress))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((_PACK_ROWS, np_), jnp.float32),
        interpret=_interpret(),
    )(packed)
    return out[0, :n] > 0.5


# ---------------------------------------------------------------------------
# Flash attention (TPU fused attention kernel)
# ---------------------------------------------------------------------------
#
# The MXU-resident attention kernel: one pallas_call computes
# softmax(q k^T / sqrt(d)) v without materializing the (S, S) score matrix
# in HBM. Grid (batch*heads, q-blocks, kv-blocks); the kv axis is the
# innermost ("arbitrary") dimension and carries the online-softmax state
# (running max m, normalizer l, weighted accumulator acc) in VMEM scratch.
# Interpret mode runs the same kernel on the CPU test mesh.

_NEG_BIG = -1e30  # -inf would turn exp(m_prev - m_new) into nan on an
#                   all-masked first block; a large-negative sentinel keeps
#                   the online-softmax algebra finite


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                  *, scale, causal, bq, bk, n_kv, seq_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)          # (bq, d)
    k = k_ref[0].astype(jnp.float32)          # (bk, d)
    v = v_ref[0].astype(jnp.float32)          # (bk, d)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale

    rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    valid = cols < seq_len                    # sequence-padding mask
    if causal:
        valid = valid & (cols <= rows)
    s = jnp.where(valid, s, _NEG_BIG)

    m_prev = m_scr[:, :1]                     # (bq, 1)
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
    acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == n_kv - 1)
    def _finish():
        o_ref[0] = (acc_scr[...]
                    / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, scale, causal, block_q=128, block_k=128):
    """q/k/v: (B, H, S, D) -> (B, H, S, D)."""
    from jax.experimental.pallas import tpu as pltpu

    import math

    b, h, s_len, d = q.shape
    bq = min(block_q, _pad_up(s_len, 8))
    bk = min(block_k, _pad_up(s_len, 128))
    # pad to a common multiple of BOTH block sizes — padding to only the
    # larger one truncates the other axis's grid and silently drops tail
    # blocks when custom block sizes don't divide it
    sp = _pad_up(s_len, math.lcm(bq, bk))
    pad = ((0, 0), (0, 0), (0, sp - s_len), (0, 0))
    qp = jnp.pad(q, pad).reshape(b * h, sp, d)
    kp = jnp.pad(k, pad).reshape(b * h, sp, d)
    vp = jnp.pad(v, pad).reshape(b * h, sp, d)
    n_q, n_kv = sp // bq, sp // bk

    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, n_kv=n_kv, seq_len=s_len)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sp, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qp, kp, vp)
    return out.reshape(b, h, sp, d)[:, :, :s_len]


def _attention_reference(q, k, v, scale, causal):
    """Pure-jnp attention — the backward recompute path."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        n = s.shape[-1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale=None, causal=False):
    """Fused multi-head attention, (B, H, S, D) layout.

    Forward runs the Pallas kernel (flash/online-softmax: O(S) memory, MXU
    matmuls, no (S, S) HBM tensor). Backward differentiates a dense jnp
    recompute, which DOES materialize the (S, S) score matrix — O(S^2)
    memory. The flash memory bound therefore holds for inference and for
    forward-only use; long-sequence TRAINING should shard S first (ring /
    Ulysses in sequence_parallel.py) so each device's S is modest.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_forward(q, k, v, scale, causal)


def _fa_fwd(q, k, v, scale, causal):
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash_forward(q, k, v, scale, causal), (q, k, v)


def _fa_bwd(scale, causal, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(lambda a, b, c:
                     _attention_reference(a, b, c, scale, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ---------------------------------------------------------------------------
# Ragged paged-attention decode (TPU LLM serving kernel)
# ---------------------------------------------------------------------------
#
# The decode-plane attention of mxnet_tpu.serving.decode: each of S decode
# slots holds ONE new query token that must attend to that sequence's whole
# KV history, which lives scattered across fixed-size pages of a static
# device pool (serving.kvcache). Shapes are static in (S, max_pages,
# page_size) regardless of how many sequences are live or how long each
# one is — membership churn and ragged lengths never retrace (the Ragged
# Paged Attention argument, PAPERS.md).
#
# Kernel layout: grid (S, max_pages); the page axis is the innermost
# ("arbitrary") dimension and carries online-softmax state (running max m,
# normalizer l, accumulator acc) in VMEM scratch, exactly the flash-kernel
# idiom above. The page table and sequence lengths ride in as
# scalar-prefetch operands (PrefetchScalarGridSpec), so the K/V BlockSpec
# index_map dereferences the page table — the pool page is DMA'd straight
# into VMEM with no gather op in the kernel body. Interpret mode runs the
# same kernel on the CPU test mesh; `paged_attention` (the dispatcher the
# decode engine calls) uses the dense jnp reference off-TPU instead, which
# is faster than interpreting and bit-comparable within fp tolerance.


def _paged_kernel(pt_ref, sl_ref, qp_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, page_size, max_pages, groups,
                  scale, causal):
    """One (slot, page) cell of ragged paged attention.

    q_ref: (1, Hp, D) — the slot's single query token (heads padded to the
    sublane tile); k_ref/v_ref: (1, page_size, KH, D) — the page named by
    the slot's page table; o_ref: (1, Hp, D). Scratch m/l: (Hp, LANES),
    acc: (Hp, D).
    """
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)            # (Hp, D)
    k = k_ref[0].astype(jnp.float32)            # (page_size, KH, D)
    v = v_ref[0].astype(jnp.float32)
    hp = q.shape[0]
    kh = k.shape[1]

    # scores (Hp, page_size): head h attends kv head h // groups. Per-kv-
    # head 2D matmuls keep the MXU fed without a batched einsum; kh is a
    # small trace-time constant so the python loop unrolls.
    scores = jnp.zeros((hp, page_size), jnp.float32)
    for khi in range(kh):
        qh = lax.dynamic_slice_in_dim(q, khi * groups, groups, 0)
        sk = jax.lax.dot_general(qh, k[:, khi, :], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        scores = lax.dynamic_update_slice_in_dim(scores, sk, khi * groups, 0)
    scores = scores * scale

    # ragged mask: token positions of this page vs the slot's length (and
    # its query position when causal). Padded table entries point at page
    # 0; the position mask kills them, so the duplicate load is harmless.
    pos = j * page_size + lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
    valid = pos < sl_ref[s]
    if causal:
        valid = jnp.logical_and(valid, pos <= qp_ref[s])
    scores = jnp.where(valid, scores, _NEG_BIG)

    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)
    l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
    pv = jnp.zeros_like(acc_scr[...])
    for khi in range(kh):
        ph = lax.dynamic_slice_in_dim(p, khi * groups, groups, 0)
        av = jax.lax.dot_general(ph, v[:, khi, :], (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        pv = lax.dynamic_update_slice_in_dim(pv, av, khi * groups, 0)
    acc_scr[...] = acc_scr[...] * alpha + pv
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == max_pages - 1)
    def _finish():
        # a fully-masked row (inactive slot, seq_len 0) never raises the
        # running max off the sentinel: its p = exp(NEG_BIG - NEG_BIG) = 1
        # accumulates garbage the flash kernel tolerates only because it
        # drops padded rows — here the row IS the slot's output, so gate
        # on the max and emit zeros instead
        seen = m_scr[:, :1] > _NEG_BIG * 0.5
        o = jnp.where(seen,
                      acc_scr[...] / jnp.maximum(l_scr[:, :1], 1e-30), 0.0)
        o_ref[0] = o.astype(o_ref.dtype)


def ragged_paged_attention(q, k_pool, v_pool, page_table, seq_lens,
                           q_pos=None, scale=None, interpret=None):
    """Ragged paged-attention for decode: one query token per slot.

    q: (S, H, D); k_pool/v_pool: (P, page_size, KH, D) static pools;
    page_table: (S, max_pages) int32 page ids (unused entries MUST point
    at a valid page — the ragged mask drops them); seq_lens: (S,) int32
    tokens live per slot (0 = inactive slot, output row is zeros).
    q_pos: optional (S,) int32 — when given, the causal bound: positions
    > q_pos[s] are masked even if < seq_lens[s] (decode passes None: the
    new token sits at seq_len - 1 and sees the whole prefix).
    H % KH == 0 (grouped-query attention: head h reads kv head h // g).

    Static in every shape — membership churn, ragged lengths and page
    reassignment never recompile. Returns (S, H, D).
    """
    from jax.experimental.pallas import tpu as pltpu

    s_slots, n_heads, d = q.shape
    n_pages_pool, page_size, n_kv, _ = k_pool.shape
    if n_heads % n_kv:
        raise ValueError("ragged_paged_attention: %d heads not divisible "
                         "by %d kv heads" % (n_heads, n_kv))
    groups = n_heads // n_kv
    max_pages = page_table.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    causal = q_pos is not None
    if interpret is None:
        interpret = _interpret()

    # heads padded to the f32 sublane tile. Pad rows are never written by
    # the per-kv-head loops (they cover exactly n_heads rows), each score
    # row's softmax state is independent, and the pad rows are sliced off
    # on return — so the padding is layout-only, not math.
    hp = _pad_up(n_heads, _PACK_ROWS)
    qp = jnp.pad(q, ((0, 0), (0, hp - n_heads), (0, 0)))
    kernel = functools.partial(
        _paged_kernel, page_size=page_size, max_pages=max_pages,
        groups=groups, scale=float(scale), causal=causal)
    pt_flat = page_table.astype(jnp.int32).ravel()
    sl = seq_lens.astype(jnp.int32)
    qpos = (q_pos.astype(jnp.int32) if causal
            else jnp.zeros_like(sl))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s_slots, max_pages),
        in_specs=[
            pl.BlockSpec((1, hp, d), lambda s, j, pt, sl, qp_: (s, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, d),
                         lambda s, j, pt, sl, qp_:
                         (pt[s * max_pages + j], 0, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, d),
                         lambda s, j, pt, sl, qp_:
                         (pt[s * max_pages + j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hp, d),
                               lambda s, j, pt, sl, qp_: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((hp, LANES), jnp.float32),
            pltpu.VMEM((hp, LANES), jnp.float32),
            pltpu.VMEM((hp, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((s_slots, hp, d), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(pt_flat, sl, qpos, qp, k_pool, v_pool)
    return out[:, :n_heads]


def paged_attention_reference(q, k_pool, v_pool, page_table, seq_lens,
                              q_pos=None, scale=None):
    """Dense jnp ragged paged attention — the kernel's parity oracle and
    the decode path on non-TPU backends (faster than interpret mode;
    gathers (S, max_pages*page_size) KV views, so it trades the kernel's
    O(page) VMEM residency for plain XLA gathers)."""
    s_slots, n_heads, d = q.shape
    _, page_size, n_kv, _ = k_pool.shape
    groups = n_heads // n_kv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    t = page_table.shape[1] * page_size
    # (S, max_pages, page_size, KH, D) -> (S, T, KH, D)
    k = k_pool[page_table].reshape(s_slots, t, n_kv, d)
    v = v_pool[page_table].reshape(s_slots, t, n_kv, d)
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    scores = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jnp.arange(t, dtype=jnp.int32)
    valid = pos[None, :] < seq_lens.astype(jnp.int32)[:, None]
    if q_pos is not None:
        valid = valid & (pos[None, :] <= q_pos.astype(jnp.int32)[:, None])
    scores = jnp.where(valid[:, None, :], scores, _NEG_BIG)
    any_valid = valid.any(axis=1)[:, None, None]
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("sht,sthd->shd", p, v.astype(jnp.float32))
    return jnp.where(any_valid, out, 0.0).astype(q.dtype)


def paged_prefill_attention(q, k_pool, v_pool, page_row, start, length,
                            scale=None):
    """Chunked-prefill attention: C chunk queries of ONE sequence attend
    over that sequence's pages (the prior prefix written by earlier
    chunks/shared prefix pages AND the chunk's own rows, which the model
    scatters into the pool before calling this).

    q: (C, H, D) — the chunk's queries at absolute positions
    ``start .. start+C-1``; page_row: (max_pages,) int32, the sequence's
    page-table row; start/length: traced int32 scalars — ``length`` is
    the chunk's real token count (padding rows beyond it come back
    zeroed). Reuses the decode kernel by treating each chunk token as
    its own grid row sharing one page table — every shape is static in
    (C, max_pages, page_size), so one compile serves every chunk of a
    rung no matter where it starts. Returns (C, H, D).
    """
    c = q.shape[0]
    idx = jnp.arange(c, dtype=jnp.int32)
    q_pos = start.astype(jnp.int32) + idx
    # query i sees positions <= start+i (causal), padding rows see nothing
    seq_lens = jnp.where(idx < length, q_pos + 1, 0).astype(jnp.int32)
    pt = jnp.broadcast_to(page_row.astype(jnp.int32)[None, :],
                          (c, page_row.shape[0]))
    return paged_attention(q, k_pool, v_pool, pt, seq_lens, q_pos=q_pos,
                           scale=scale)


def paged_attention(q, k_pool, v_pool, page_table, seq_lens, q_pos=None,
                    scale=None):
    """Dispatcher the decode engine traces: the Pallas kernel on TPU (when
    the pool meets the (8, 128) tiling), the jnp reference elsewhere —
    same math, tested for parity in interpret mode."""
    page_size = k_pool.shape[1]
    d = k_pool.shape[3]
    if jax.default_backend() == "tpu" and page_size % 8 == 0 \
            and d % LANES == 0:
        return ragged_paged_attention(q, k_pool, v_pool, page_table,
                                      seq_lens, q_pos=q_pos, scale=scale,
                                      interpret=False)
    return paged_attention_reference(q, k_pool, v_pool, page_table,
                                     seq_lens, q_pos=q_pos, scale=scale)


def _paged_spec_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                       m_scr, l_scr, acc_scr, *, page_size, max_pages,
                       groups, width, hp, scale):
    """One (slot, page) cell of multi-query ragged paged attention — the
    speculative verify tick: each slot carries ``width`` = K+1 query rows
    (last committed token + up to K draft tokens) instead of one.

    q_ref: (1, width*Hp, D) with row layout ``row = w*Hp + h`` (each
    query's heads contiguous, so the per-kv-head slices of the decode
    kernel still work per w); k_ref/v_ref: (1, page_size, KH, D);
    o_ref: (1, width*Hp, D). Scratch m/l: (width*Hp, LANES), acc:
    (width*Hp, D). sl_ref is (S*width,): per-ROW seq_lens — query w of
    slot s sits at position sl[s*width+w]-1 and sees everything below
    it, so the ragged mask alone encodes causality between draft rows
    (no q_pos operand needed; a padded row carries seq_len 0 and emits
    zeros exactly like an inactive slot in the single-query kernel).
    """
    s = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)            # (width*Hp, D)
    k = k_ref[0].astype(jnp.float32)            # (page_size, KH, D)
    v = v_ref[0].astype(jnp.float32)
    whp = q.shape[0]
    kh = k.shape[1]

    # scores (width*Hp, page_size): within each w block, head h attends
    # kv head h // groups — width*kh small unrolled 2D matmuls.
    scores = jnp.zeros((whp, page_size), jnp.float32)
    for w in range(width):
        for khi in range(kh):
            row0 = w * hp + khi * groups
            qh = lax.dynamic_slice_in_dim(q, row0, groups, 0)
            sk = jax.lax.dot_general(qh, k[:, khi, :],
                                     (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            scores = lax.dynamic_update_slice_in_dim(scores, sk, row0, 0)
    scores = scores * scale

    # per-row ragged mask: row w's length is sl[s*width + w]. The w of a
    # row is its index // hp — build the (whp, 1) length column by an
    # unrolled select over the width scalar-prefetch entries.
    row_w = lax.broadcasted_iota(jnp.int32, (whp, 1), 0) // hp
    sl_rows = jnp.zeros((whp, 1), jnp.int32)
    for w in range(width):
        sl_rows = jnp.where(row_w == w, sl_ref[s * width + w], sl_rows)
    pos = j * page_size + lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
    valid = pos < sl_rows
    scores = jnp.where(valid, scores, _NEG_BIG)

    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)
    l_new = alpha * l_prev + p.sum(axis=-1, keepdims=True)
    pv = jnp.zeros_like(acc_scr[...])
    for w in range(width):
        for khi in range(kh):
            row0 = w * hp + khi * groups
            ph = lax.dynamic_slice_in_dim(p, row0, groups, 0)
            av = jax.lax.dot_general(ph, v[:, khi, :],
                                     (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            pv = lax.dynamic_update_slice_in_dim(pv, av, row0, 0)
    acc_scr[...] = acc_scr[...] * alpha + pv
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == max_pages - 1)
    def _finish():
        # same fully-masked-row gate as the single-query kernel: a padded
        # draft row (seq_len 0) is the row's OWN output — emit zeros.
        seen = m_scr[:, :1] > _NEG_BIG * 0.5
        o = jnp.where(seen,
                      acc_scr[...] / jnp.maximum(l_scr[:, :1], 1e-30), 0.0)
        o_ref[0] = o.astype(o_ref.dtype)


def ragged_spec_attention(q, k_pool, v_pool, page_table, seq_lens,
                          scale=None, interpret=None):
    """Multi-query ragged paged attention — the speculative verify step.

    q: (S, W, H, D) — W = K+1 query rows per slot (committed token +
    drafts, in position order); page_table: (S, max_pages) — ONE row per
    slot, shared by its W queries (speculation widens queries, not KV
    residency); seq_lens: (S*W,) int32, PER ROW: row w of slot s has
    seq_len = its absolute position + 1, so each draft row attends the
    committed prefix plus the earlier draft rows already written below
    it, and a padded/inactive row carries 0 and returns zeros.

    Shapes are static in (S, W, max_pages, page_size): speculation depth
    and per-slot acceptance vary the seq_lens DATA only — membership
    churn, rejection, ragged drafts never recompile. Returns (S, W, H, D).
    """
    from jax.experimental.pallas import tpu as pltpu

    s_slots, width, n_heads, d = q.shape
    n_pages_pool, page_size, n_kv, _ = k_pool.shape
    if n_heads % n_kv:
        raise ValueError("ragged_spec_attention: %d heads not divisible "
                         "by %d kv heads" % (n_heads, n_kv))
    if seq_lens.shape[0] != s_slots * width:
        raise ValueError("ragged_spec_attention: seq_lens %s != S*W = %d"
                         % (seq_lens.shape, s_slots * width))
    groups = n_heads // n_kv
    max_pages = page_table.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _interpret()

    hp = _pad_up(n_heads, _PACK_ROWS)
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, hp - n_heads), (0, 0)))
    qp = qp.reshape(s_slots, width * hp, d)
    kernel = functools.partial(
        _paged_spec_kernel, page_size=page_size, max_pages=max_pages,
        groups=groups, width=width, hp=hp, scale=float(scale))
    pt_flat = page_table.astype(jnp.int32).ravel()
    sl = seq_lens.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_slots, max_pages),
        in_specs=[
            pl.BlockSpec((1, width * hp, d), lambda s, j, pt, sl: (s, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, d),
                         lambda s, j, pt, sl:
                         (pt[s * max_pages + j], 0, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, d),
                         lambda s, j, pt, sl:
                         (pt[s * max_pages + j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, width * hp, d),
                               lambda s, j, pt, sl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((width * hp, LANES), jnp.float32),
            pltpu.VMEM((width * hp, LANES), jnp.float32),
            pltpu.VMEM((width * hp, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((s_slots, width * hp, d), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(pt_flat, sl, qp, k_pool, v_pool)
    return out.reshape(s_slots, width, hp, d)[:, :, :n_heads]


def paged_spec_attention_reference(q, k_pool, v_pool, page_table, seq_lens,
                                   scale=None):
    """Dense oracle for the multi-query verify step: each of a slot's W
    query rows is treated as its own single-query slot sharing the slot's
    page-table row — the chunked-prefill broadcast-row trick, with the
    per-row seq_lens carrying causality. q: (S*W, H, D), page_table:
    (S, max_pages), seq_lens: (S*W,). Returns (S*W, H, D)."""
    s_rows = q.shape[0]
    width = s_rows // page_table.shape[0]
    pt = jnp.repeat(page_table.astype(jnp.int32), width, axis=0)
    return paged_attention_reference(q, k_pool, v_pool, pt, seq_lens,
                                     scale=scale)


def paged_spec_attention(q, k_pool, v_pool, page_table, seq_lens,
                         scale=None):
    """Dispatcher for the widened (speculative) decode step: q is the
    flattened (S*W, H, D) query block — W derived from the page-table row
    count at trace time, so the engine's model code needs no signature
    change. Pallas kernel on TPU (same tiling bar as `paged_attention`),
    dense reference elsewhere."""
    s_slots = page_table.shape[0]
    width = q.shape[0] // s_slots
    page_size = k_pool.shape[1]
    d = k_pool.shape[3]
    if jax.default_backend() == "tpu" and page_size % 8 == 0 \
            and d % LANES == 0:
        out = ragged_spec_attention(
            q.reshape(s_slots, width, q.shape[1], q.shape[2]),
            k_pool, v_pool, page_table, seq_lens, scale=scale,
            interpret=False)
        return out.reshape(q.shape)
    return paged_spec_attention_reference(q, k_pool, v_pool, page_table,
                                          seq_lens, scale=scale)


def _register_flash_attention_op():
    """Expose the kernel through the op registry:
    ``_contrib_flash_attention(query, key, value)`` on (B, H, S, D)."""
    from .registry import register

    @register("_contrib_flash_attention",
              params={"scale": (float, None), "causal": (bool, False)},
              inputs=("query", "key", "value"),
              aliases=("flash_attention",))
    def _op(attrs, q, k, v):
        return flash_attention(q, k, v, attrs.scale, attrs.causal)


_register_flash_attention_op()
