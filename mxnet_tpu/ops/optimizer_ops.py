"""Fused optimizer-update operators.

Reference: ``src/operator/optimizer_op.cc`` — the reference registers every
optimizer step as a fused engine op (sgd_update, sgd_mom_update,
mp_sgd*_update multi-precision, adam_update, ftml/ftrl/rmsprop/
rmspropalex, signsgd/signum, _sparse_adagrad_update) that the Python
``Optimizer`` fast path invokes. Here the same names are registered as
functional ops: state-carrying variants return ``(weight', state'...)``
(XLA is functional — in-place mutation is expressed by invoking with
``out=`` / rebinding, and the jitted ``Optimizer.pure_step`` path fuses the
whole update anyway). Math matches the reference kernels; tests assert
parity against :mod:`mxnet_tpu.optimizer`.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import REQUIRED, register

__all__ = []


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


def _prep_wd(grad, weight, attrs, clip=None):
    """adam/ftml/rmsprop/rmspropalex fold weight decay into the gradient
    BEFORE clipping (reference optimizer_op-inl.h AdamUpdate ~:858,
    FTMLKernel :761, RMSProp*/~:1157-1260): g = clip(rescale*grad + wd*w).
    The sgd family clips first and applies wd outside — see _prep callers."""
    g = grad * attrs.rescale_grad + attrs.wd * weight
    clip = attrs.clip_gradient if clip is None else clip
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


_COMMON = {
    "lr": (float, REQUIRED),
    "wd": (float, 0.0),
    "rescale_grad": (float, 1.0),
    "clip_gradient": (float, -1.0),
}


@register("sgd_update", params=dict(_COMMON, lazy_update=(bool, True)),
          inputs=("weight", "grad"))
def _sgd_update(attrs, weight, grad):
    g = _prep(grad, attrs.rescale_grad, attrs.clip_gradient)
    return weight - attrs.lr * (g + attrs.wd * weight)


@register("sgd_mom_update",
          params=dict(_COMMON, momentum=(float, 0.0), lazy_update=(bool, True)),
          inputs=("weight", "grad", "mom"), num_outputs=2)
def _sgd_mom_update(attrs, weight, grad, mom):
    g = _prep(grad, attrs.rescale_grad, attrs.clip_gradient)
    mom_new = attrs.momentum * mom - attrs.lr * (g + attrs.wd * weight)
    return weight + mom_new, mom_new


@register("mp_sgd_update", params=dict(_COMMON, lazy_update=(bool, True)),
          inputs=("weight", "grad", "weight32"), num_outputs=2)
def _mp_sgd_update(attrs, weight, grad, weight32):
    """Multi-precision: master fp32 weights updated from low-precision
    grads (reference optimizer_op.cc mp_sgd_update)."""
    g = _prep(grad.astype(jnp.float32), attrs.rescale_grad, attrs.clip_gradient)
    w32 = weight32 - attrs.lr * (g + attrs.wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update",
          params=dict(_COMMON, momentum=(float, 0.0), lazy_update=(bool, True)),
          inputs=("weight", "grad", "mom", "weight32"), num_outputs=3)
def _mp_sgd_mom_update(attrs, weight, grad, mom, weight32):
    g = _prep(grad.astype(jnp.float32), attrs.rescale_grad, attrs.clip_gradient)
    mom_new = attrs.momentum * mom - attrs.lr * (g + attrs.wd * weight32)
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@register("adam_update",
          params=dict(_COMMON, beta1=(float, 0.9), beta2=(float, 0.999),
                      epsilon=(float, 1e-8), lazy_update=(bool, True)),
          inputs=("weight", "grad", "mean", "var"), num_outputs=3)
def _adam_update(attrs, weight, grad, mean, var):
    g = _prep_wd(grad, weight, attrs)
    m = attrs.beta1 * mean + (1 - attrs.beta1) * g
    v = attrs.beta2 * var + (1 - attrs.beta2) * g * g
    w = weight - attrs.lr * m / (jnp.sqrt(v) + attrs.epsilon)
    return w, m, v


@register("ftml_update",
          params=dict(_COMMON, beta1=(float, 0.6), beta2=(float, 0.999),
                      epsilon=(float, 1e-8), t=(int, REQUIRED),
                      clip_grad=(float, -1.0)),
          inputs=("weight", "grad", "d", "v", "z"), num_outputs=4)
def _ftml_update(attrs, weight, grad, d, v, z):
    clip = attrs.clip_grad if attrs.clip_grad > 0 else attrs.clip_gradient
    g = _prep_wd(grad, weight, attrs, clip=clip)
    t = attrs.t
    v_new = attrs.beta2 * v + (1 - attrs.beta2) * g * g
    d_new = (1 - attrs.beta1 ** t) / attrs.lr * (
        jnp.sqrt(v_new / (1 - attrs.beta2 ** t)) + attrs.epsilon)
    sigma = d_new - attrs.beta1 * d
    z_new = attrs.beta1 * z + (1 - attrs.beta1) * g - sigma * weight
    w = -z_new / d_new
    return w, d_new, v_new, z_new


@register("ftrl_update",
          params=dict(_COMMON, lamda1=(float, 0.01), beta=(float, 1.0)),
          inputs=("weight", "grad", "z", "n"), num_outputs=3)
def _ftrl_update(attrs, weight, grad, z, n):
    g = _prep(grad, attrs.rescale_grad, attrs.clip_gradient)
    n_new = n + g * g
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / attrs.lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) <= attrs.lamda1,
        jnp.zeros_like(weight),
        -(z_new - jnp.sign(z_new) * attrs.lamda1)
        / ((attrs.beta + jnp.sqrt(n_new)) / attrs.lr + attrs.wd))
    return w, z_new, n_new


@register("rmsprop_update",
          params=dict(_COMMON, gamma1=(float, 0.95), epsilon=(float, 1e-8)),
          inputs=("weight", "grad", "n"), num_outputs=2)
def _rmsprop_update(attrs, weight, grad, n):
    g = _prep_wd(grad, weight, attrs)
    n_new = attrs.gamma1 * n + (1 - attrs.gamma1) * g * g
    w = weight - attrs.lr * g / jnp.sqrt(n_new + attrs.epsilon)
    return w, n_new


@register("rmspropalex_update",
          params=dict(_COMMON, gamma1=(float, 0.95), gamma2=(float, 0.9),
                      epsilon=(float, 1e-8)),
          inputs=("weight", "grad", "n", "g", "delta"), num_outputs=4)
def _rmspropalex_update(attrs, weight, grad, n, g_state, delta):
    g = _prep_wd(grad, weight, attrs)
    n_new = attrs.gamma1 * n + (1 - attrs.gamma1) * g * g
    g_new = attrs.gamma1 * g_state + (1 - attrs.gamma1) * g
    delta_new = attrs.gamma2 * delta - attrs.lr * g / jnp.sqrt(
        n_new - g_new * g_new + attrs.epsilon)
    return weight + delta_new, n_new, g_new, delta_new


@register("signsgd_update", params=dict(_COMMON),
          inputs=("weight", "grad"))
def _signsgd_update(attrs, weight, grad):
    g = _prep(grad, attrs.rescale_grad, attrs.clip_gradient)
    return weight - attrs.lr * (jnp.sign(g) + attrs.wd * weight)


@register("signum_update",
          params=dict(_COMMON, momentum=(float, 0.0),
                      wd_lh=(float, 0.0)),
          inputs=("weight", "grad", "mom"), num_outputs=2)
def _signum_update(attrs, weight, grad, mom):
    g = _prep(grad, attrs.rescale_grad, attrs.clip_gradient)
    mom_new = attrs.momentum * mom - (1 - attrs.momentum) * (
        g + attrs.wd * weight)
    w = (1 - attrs.lr * attrs.wd_lh) * weight + attrs.lr * jnp.sign(mom_new)
    return w, mom_new


@register("_sparse_adagrad_update",
          params=dict(_COMMON, epsilon=(float, 1e-7)),
          inputs=("weight", "grad", "history"), num_outputs=2,
          aliases=("adagrad_update",))
def _sparse_adagrad_update(attrs, weight, grad, history):
    """AdaGrad with implicit row sparsity (reference optimizer_op.cc
    _sparse_adagrad_update): rows with all-zero gradient are untouched —
    history and weight stay exactly as before for those rows, the lazy
    sparse-update contract."""
    g = _prep(grad, attrs.rescale_grad, attrs.clip_gradient)
    if g.ndim >= 2:
        row_active = jnp.any(g != 0, axis=tuple(range(1, g.ndim)),
                             keepdims=True)
    else:
        row_active = g != 0
    hist_new = jnp.where(row_active, history + g * g, history)
    upd = attrs.lr * (g / (jnp.sqrt(hist_new) + attrs.epsilon)
                      + attrs.wd * weight)
    w = jnp.where(row_active, weight - upd, weight)
    return w, hist_new
