"""Operator registry package. Importing this populates OP_REGISTRY with the
full op library (counterpart of the reference's static NNVM_REGISTER_OP
initializers across `src/operator/`)."""
from .registry import OP_REGISTRY, OpDef, AttrDict, get_op, list_ops, register, REQUIRED

from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import linalg  # noqa: F401
from . import control_flow  # noqa: F401
from . import detection  # noqa: F401
from . import quantization  # noqa: F401
from . import vision  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sparse  # noqa: F401
from . import misc_tail  # noqa: F401

__all__ = ["OP_REGISTRY", "OpDef", "AttrDict", "get_op", "list_ops", "register", "REQUIRED"]

# the Custom-op bridge registers the "Custom" op; import it here so the
# nd/sym wrapper generation (which runs right after `ops`) picks it up
from .. import operator as _custom_bridge  # noqa: E402,F401
