"""Operator registry package. Importing this populates OP_REGISTRY with the
full op library (counterpart of the reference's static NNVM_REGISTER_OP
initializers across `src/operator/`)."""
from .registry import OP_REGISTRY, OpDef, AttrDict, get_op, list_ops, register, REQUIRED

from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import linalg  # noqa: F401
from . import control_flow  # noqa: F401
from . import detection  # noqa: F401

__all__ = ["OP_REGISTRY", "OpDef", "AttrDict", "get_op", "list_ops", "register", "REQUIRED"]
