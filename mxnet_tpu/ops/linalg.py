"""Linear-algebra ops (reference `src/operator/tensor/la_op.cc`,
`c_lapack_api.h`): _linalg_{gemm,gemm2,potrf,potri,trmm,trsm,sumlogdiag,
syrk,syevd,gelqf,...}. LAPACK calls become jax.numpy.linalg / lax.linalg,
which XLA lowers to MXU-friendly blocked kernels."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import REQUIRED, register


def _t(x, transpose):
    return jnp.swapaxes(x, -1, -2) if transpose else x


@register(
    "_linalg_gemm",
    params={
        "transpose_a": (bool, False),
        "transpose_b": (bool, False),
        "alpha": (float, 1.0),
        "beta": (float, 1.0),
        "axis": (int, -2),
    },
    inputs=("A", "B", "C"),
    aliases=("linalg_gemm",),
)
def linalg_gemm(attrs, a, b, c):
    return attrs.alpha * jnp.matmul(_t(a, attrs.transpose_a), _t(b, attrs.transpose_b)) + attrs.beta * c


@register(
    "_linalg_gemm2",
    params={
        "transpose_a": (bool, False),
        "transpose_b": (bool, False),
        "alpha": (float, 1.0),
        "axis": (int, -2),
    },
    inputs=("A", "B"),
    aliases=("linalg_gemm2",),
)
def linalg_gemm2(attrs, a, b):
    return attrs.alpha * jnp.matmul(_t(a, attrs.transpose_a), _t(b, attrs.transpose_b))


@register("_linalg_potrf", inputs=("A",), aliases=("linalg_potrf",))
def linalg_potrf(attrs, a):
    return jnp.linalg.cholesky(a)


@register("_linalg_potri", inputs=("A",), aliases=("linalg_potri",))
def linalg_potri(attrs, a):
    """Inverse of the SPD matrix whose Cholesky factor is A (reference potri)."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register(
    "_linalg_trmm",
    params={"transpose": (bool, False), "rightside": (bool, False), "lower": (bool, True), "alpha": (float, 1.0)},
    inputs=("A", "B"),
    aliases=("linalg_trmm",),
)
def linalg_trmm(attrs, a, b):
    at = _t(a, attrs.transpose)
    out = jnp.matmul(b, at) if attrs.rightside else jnp.matmul(at, b)
    return attrs.alpha * out


@register(
    "_linalg_trsm",
    params={"transpose": (bool, False), "rightside": (bool, False), "lower": (bool, True), "alpha": (float, 1.0)},
    inputs=("A", "B"),
    aliases=("linalg_trsm",),
)
def linalg_trsm(attrs, a, b):
    lower = attrs.lower != attrs.transpose
    if attrs.rightside:
        # solve X A^T' = alpha B  ->  A' X^T = alpha B^T
        xt = jax.scipy.linalg.solve_triangular(
            _t(a, not attrs.transpose), _t(attrs.alpha * b, True), lower=not lower
        )
        return _t(xt, True)
    return jax.scipy.linalg.solve_triangular(_t(a, attrs.transpose), attrs.alpha * b, lower=lower)


@register("_linalg_sumlogdiag", inputs=("A",), aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(attrs, a):
    return jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)), axis=-1)


@register(
    "_linalg_syrk",
    params={"transpose": (bool, False), "alpha": (float, 1.0)},
    inputs=("A",),
    aliases=("linalg_syrk",),
)
def linalg_syrk(attrs, a):
    at = _t(a, True)
    return attrs.alpha * (jnp.matmul(at, a) if attrs.transpose else jnp.matmul(a, at))


@register("_linalg_syevd", inputs=("A",), num_outputs=2, aliases=("linalg_syevd",))
def linalg_syevd(attrs, a):
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("_linalg_gelqf", inputs=("A",), num_outputs=2, aliases=("linalg_gelqf",))
def linalg_gelqf(attrs, a):
    """LQ factorization A = L Q with Q orthonormal rows (reference gelqf)."""
    q, r = jnp.linalg.qr(_t(a, True))
    return _t(r, True), _t(q, True)


@register("_linalg_makediag", params={"offset": (int, 0)}, inputs=("A",), aliases=("linalg_makediag",))
def linalg_makediag(attrs, a):
    k = attrs.offset
    n = a.shape[-1] + abs(k)
    base = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
    idx = jnp.arange(a.shape[-1])
    rows = idx - min(k, 0)
    cols = idx + max(k, 0)
    return base.at[..., rows, cols].set(a)


@register("_linalg_extractdiag", params={"offset": (int, 0)}, inputs=("A",), aliases=("linalg_extractdiag",))
def linalg_extractdiag(attrs, a):
    return jnp.diagonal(a, offset=attrs.offset, axis1=-2, axis2=-1)


@register("_linalg_inverse", inputs=("A",), aliases=("linalg_inverse",))
def linalg_inverse(attrs, a):
    return jnp.linalg.inv(a)


@register("_linalg_det", inputs=("A",), aliases=("linalg_det",))
def linalg_det(attrs, a):
    return jnp.linalg.det(a)


@register("_linalg_slogdet", inputs=("A",), num_outputs=2, aliases=("linalg_slogdet",))
def linalg_slogdet(attrs, a):
    sign, logdet = jnp.linalg.slogdet(a)
    return sign, logdet
