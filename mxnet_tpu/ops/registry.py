"""Operator registry — the TPU-native counterpart of the reference's NNVM op
registry (`NNVM_REGISTER_OP` + `FCompute`/`FInferShape` attributes, see
reference `include/mxnet/op_attr_types.h:198-281`).

Design: every op registers
  * a ``fcompute(attrs, *inputs) -> output | tuple`` implemented with
    jax.numpy / lax — traced eagerly for NDArray calls, and traced into one
    XLA HloModule when invoked inside a jitted Symbol executor or CachedOp;
  * a typed parameter spec (counterpart of dmlc::Parameter reflection) so
    string attrs from MXNet-format symbol JSON round-trip losslessly;
  * input argument names for Symbol composition (list_arguments parity).

Gradients are NOT hand-registered per op: autograd uses jax.vjp over
fcompute, which is exactly the whole-graph XLA gradient the reference
builds via its nnvm Gradient pass (`src/executor/graph_executor.cc:231-295`).
Ops needing custom backward semantics (e.g. SoftmaxOutput) wrap their
fcompute in jax.custom_vjp themselves.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, parser_for

__all__ = ["OpDef", "register", "register_ex", "get_op", "list_ops",
           "AttrDict", "OP_REGISTRY"]

OP_REGISTRY: Dict[str, "OpDef"] = {}


class AttrDict(dict):
    """Parsed op attributes with attribute access (`attrs.kernel`)."""

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k)


class OpDef:
    """One registered operator.

    Parameters
    ----------
    name : canonical MXNet-compatible op name (e.g. "FullyConnected").
    fcompute : callable(attrs: AttrDict, *inputs) -> jnp array or tuple.
    params : dict attr_name -> (type, default). type is one of
        bool/int/float/tuple/str/'dtype' or a callable parser. default
        ``REQUIRED`` marks mandatory attrs.
    inputs : list of input names, or a callable(attrs)->list for ops whose
        arity depends on attrs (e.g. Concat's num_args, no_bias).
    num_outputs : int or callable(attrs)->int.
    """

    REQUIRED = object()

    def __init__(
        self,
        name: str,
        fcompute: Callable,
        params: Optional[Dict[str, Tuple[Any, Any]]] = None,
        inputs: Any = ("data",),
        num_outputs: Any = 1,
        aliases: Sequence[str] = (),
        doc: str = "",
    ):
        self.name = name
        self.fcompute = fcompute
        self.params = params or {}
        self._inputs = inputs
        self._num_outputs = num_outputs
        self.aliases = tuple(aliases)
        self.doc = doc
        self._attr_cache: Dict[Any, "AttrDict"] = {}
        # Storage-type dispatch (the reference's FComputeEx,
        # op_attr_types.h:229): when set, invoke() routes calls with sparse
        # NDArray inputs (or dispatch_ex_always ops) here. The ex kernel
        # receives SparseRep views for sparse inputs and may return SparseRep
        # outputs. ex_differentiable marks ex kernels whose outputs are dense
        # arrays differentiable w.r.t. their dense inputs (sparse inputs get
        # grad_req=null, matching the reference's sparse dot).
        self.fcompute_ex: Optional[Callable] = None
        self.dispatch_ex_always = False
        self.ex_differentiable = False
        # True when the dense FCompute is a full equivalent, so autograd
        # recording may fall back to it for taping (ops whose dense stub
        # raises, e.g. _sparse_retain, must never take that fallback)
        self.ex_grad_fallback = False

    # ------------------------------------------------------------------
    def input_names(self, attrs: Optional[AttrDict] = None) -> List[str]:
        if callable(self._inputs):
            return list(self._inputs(attrs or self.parse_attrs({})))
        return list(self._inputs)

    def num_outputs(self, attrs: Optional[AttrDict] = None) -> int:
        if callable(self._num_outputs):
            return int(self._num_outputs(attrs or self.parse_attrs({})))
        return int(self._num_outputs)

    def parse_attrs(self, raw: Dict[str, Any]) -> AttrDict:
        """Parse raw (possibly string-valued) attrs into typed values,
        applying defaults and validating required fields.

        Results are memoized per attr signature (eager dispatch calls this
        on every op invocation with a handful of distinct signatures); a
        shallow copy is returned so callers may mutate their view.
        """
        # only primitive-valued signatures are cacheable: object-valued attrs
        # (e.g. control-flow subgraph Symbols) have identity hashes but
        # overloaded __eq__, which a dict collision would misinterpret
        key = None
        if all(isinstance(v, (str, int, float, bool, tuple, type(None)))
               for v in raw.values()):
            try:
                key = tuple(sorted(raw.items()))
                hash(key)
            except TypeError:
                key = None
        if key is not None:
            cached = self._attr_cache.get(key)
            if cached is not None:
                return AttrDict(cached)
        out = self._parse_attrs_uncached(raw)
        if key is not None:
            if len(self._attr_cache) > 256:  # bound per-op memory
                self._attr_cache.clear()
            self._attr_cache[key] = AttrDict(out)
        return out

    def _parse_attrs_uncached(self, raw: Dict[str, Any]) -> AttrDict:
        out = AttrDict()
        for pname, (ptype, pdefault) in self.params.items():
            if pname in raw:
                v = raw[pname]
                if v is None or (isinstance(v, str) and v == "None"):
                    # explicit None on an optional attr = "unset" (reference
                    # dmlc::optional<T> accepts the string "None")
                    if pdefault is not OpDef.REQUIRED:
                        out[pname] = pdefault
                    else:
                        raise MXNetError(
                            "op %s: required attribute %r is None"
                            % (self.name, pname))
                elif isinstance(v, str) or ptype in (bool, int, float, tuple) or isinstance(ptype, str):
                    out[pname] = parser_for(ptype)(v)
                else:
                    out[pname] = v
            elif pdefault is OpDef.REQUIRED:
                raise MXNetError(
                    "op %s: required attribute %r missing" % (self.name, pname)
                )
            else:
                out[pname] = pdefault
        # keep unknown attrs verbatim (forward/JSON compat)
        for k, v in raw.items():
            if k not in out and not k.startswith("__"):
                out[k] = v
        return out

    def serialize_attrs(self, attrs: Dict[str, Any]) -> Dict[str, str]:
        """Stringify attrs for MXNet-format symbol JSON."""
        out = {}
        for k, v in attrs.items():
            if k not in self.params:
                continue
            ptype, pdefault = self.params[k]
            if v is None and pdefault is None:
                continue
            if ptype == "dtype" and v is not None:
                from ..base import dtype_name

                out[k] = dtype_name(v)
            elif hasattr(v, "tojson"):
                # subgraph attrs (control-flow ops) nest their graph JSON
                out[k] = v.tojson()
            elif isinstance(v, (tuple, list)):
                if v and all(isinstance(x, str) for x in v):
                    out[k] = ",".join(v)  # name lists (control-flow ops)
                else:
                    # ints print as ints (shape compat); floats keep their
                    # value (detection sizes/ratios/variances). () round-trips
                    out[k] = "(" + ", ".join(
                        str(int(x)) if float(x).is_integer() else repr(float(x))
                        for x in v) + ")"
            else:
                out[k] = str(v)
        return out

    def __call__(self, attrs: AttrDict, *inputs):
        return self.fcompute(attrs, *inputs)

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(
    name: str,
    params: Optional[Dict[str, Tuple[Any, Any]]] = None,
    inputs: Any = ("data",),
    num_outputs: Any = 1,
    aliases: Sequence[str] = (),
):
    """Decorator registering ``fcompute`` under ``name`` (+aliases)."""

    def deco(fn: Callable) -> Callable:
        opdef = OpDef(
            name,
            fn,
            params=params,
            inputs=inputs,
            num_outputs=num_outputs,
            aliases=aliases,
            doc=fn.__doc__ or "",
        )
        if name in OP_REGISTRY:
            raise MXNetError("op %r registered twice" % name)
        OP_REGISTRY[name] = opdef
        for a in aliases:
            OP_REGISTRY.setdefault(a, opdef)
        return fn

    return deco


def register_ex(name: str, always: bool = False, differentiable: bool = False,
                grad_fallback: bool = False):
    """Attach an FComputeEx kernel to an already-registered op (the
    reference registers FCompute and FComputeEx as separate attributes on
    one NNVM op, e.g. dot's DotForwardEx in dot-inl.h). ``grad_fallback``
    marks ops whose dense FCompute is a full equivalent, letting autograd
    recording tape through the dense path instead."""

    def deco(fn: Callable) -> Callable:
        opdef = get_op(name)
        opdef.fcompute_ex = fn
        opdef.dispatch_ex_always = always
        opdef.ex_differentiable = differentiable
        opdef.ex_grad_fallback = grad_fallback
        return fn

    return deco


def get_op(name: str) -> OpDef:
    try:
        return OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("operator %r is not registered" % (name,))


def list_ops() -> List[str]:
    return sorted(OP_REGISTRY.keys())


REQUIRED = OpDef.REQUIRED
