"""Registry tail: the last reference registration sites without a
counterpart here — transformer scaling (``src/operator/contrib/
transformer.cc:33 _contrib_div_sqrt_dim``), the tutorial op
(``contrib/quadratic_op.cc``), functional slice assignment
(``src/operator/tensor/matrix_op.cc _slice_assign``), storage-preserving
scatter scalar ops, copy aliases, and the opencv-named image ops
(``src/operator/image/image_random.cc``, ``src/io/image_io.cc``) —
implemented on PIL/jax.image since OpenCV is not in the image.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OP_REGISTRY, REQUIRED, register, register_ex
from .sparse import SparseRep

__all__ = []


@register("_contrib_div_sqrt_dim")
def _div_sqrt_dim(attrs, x):
    """out = data / sqrt(data.shape[-1]) — attention-score scaling
    (reference transformer.cc:33)."""
    return x / jnp.sqrt(jnp.asarray(x.shape[-1], x.dtype))


@register("_contrib_quadratic",
          params={"a": (float, 0.0), "b": (float, 0.0), "c": (float, 0.0)},
          aliases=("_contrib_quadratic_function",))
def _quadratic(attrs, x):
    """a*x^2 + b*x + c (reference contrib/quadratic_op.cc — the custom-op
    tutorial's example operator)."""
    return attrs.a * x * x + attrs.b * x + attrs.c


def _assign_index(attrs, ndim):
    begin = tuple(attrs.begin)
    end = tuple(attrs.end)
    step = tuple(attrs.step) if attrs.step else (1,) * len(begin)
    if len(begin) != len(end) or len(step) != len(begin):
        raise MXNetError(
            "_slice_assign: begin/end/step lengths differ (%d/%d/%d)"
            % (len(begin), len(end), len(step)))
    idx = tuple(slice(b, e, s or 1) for b, e, s in zip(begin, end, step))
    return idx + (slice(None),) * (ndim - len(idx))


@register("_slice_assign",
          params={"begin": (tuple, REQUIRED), "end": (tuple, REQUIRED),
                  "step": (tuple, ())},
          inputs=("lhs", "rhs"), aliases=("_crop_assign",))
def _slice_assign(attrs, lhs, rhs):
    """Functional slice write: lhs with lhs[begin:end:step] = rhs
    (reference matrix_op.cc _slice_assign — the __setitem__ kernel)."""
    return lhs.at[_assign_index(attrs, lhs.ndim)].set(rhs)


@register("_slice_assign_scalar",
          params={"scalar": (float, 0.0), "begin": (tuple, REQUIRED),
                  "end": (tuple, REQUIRED), "step": (tuple, ())},
          inputs=("data",), aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(attrs, data):
    return data.at[_assign_index(attrs, data.ndim)].set(attrs.scalar)


# copy aliases: one functional copy serves _copyto and the cross-device
# copy node the reference inserts for group2ctx placement (placement is
# jax.device_put at the executor layer here)
for _alias in ("_copyto", "_CrossDeviceCopy"):
    OP_REGISTRY.setdefault(_alias, OP_REGISTRY["_copy"])
# _subgraph_op registers later (mxnet_tpu.subgraph); alias added there


# ---------------------------------------------------------------------------
# storage-preserving scatter scalar ops (reference elemwise_scatter_op.cc):
# like the plain scalar ops on dense input, but on sparse storage they
# touch only the STORED elements, keeping the result sparse
# ---------------------------------------------------------------------------

def _scatter_scalar(name, fn):
    @register(name, params={"scalar": (float, 0.0)})
    def _dense(attrs, x, _fn=fn):
        return _fn(x, attrs.scalar)

    @register_ex(name)
    def _ex(attrs, x, _fn=fn):
        if not isinstance(x, SparseRep):
            return _fn(x, attrs.scalar)
        return SparseRep(x.stype, _fn(x.data, attrs.scalar), x.indices,
                         x.indptr, x.shape)


_scatter_scalar("_scatter_plus_scalar", lambda x, s: x + s)
_scatter_scalar("_scatter_minus_scalar", lambda x, s: x - s)


@register("_scatter_elemwise_div", inputs=("lhs", "rhs"))
def _scatter_elemwise_div_dense(attrs, lhs, rhs):
    return lhs / rhs


@register_ex("_scatter_elemwise_div")
def _scatter_elemwise_div_ex(attrs, lhs, rhs):
    """lhs(sparse) / rhs(dense): divides only the stored elements, result
    keeps lhs's storage (reference elemwise_scatter_op.cc)."""
    from .sparse import _densify

    if not isinstance(lhs, SparseRep):
        rhs_d = _densify(rhs) if isinstance(rhs, SparseRep) else rhs
        return lhs / rhs_d
    if isinstance(rhs, SparseRep):
        raise MXNetError("_scatter_elemwise_div expects a dense divisor")
    if lhs.stype == "row_sparse":
        denom = jnp.take(rhs, lhs.indices.astype(jnp.int32), axis=0)
    else:
        from .sparse import csr_row_ids

        rows = csr_row_ids(lhs)
        denom = rhs[rows, lhs.indices.astype(jnp.int32)]
    return SparseRep(lhs.stype, lhs.data / denom, lhs.indices, lhs.indptr,
                     lhs.shape)


# ---------------------------------------------------------------------------
# image ops (reference image_random.cc / image_io.cc; cv-prefixed names
# match the reference's OpenCV-backed registrations)
# ---------------------------------------------------------------------------

@register("_image_to_tensor", aliases=("to_tensor",))
def _image_to_tensor(attrs, x):
    """HWC [0,255] -> CHW float32 [0,1] (reference image_random.cc
    ToTensor)."""
    chw = jnp.moveaxis(x.astype(jnp.float32) / 255.0, -1, -3)
    return chw


def _float_tuple(v):
    if isinstance(v, str):
        body = v.strip().lstrip("([").rstrip(")]")
        return tuple(float(x) for x in body.split(",") if x.strip())
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


@register("_image_normalize",
          params={"mean": (_float_tuple, (0.0,)),
                  "std": (_float_tuple, (1.0,))},
          aliases=("image_normalize",))
def _image_normalize(attrs, x):
    """Per-channel (CHW) normalize: (x - mean) / std (reference
    image_random.cc Normalize)."""
    c = x.shape[-3]
    mean = jnp.asarray(attrs.mean, x.dtype)
    std = jnp.asarray(attrs.std, x.dtype)
    if mean.size == 1:
        mean = jnp.broadcast_to(mean, (c,))
    if std.size == 1:
        std = jnp.broadcast_to(std, (c,))
    shape = (c,) + (1,) * (2)
    return (x - mean.reshape(shape)) / std.reshape(shape)


@register("_cvimresize", params={"w": (int, REQUIRED), "h": (int, REQUIRED),
                                 "interp": (int, 1)},
          aliases=("imresize",))
def _cvimresize(attrs, x):
    """HWC resize (reference image_io.cc _cvimresize; jax.image in place
    of OpenCV). Integer inputs round back to the input dtype."""
    method = {0: "nearest", 1: "bilinear", 2: "cubic", 3: "bilinear",
              4: "lanczos3"}.get(attrs.interp, "bilinear")
    out = jax.image.resize(x.astype(jnp.float32),
                           (attrs.h, attrs.w, x.shape[2]), method=method)
    if jnp.issubdtype(x.dtype, jnp.integer):
        info = jnp.iinfo(x.dtype)
        out = jnp.clip(jnp.round(out), info.min, info.max).astype(x.dtype)
    return out


@register("_cvcopyMakeBorder",
          params={"top": (int, REQUIRED), "bot": (int, REQUIRED),
                  "left": (int, REQUIRED), "right": (int, REQUIRED),
                  "type": (int, 0), "value": (float, 0.0)},
          aliases=("copyMakeBorder",))
def _cv_copy_make_border(attrs, x):
    """Pad an HWC image (reference image_io.cc _cvcopyMakeBorder;
    type 0 = constant border)."""
    pads = ((attrs.top, attrs.bot), (attrs.left, attrs.right), (0, 0))
    if attrs.type == 0:  # cv2.BORDER_CONSTANT
        return jnp.pad(x, pads, constant_values=attrs.value)
    mode = {1: "edge",        # BORDER_REPLICATE
            2: "symmetric",   # BORDER_REFLECT
            3: "wrap",        # BORDER_WRAP
            4: "reflect"}.get(attrs.type)  # BORDER_REFLECT_101
    if mode is None:
        raise MXNetError("_cvcopyMakeBorder: unsupported border type %d"
                         % attrs.type)
    return jnp.pad(x, pads, mode=mode)


@register("_cvimdecode", params={"flag": (int, 1), "to_rgb": (bool, True)},
          inputs=("buf",), aliases=("imdecode_op",))
def _cvimdecode(attrs, buf):
    """JPEG/PNG decode (reference image_io.cc Imdecode). HOST op — decodes
    a uint8 byte buffer via PIL; eager-only like every decode kernel."""
    import io as _io

    from PIL import Image

    raw = bytes(np.asarray(buf).astype(np.uint8).tobytes())
    img = Image.open(_io.BytesIO(raw))
    if attrs.flag == 0:
        img = img.convert("L")
    elif img.mode != "RGB":
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if not attrs.to_rgb and arr.shape[2] == 3:
        arr = arr[:, :, ::-1]
    return jnp.asarray(arr)


@register("_cvimread",
          params={"filename": (str, REQUIRED), "flag": (int, 1),
                  "to_rgb": (bool, True)}, inputs=())
def _cvimread(attrs, ):
    """Read + decode an image file (reference image_io.cc Imread) — host
    op, eager-only."""
    with open(attrs.filename, "rb") as f:
        raw = np.frombuffer(f.read(), np.uint8).copy()
    return _cvimdecode(attrs, jnp.asarray(raw))
