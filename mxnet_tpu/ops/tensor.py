"""Tensor op library: elementwise / broadcast / scalar / reduction / matrix /
indexing / init / ordering ops.

Capability parity with reference `src/operator/tensor/` (elemwise_*.cc,
broadcast_reduce-inl.h, matrix_op-inl.h, indexing_op.h, dot-inl.h,
ordering_op.cc, init_op.cc — see SURVEY.md Appendix A for the name
inventory). Implementation is pure jax.numpy/lax: eager calls dispatch op-by-op
through XLA; symbolic executors trace these same functions into one HloModule,
which subsumes the reference's mshadow kernel + Kernel<OP,xpu>::Launch idiom.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import OpDef, OP_REGISTRY, REQUIRED, register

# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _norm_axes(axis, ndim, exclude=False):
    if axis is None or (isinstance(axis, tuple) and len(axis) == 0):
        axes = tuple(range(ndim))
    else:
        if isinstance(axis, int):
            axis = (axis,)
        axes = tuple(sorted(a % ndim if a < 0 else a for a in axis))
    if exclude:
        axes = tuple(i for i in range(ndim) if i not in axes)
    return axes


def _reg(name, fn, params=None, inputs=("data",), num_outputs=1, aliases=()):
    opdef = OpDef(name, fn, params=params, inputs=inputs, num_outputs=num_outputs, aliases=aliases)
    if name in OP_REGISTRY:
        raise MXNetError("op %r registered twice" % name)
    OP_REGISTRY[name] = opdef
    for a in aliases:
        OP_REGISTRY.setdefault(a, opdef)


def _def_unary(name, fn, aliases=()):
    _reg(name, lambda attrs, x, _fn=fn: _fn(x), inputs=("data",), aliases=aliases)


def _def_binary(name, fn, aliases=()):
    _reg(name, lambda attrs, a, b, _fn=fn: _fn(a, b), inputs=("lhs", "rhs"), aliases=aliases)


def _def_scalar(name, fn, aliases=()):
    # output keeps the input dtype (reference elemwise_binary_scalar_op semantics)
    _reg(
        name,
        lambda attrs, a, _fn=fn: _fn(a, jnp.asarray(attrs.scalar, dtype=a.dtype)),
        params={"scalar": (float, 0.0)},
        inputs=("data",),
        aliases=aliases,
    )


# ---------------------------------------------------------------------------
# unary math (reference src/operator/tensor/elemwise_unary_op_basic.cc etc.)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "negative": jnp.negative,
    "reciprocal": jnp.reciprocal,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "rint": jnp.rint,
    "fix": jnp.trunc,  # fix == round-toward-zero; jnp.fix is deprecated in jax 0.9
    "trunc": jnp.trunc,
    "gamma": getattr(jax.scipy.special, "gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x))),
    "gammaln": jax.scipy.special.gammaln,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "softsign": jax.nn.soft_sign,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "_copy": lambda x: x,
    "ones_like": jnp.ones_like,
    "zeros_like": jnp.zeros_like,
}
for _n, _f in _UNARY.items():
    _def_unary(_n, _f)

_reg("BlockGrad", lambda attrs, x: lax.stop_gradient(x), aliases=("stop_gradient",))
def _make_loss(attrs, x):
    """Identity forward; backward emits grad_scale (optionally normalized)
    like the reference MakeLossOp (make_loss.cc: grad = grad_scale, divided
    by batch size for normalization='batch' or by the count of entries
    above valid_thresh for 'valid')."""
    scale = attrs.get("grad_scale", 1.0)
    norm = attrs.get("normalization", "null")
    valid_thresh = attrs.get("valid_thresh", 0.0)

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, x

    def bwd(res, g):
        s = jnp.asarray(scale, g.dtype)
        if norm == "batch":
            s = s / res.shape[0]
        elif norm == "valid":
            s = s / jnp.maximum(
                jnp.sum((res > valid_thresh).astype(g.dtype)), 1.0)
        return (g * s,)

    f.defvjp(fwd, bwd)
    return f(x)


_reg(
    "make_loss",
    _make_loss,
    params={"grad_scale": (float, 1.0), "valid_thresh": (float, 0.0),
            "normalization": (str, "null")},
    aliases=("MakeLoss_", "MakeLoss"),
)
_reg(
    "smooth_l1",
    lambda attrs, x: jnp.where(
        jnp.abs(x) < 1.0 / (attrs.scalar ** 2),
        0.5 * (x * attrs.scalar) ** 2,
        jnp.abs(x) - 0.5 / (attrs.scalar ** 2),
    ),
    params={"scalar": (float, 1.0)},
)
_reg(
    "clip",
    lambda attrs, x: jnp.clip(x, attrs.a_min, attrs.a_max),
    params={"a_min": (float, REQUIRED), "a_max": (float, REQUIRED)},
)
_reg(
    "Cast",
    lambda attrs, x: x.astype(attrs.dtype),
    params={"dtype": ("dtype", REQUIRED)},
    aliases=("cast",),
)


# ---------------------------------------------------------------------------
# binary elementwise + broadcast (reference elemwise_binary_op*.cc,
# elemwise_binary_broadcast_op*.cc)
# ---------------------------------------------------------------------------

def _logical_xor(a, b):
    return ((a != 0) ^ (b != 0)).astype(a.dtype)


# plain-operator forms: the jnp.<ufunc> wrappers add ~25us of eager
# dispatch per call that the __add__-style operator path skips entirely
def _op_add(a, b):
    return a + b


def _op_sub(a, b):
    return a - b


def _op_mul(a, b):
    return a * b


def _op_div(a, b):
    return a / b


_BINARY = {
    "elemwise_add": (_op_add, ("_add", "_plus", "_Plus")),
    "elemwise_sub": (_op_sub, ("_sub", "_minus", "_Minus")),
    "elemwise_mul": (_op_mul, ("_mul", "_Mul")),
    "elemwise_div": (_op_div, ("_div", "_Div")),
    "_grad_add": (_op_add, ()),
    "_mod": (jnp.mod, ("_Mod",)),
    "_power": (jnp.power, ("_Power", "pow")),
    "_hypot": (jnp.hypot, ()),
    "_maximum": (jnp.maximum, ("_Maximum",)),
    "_minimum": (jnp.minimum, ("_Minimum",)),
    "_equal": (lambda a, b: (a == b).astype(a.dtype), ("_Equal",)),
    "_not_equal": (lambda a, b: (a != b).astype(a.dtype), ("_Not_Equal",)),
    "_greater": (lambda a, b: (a > b).astype(a.dtype), ("_Greater",)),
    "_greater_equal": (lambda a, b: (a >= b).astype(a.dtype), ("_Greater_Equal",)),
    "_lesser": (lambda a, b: (a < b).astype(a.dtype), ("_Lesser",)),
    "_lesser_equal": (lambda a, b: (a <= b).astype(a.dtype), ("_Lesser_Equal",)),
    "_logical_and": (lambda a, b: ((a != 0) & (b != 0)).astype(a.dtype), ()),
    "_logical_or": (lambda a, b: ((a != 0) | (b != 0)).astype(a.dtype), ()),
    "_logical_xor": (_logical_xor, ()),
}
for _n, (_f, _al) in _BINARY.items():
    _def_binary(_n, _f, aliases=_al)

# broadcast_* family shares implementations (jnp broadcasts natively)
_BCAST = {
    "broadcast_add": _op_add,
    "broadcast_sub": _op_sub,
    "broadcast_mul": _op_mul,
    "broadcast_div": _op_div,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_hypot": jnp.hypot,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_equal": lambda a, b: (a == b).astype(a.dtype),
    "broadcast_not_equal": lambda a, b: (a != b).astype(a.dtype),
    "broadcast_greater": lambda a, b: (a > b).astype(a.dtype),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(a.dtype),
    "broadcast_lesser": lambda a, b: (a < b).astype(a.dtype),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(a.dtype),
    "broadcast_logical_and": lambda a, b: ((a != 0) & (b != 0)).astype(a.dtype),
    "broadcast_logical_or": lambda a, b: ((a != 0) | (b != 0)).astype(a.dtype),
    "broadcast_logical_xor": _logical_xor,
}
for _n, _f in _BCAST.items():
    _def_binary(_n, _f)

# scalar variants (reference elemwise_binary_scalar_op*.cc)
_SCALAR = {
    "_plus_scalar": (lambda a, s: a + s, ("_PlusScalar",)),
    "_minus_scalar": (lambda a, s: a - s, ("_MinusScalar",)),
    "_rminus_scalar": (lambda a, s: s - a, ("_RMinusScalar",)),
    "_mul_scalar": (lambda a, s: a * s, ("_MulScalar",)),
    "_div_scalar": (lambda a, s: a / s, ("_DivScalar",)),
    "_rdiv_scalar": (lambda a, s: s / a, ("_RDivScalar",)),
    "_mod_scalar": (lambda a, s: jnp.mod(a, s), ("_ModScalar",)),
    "_rmod_scalar": (lambda a, s: jnp.mod(s, a), ("_RModScalar",)),
    "_power_scalar": (lambda a, s: jnp.power(a, s), ("_PowerScalar",)),
    "_rpower_scalar": (lambda a, s: jnp.power(s, a), ("_RPowerScalar",)),
    "_maximum_scalar": (jnp.maximum, ("_MaximumScalar",)),
    "_minimum_scalar": (jnp.minimum, ("_MinimumScalar",)),
    "_hypot_scalar": (jnp.hypot, ()),
    "_equal_scalar": (lambda a, s: (a == s).astype(a.dtype), ()),
    "_not_equal_scalar": (lambda a, s: (a != s).astype(a.dtype), ()),
    "_greater_scalar": (lambda a, s: (a > s).astype(a.dtype), ()),
    "_greater_equal_scalar": (lambda a, s: (a >= s).astype(a.dtype), ()),
    "_lesser_scalar": (lambda a, s: (a < s).astype(a.dtype), ()),
    "_lesser_equal_scalar": (lambda a, s: (a <= s).astype(a.dtype), ()),
    "_logical_and_scalar": (lambda a, s: ((a != 0) & (s != 0)).astype(a.dtype), ()),
    "_logical_or_scalar": (lambda a, s: ((a != 0) | (s != 0)).astype(a.dtype), ()),
    "_logical_xor_scalar": (_logical_xor, ()),
}
for _n, (_f, _al) in _SCALAR.items():
    _def_scalar(_n, _f, aliases=_al)

_reg(
    "add_n",
    lambda attrs, *xs: sum(xs[1:], xs[0]),
    params={"num_args": (int, 1)},
    inputs=lambda attrs: ["arg%d" % i for i in range(attrs.get("num_args", 1))],
    aliases=("ElementWiseSum", "_sum"),
)


# ---------------------------------------------------------------------------
# reductions (reference broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------

_REDUCE_PARAMS = {"axis": (tuple, None), "keepdims": (bool, False), "exclude": (bool, False)}


def _def_reduce(name, fn, aliases=()):
    def f(attrs, x, _fn=fn):
        axes = _norm_axes(attrs.axis, x.ndim, attrs.exclude)
        return _fn(x, axis=axes, keepdims=attrs.keepdims)

    _reg(name, f, params=dict(_REDUCE_PARAMS), aliases=aliases)


_def_reduce("sum", jnp.sum, aliases=("sum_axis",))
_def_reduce("mean", jnp.mean)
_def_reduce("prod", jnp.prod)
_def_reduce("nansum", jnp.nansum)
_def_reduce("nanprod", jnp.nanprod)
_def_reduce("max", jnp.max, aliases=("max_axis",))
_def_reduce("min", jnp.min, aliases=("min_axis",))
_reg(
    "norm",
    lambda attrs, x: jnp.sqrt(jnp.sum(jnp.square(x), axis=_norm_axes(attrs.axis, x.ndim), keepdims=attrs.keepdims))
    if attrs.ord == 2
    else jnp.sum(jnp.abs(x), axis=_norm_axes(attrs.axis, x.ndim), keepdims=attrs.keepdims),
    params={"ord": (int, 2), "axis": (tuple, None), "keepdims": (bool, False)},
)
_reg(
    "_square_sum",
    lambda attrs, x: jnp.sum(jnp.square(x), axis=_norm_axes(attrs.axis, x.ndim, attrs.exclude), keepdims=attrs.keepdims),
    params=dict(_REDUCE_PARAMS),
)


def _arg_reduce(fn):
    def f(attrs, x):
        if attrs.axis is None:
            return fn(x.reshape(-1), axis=0).astype(x.dtype)
        ax = attrs.axis[0] if isinstance(attrs.axis, tuple) else int(attrs.axis)
        out = fn(x, axis=ax)
        if attrs.keepdims:
            out = jnp.expand_dims(out, ax)
        return out.astype(x.dtype)

    return f


_reg("argmax", _arg_reduce(jnp.argmax), params={"axis": (tuple, None), "keepdims": (bool, False)})
_reg("argmin", _arg_reduce(jnp.argmin), params={"axis": (tuple, None), "keepdims": (bool, False)})
_reg("argmax_channel", lambda attrs, x: jnp.argmax(x, axis=1).astype(x.dtype))


# ---------------------------------------------------------------------------
# broadcast/shape manipulation (reference matrix_op-inl.h)
# ---------------------------------------------------------------------------


def _reshape_infer(shape, target):
    """MXNet Reshape semantics: 0 copies input dim, -1 infers, -2 copies rest,
    -3 merges two dims, -4 splits a dim (reference matrix_op-inl.h:95-180)."""
    out = []
    src = list(shape)
    i = 0
    j = 0
    while j < len(target):
        t = target[j]
        if t == 0:
            out.append(src[i]); i += 1
        elif t == -1:
            out.append(-1); i += 1
        elif t == -2:
            out.extend(src[i:]); i = len(src)
        elif t == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif t == -4:
            a, b = target[j + 1], target[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        else:
            out.append(t); i += 1
        j += 1
    if -1 in out:
        known = 1
        for v in out:
            if v != -1:
                known *= v
        total = 1
        for v in shape:
            total *= v
        out[out.index(-1)] = total // known
    return tuple(out)


_reg(
    "Reshape",
    lambda attrs, x: x.reshape(_reshape_infer(x.shape, attrs.shape) if attrs.shape else x.shape)
    if not attrs.reverse
    else x.reshape(tuple(reversed(_reshape_infer(tuple(reversed(x.shape)), tuple(reversed(attrs.shape)))))),
    params={"shape": (tuple, None), "reverse": (bool, False)},
    aliases=("reshape",),
)
_reg("Flatten", lambda attrs, x: x.reshape(x.shape[0], -1), aliases=("flatten",))
_reg(
    "transpose",
    lambda attrs, x: jnp.transpose(x, attrs.axes if attrs.axes else None),
    params={"axes": (tuple, None)},
)
_reg(
    "expand_dims",
    lambda attrs, x: jnp.expand_dims(x, attrs.axis),
    params={"axis": (int, REQUIRED)},
)
_reg(
    "squeeze",
    lambda attrs, x: jnp.squeeze(x, axis=attrs.axis if attrs.axis else None),
    params={"axis": (tuple, None)},
)


def _slice(attrs, x):
    nd = x.ndim
    begin = list(attrs.begin) + [None] * (nd - len(attrs.begin))
    end = list(attrs.end) + [None] * (nd - len(attrs.end))
    step = list(attrs.step) + [None] * (nd - len(attrs.step)) if attrs.step else [None] * nd
    idx = tuple(
        slice(
            None if b in (None,) else b,
            None if e in (None,) else e,
            None if s in (None, 0) else s,
        )
        for b, e, s in zip(begin, end, step)
    )
    return x[idx]


_reg(
    "slice",
    _slice,
    params={"begin": (tuple, REQUIRED), "end": (tuple, REQUIRED), "step": (tuple, None)},
    aliases=("crop",),
)
_reg(
    "slice_axis",
    lambda attrs, x: lax.slice_in_dim(
        x,
        attrs.begin if attrs.begin >= 0 else x.shape[attrs.axis] + attrs.begin,
        x.shape[attrs.axis] if attrs.end is None else (attrs.end if attrs.end >= 0 else x.shape[attrs.axis] + attrs.end),
        axis=attrs.axis % x.ndim,
    ),
    params={"axis": (int, REQUIRED), "begin": (int, REQUIRED), "end": (int, None)},
)
_reg(
    "slice_like",
    lambda attrs, x, like: x[
        tuple(
            slice(0, like.shape[i]) if (not attrs.axes or i in [a % x.ndim for a in attrs.axes]) else slice(None)
            for i in range(x.ndim)
        )
    ],
    params={"axes": (tuple, None)},
    inputs=("data", "shape_like"),
)
_reg(
    "Concat",
    lambda attrs, *xs: jnp.concatenate(xs, axis=attrs.dim),
    params={"num_args": (int, 1), "dim": (int, 1)},
    inputs=lambda attrs: ["arg%d" % i for i in range(attrs.get("num_args", 1))],
    aliases=("concat",),
)
_reg(
    "stack",
    lambda attrs, *xs: jnp.stack(xs, axis=attrs.axis),
    params={"num_args": (int, 1), "axis": (int, 0)},
    inputs=lambda attrs: ["arg%d" % i for i in range(attrs.get("num_args", 1))],
)
_reg(
    "SliceChannel",
    lambda attrs, x: tuple(
        jnp.squeeze(s, axis=attrs.axis) if attrs.squeeze_axis else s
        for s in jnp.split(x, attrs.num_outputs, axis=attrs.axis)
    ),
    params={"num_outputs": (int, REQUIRED), "axis": (int, 1), "squeeze_axis": (bool, False)},
    num_outputs=lambda attrs: attrs.num_outputs,
    aliases=("split",),
)
_reg(
    "tile",
    lambda attrs, x: jnp.tile(x, attrs.reps),
    params={"reps": (tuple, REQUIRED)},
)
_reg(
    "repeat",
    lambda attrs, x: jnp.repeat(x, attrs.repeats, axis=attrs.axis),
    params={"repeats": (int, REQUIRED), "axis": (int, None)},
)
_reg(
    "reverse",
    lambda attrs, x: jnp.flip(x, axis=attrs.axis),
    params={"axis": (tuple, REQUIRED)},
    aliases=("flip",),
)
_reg(
    "SwapAxis",
    lambda attrs, x: jnp.swapaxes(x, attrs.dim1, attrs.dim2),
    params={"dim1": (int, 0), "dim2": (int, 0)},
    aliases=("swapaxes",),
)
def _broadcast_to(attrs, x):
    tgt = attrs.shape
    if len(tgt) == x.ndim:  # 0 means "keep input dim" (reference semantics)
        tgt = tuple(t if t != 0 else s for t, s in zip(tgt, x.shape))
    return jnp.broadcast_to(x, tgt)


_reg("broadcast_to", _broadcast_to, params={"shape": (tuple, REQUIRED)})
_reg(
    "broadcast_axis",
    lambda attrs, x: jnp.broadcast_to(
        x,
        tuple(
            attrs.size[list(attrs.axis).index(i)] if i in attrs.axis else s
            for i, s in enumerate(x.shape)
        ),
    ),
    params={"axis": (tuple, REQUIRED), "size": (tuple, REQUIRED)},
    aliases=("broadcast_axes",),
)
_reg("broadcast_like", lambda attrs, x, like: jnp.broadcast_to(x, like.shape), inputs=("lhs", "rhs"))
_reg("reshape_like", lambda attrs, x, like: x.reshape(like.shape), inputs=("lhs", "rhs"))
_reg("shape_array", lambda attrs, x: jnp.asarray(x.shape, dtype=jnp.int64))
_reg("size_array", lambda attrs, x: jnp.asarray([x.size], dtype=jnp.int64))
_reg(
    "Pad",
    lambda attrs, x: jnp.pad(
        x,
        [(attrs.pad_width[2 * i], attrs.pad_width[2 * i + 1]) for i in range(x.ndim)],
        mode={"constant": "constant", "edge": "edge", "reflect": "reflect"}[attrs.mode],
        **({"constant_values": attrs.constant_value} if attrs.mode == "constant" else {}),
    ),
    params={"mode": (str, "constant"), "pad_width": (tuple, REQUIRED), "constant_value": (float, 0.0)},
    aliases=("pad",),
)
_reg(
    "depth_to_space",
    lambda attrs, x: _depth_to_space(x, attrs.block_size),
    params={"block_size": (int, REQUIRED)},
)
_reg(
    "space_to_depth",
    lambda attrs, x: _space_to_depth(x, attrs.block_size),
    params={"block_size": (int, REQUIRED)},
)


def _depth_to_space(x, b):
    n, c, h, w = x.shape
    x = x.reshape(n, b, b, c // (b * b), h, w)
    x = jnp.transpose(x, (0, 3, 4, 1, 5, 2))
    return x.reshape(n, c // (b * b), h * b, w * b)


def _space_to_depth(x, b):
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * b * b, h // b, w // b)


_reg(
    "diag",
    lambda attrs, x: jnp.diag(x, k=attrs.k) if x.ndim <= 2 else jnp.diagonal(x, offset=attrs.k, axis1=attrs.axis1, axis2=attrs.axis2),
    params={"k": (int, 0), "axis1": (int, 0), "axis2": (int, 1)},
)
_reg(
    "where",
    lambda attrs, cond, a, b: jnp.where(
        cond.reshape(cond.shape + (1,) * (a.ndim - cond.ndim)) != 0, a, b
    ),
    inputs=("condition", "x", "y"),
)

# ---------------------------------------------------------------------------
# dot / batch_dot (reference src/operator/tensor/dot-inl.h)
# ---------------------------------------------------------------------------


def _dot(attrs, a, b):
    """Contract last axis of a with first axis of b; result shape
    a.shape[:-1] + b.shape[1:] (reference dot-inl.h semantics)."""
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    am = jnp.swapaxes(a, -1, -2) if attrs.transpose_a else a
    bm = jnp.swapaxes(b, 0, 1) if attrs.transpose_b else b
    if bm.ndim == 2:
        # matmul contracts am's last axis with bm's first and broadcasts
        # leading dims — identical to the tensordot below but ~5x cheaper to
        # dispatch eagerly (single primitive bind, no reshape chain)
        return jnp.matmul(am, bm)
    return jnp.tensordot(am, bm, axes=([am.ndim - 1], [0]))


_reg(
    "dot",
    _dot,
    params={"transpose_a": (bool, False), "transpose_b": (bool, False)},
    inputs=("lhs", "rhs"),
)


def _batch_dot(attrs, a, b):
    ta, tb = attrs.transpose_a, attrs.transpose_b
    am = jnp.swapaxes(a, -1, -2) if ta else a
    bm = jnp.swapaxes(b, -1, -2) if tb else b
    return jnp.matmul(am, bm)


_reg(
    "batch_dot",
    _batch_dot,
    params={"transpose_a": (bool, False), "transpose_b": (bool, False)},
    inputs=("lhs", "rhs"),
)
_reg(
    "khatri_rao",
    lambda attrs, *xs: _khatri_rao(xs),
    params={"num_args": (int, 1)},
    inputs=lambda attrs: ["arg%d" % i for i in range(attrs.get("num_args", 1))],
)


def _khatri_rao(mats):
    out = mats[0]
    for m in mats[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(out.shape[0] * m.shape[0], *out.shape[1:])
    return out


# ---------------------------------------------------------------------------
# indexing (reference src/operator/tensor/indexing_op.h)
# ---------------------------------------------------------------------------

_reg(
    "take",
    lambda attrs, a, idx: jnp.take(
        a,
        idx.astype(jnp.int32),
        axis=attrs.axis,
        mode={"clip": "clip", "wrap": "wrap", "raise": "clip"}[attrs.mode],
    ),
    params={"axis": (int, 0), "mode": (str, "clip")},
    inputs=("a", "indices"),
)
_reg(
    "batch_take",
    lambda attrs, a, idx: jnp.take_along_axis(
        a, idx.astype(jnp.int32).reshape(-1, 1), axis=1
    ).reshape(idx.shape),
    inputs=("a", "indices"),
)
_reg(
    "pick",
    lambda attrs, x, idx: _pick(attrs, x, idx),
    params={"axis": (int, -1), "keepdims": (bool, False), "mode": (str, "clip")},
    inputs=("data", "index"),
)


def _pick(attrs, x, idx):
    ax = attrs.axis % x.ndim
    idxe = jnp.expand_dims(idx.astype(jnp.int32), ax)
    out = jnp.take_along_axis(x, jnp.clip(idxe, 0, x.shape[ax] - 1), axis=ax)
    return out if attrs.keepdims else jnp.squeeze(out, axis=ax)


_reg(
    "Embedding",
    lambda attrs, data, weight: jnp.take(weight, data.astype(jnp.int32), axis=0),
    params={
        "input_dim": (int, REQUIRED),
        "output_dim": (int, REQUIRED),
        "dtype": ("dtype", None),
        "sparse_grad": (bool, False),
    },
    inputs=("data", "weight"),
)
_reg(
    "one_hot",
    lambda attrs, idx: (
        jax.nn.one_hot(idx.astype(jnp.int32), attrs.depth, dtype=attrs.dtype or jnp.float32)
        * (attrs.on_value - attrs.off_value)
        + attrs.off_value
    ),
    params={
        "depth": (int, REQUIRED),
        "on_value": (float, 1.0),
        "off_value": (float, 0.0),
        "dtype": ("dtype", None),
    },
    inputs=("indices",),
)
_reg(
    "gather_nd",
    lambda attrs, data, indices: data[tuple(indices.astype(jnp.int32))],
    inputs=("data", "indices"),
)


def _scatter_nd(attrs, data, indices):
    out = jnp.zeros(attrs.shape, dtype=data.dtype)
    return out.at[tuple(indices.astype(jnp.int32))].add(data)


_reg(
    "scatter_nd",
    _scatter_nd,
    params={"shape": (tuple, REQUIRED)},
    inputs=("data", "indices"),
)
_reg(
    "_scatter_set_nd",
    lambda attrs, lhs, rhs, indices: lhs.at[tuple(indices.astype(jnp.int32))].set(rhs),
    params={"shape": (tuple, None)},
    inputs=("lhs", "rhs", "indices"),
)
_reg(
    "_ravel_multi_index",
    lambda attrs, data: _ravel(attrs, data),
    params={"shape": (tuple, REQUIRED)},
    inputs=("data",),
)


def _ravel(attrs, data):
    shape = attrs.shape
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.asarray(list(reversed(strides)), dtype=data.dtype)
    return jnp.sum(data * strides.reshape(-1, *([1] * (data.ndim - 1))), axis=0)


def _unravel(attrs, data):
    shape = attrs.shape
    idx = data.astype(jnp.int64)
    outs = []
    for s in reversed(shape):
        outs.append(idx % s)
        idx = idx // s
    return jnp.stack(list(reversed(outs)), axis=0).astype(data.dtype)


_reg("_unravel_index", _unravel, params={"shape": (tuple, REQUIRED)}, inputs=("data",))

# ---------------------------------------------------------------------------
# init ops (reference src/operator/tensor/init_op.cc)
# ---------------------------------------------------------------------------

_reg(
    "_zeros",
    lambda attrs: jnp.zeros(attrs.shape or (), dtype=attrs.dtype or jnp.float32),
    params={"shape": (tuple, None), "dtype": ("dtype", None), "ctx": (str, "")},
    inputs=(),
)
_reg(
    "_ones",
    lambda attrs: jnp.ones(attrs.shape or (), dtype=attrs.dtype or jnp.float32),
    params={"shape": (tuple, None), "dtype": ("dtype", None), "ctx": (str, "")},
    inputs=(),
)
_reg(
    "_full",
    lambda attrs: jnp.full(attrs.shape or (), attrs.value, dtype=attrs.dtype or jnp.float32),
    params={"shape": (tuple, None), "value": (float, 0.0), "dtype": ("dtype", None), "ctx": (str, "")},
    inputs=(),
)
_reg(
    "_arange",
    lambda attrs: jnp.tile(
        jnp.arange(attrs.start, attrs.stop, attrs.step, dtype=attrs.dtype or jnp.float32),
        attrs.repeat,
    )
    if attrs.repeat == 1
    else jnp.repeat(
        jnp.arange(attrs.start, attrs.stop, attrs.step, dtype=attrs.dtype or jnp.float32),
        attrs.repeat,
    ),
    params={
        "start": (float, 0.0),
        "stop": (float, None),
        "step": (float, 1.0),
        "repeat": (int, 1),
        "dtype": ("dtype", None),
        "ctx": (str, ""),
        "infer_range": (bool, False),
    },
    inputs=(),
)
_reg(
    "_eye",
    lambda attrs: jnp.eye(attrs.N, attrs.M or None, k=attrs.k, dtype=attrs.dtype or jnp.float32),
    params={"N": (int, REQUIRED), "M": (int, 0), "k": (int, 0), "dtype": ("dtype", None), "ctx": (str, "")},
    inputs=(),
)
_reg(
    "_identity_with_attr_like_rhs",
    lambda attrs, lhs, rhs: lhs,
    inputs=("lhs", "rhs"),
)
_reg("_NoGradient", lambda attrs: jnp.zeros(()), inputs=())

# ---------------------------------------------------------------------------
# ordering ops (reference src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------


def _topk(attrs, x):
    ax = x.ndim - 1 if attrs.axis is None else attrs.axis % x.ndim
    k = attrs.k if attrs.k > 0 else x.shape[ax]
    xm = jnp.moveaxis(x, ax, -1)
    if attrs.is_ascend:
        vals, idxs = lax.top_k(-xm, k)
        vals = -vals
    else:
        vals, idxs = lax.top_k(xm, k)
    vals = jnp.moveaxis(vals, -1, ax)
    idxs = jnp.moveaxis(idxs, -1, ax)
    rt = attrs.ret_typ
    if rt == "value":
        return vals
    if rt == "indices":
        return idxs.astype(attrs.dtype or jnp.float32)
    if rt == "mask":
        mask = jnp.zeros(jnp.moveaxis(x, ax, -1).shape, dtype=x.dtype)
        mask = mask.at[..., 0].set(0)  # shape anchor
        oh = jax.nn.one_hot(jnp.moveaxis(idxs, ax, -1), x.shape[ax], dtype=x.dtype).sum(axis=-2)
        return jnp.moveaxis(oh, -1, ax)
    return vals, idxs.astype(attrs.dtype or jnp.float32)


_reg(
    "topk",
    _topk,
    params={
        "axis": (int, -1),
        "k": (int, 1),
        "ret_typ": (str, "indices"),
        "is_ascend": (bool, False),
        "dtype": ("dtype", None),
    },
    num_outputs=lambda attrs: 2 if attrs.get("ret_typ") == "both" else 1,
)


def _sort(attrs, x):
    ax = x.ndim - 1 if attrs.axis is None else attrs.axis % x.ndim
    s = jnp.sort(x, axis=ax)
    return s if attrs.is_ascend else jnp.flip(s, axis=ax)


_reg("sort", _sort, params={"axis": (int, -1), "is_ascend": (bool, True)})


def _argsort(attrs, x):
    ax = x.ndim - 1 if attrs.axis is None else attrs.axis % x.ndim
    s = jnp.argsort(x, axis=ax)
    if not attrs.is_ascend:
        s = jnp.flip(s, axis=ax)
    return s.astype(attrs.dtype or jnp.float32)


_reg(
    "argsort",
    _argsort,
    params={"axis": (int, -1), "is_ascend": (bool, True), "dtype": ("dtype", None)},
)

# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------


def _histogram(attrs, data, *bins):
    if bins:
        edges = bins[0]
        cnt, _ = jnp.histogram(data.reshape(-1), bins=edges)
        return cnt.astype(jnp.int64), edges
    rng = attrs.range or (float(jnp.min(data)), float(jnp.max(data)))
    cnt, edges = jnp.histogram(data.reshape(-1), bins=attrs.bin_cnt or 10, range=rng)
    return cnt.astype(jnp.int64), edges


_reg(
    "_histogram",
    _histogram,
    params={"bin_cnt": (int, None), "range": (tuple, None)},
    inputs=("data",),
    num_outputs=2,
)
