"""Runtime kernel compilation: user-supplied Pallas kernels.

TPU-native re-design of the reference's RTC subsystem
(``src/common/rtc.cc:31-94`` — NVRTC compiles CUDA-C strings to PTX at
runtime; Python surface ``python/mxnet/rtc.py`` ``CudaModule``/
``get_kernel``/``launch``). On TPU the runtime-kernel substrate is Pallas:
a :class:`PallasModule` takes kernel SOURCE (a Python string defining
Pallas kernel functions over ``pl``/``jnp``), compiles it lazily through
XLA's Mosaic pipeline at first launch, and launches over a grid — same
workflow, same signature-driven input/output convention (``const`` marks
inputs, non-const pointers are outputs, exactly like the reference's
signature strings).

Kernels fall back to Pallas interpret mode off-TPU so user code is testable
on CPU.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, np_dtype
from .ndarray.ndarray import NDArray

__all__ = ["PallasModule", "Kernel", "CudaModule"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


class Kernel(object):
    """One launchable kernel (reference rtc.py:CudaKernel).

    The wrapped function is a Pallas kernel taking ``(*in_refs, *out_refs)``
    in the order declared by the signature.
    """

    def __init__(self, fn, name: str, spec: List[Tuple[str, object, bool]]):
        self._fn = fn
        self._name = name
        self._spec = spec  # (arg_name, dtype, is_output)

    def launch(self, args: Sequence, ctx=None,
               grid_dims: Tuple[int, int, int] = (1, 1, 1),
               block_dims: Tuple[int, int, int] = (1, 1, 1),
               shared_mem: int = 0):
        """Launch over a grid (reference rtc.py:CudaKernel.launch).

        ``args`` pairs with the signature; output args are NDArrays whose
        contents are REPLACED by the kernel result (the CUDA out-pointer
        idiom, realized functionally). ``block_dims``/``shared_mem`` are
        accepted for API parity — Pallas blocks are expressed by the
        kernel's own BlockSpecs/refs, and scratch memory by its allocations.
        """
        del ctx, block_dims, shared_mem
        if len(args) != len(self._spec):
            raise MXNetError("kernel %s: %d args for %d-parameter signature"
                             % (self._name, len(args), len(self._spec)))
        from jax.experimental import pallas as pl

        ins, outs, out_refs = [], [], []
        for a, (_, dt, is_out) in zip(args, self._spec):
            if is_out:
                if not isinstance(a, NDArray):
                    raise MXNetError("kernel %s: output args must be NDArrays"
                                     % self._name)
                outs.append(jax.ShapeDtypeStruct(a.shape, dt))
                out_refs.append(a)
            else:
                data = a._data if isinstance(a, NDArray) else jnp.asarray(a)
                ins.append(data.astype(dt) if data.dtype != dt else data)
        # Preserve grid RANK: a kernel written against grid (1, 8, 1) reads
        # pl.program_id(1) for its real axis — dropping interior 1-dims would
        # silently renumber its axes. Only trailing 1s are safe to strip.
        grid = tuple(int(g) for g in grid_dims) or (1,)
        if any(g < 1 for g in grid):
            # CUDA rejects a zero gridDim launch; silently running zero
            # grid steps would return an unwritten output buffer
            raise MXNetError("kernel %s: invalid grid_dims %r (all dims "
                             "must be >= 1)" % (self._name, grid_dims))
        while len(grid) > 1 and grid[-1] == 1:
            grid = grid[:-1]
        result = pl.pallas_call(
            self._fn,
            out_shape=outs if len(outs) > 1 else outs[0],
            grid=grid,
            interpret=_interpret(),
        )(*ins)
        results = result if isinstance(result, (tuple, list)) else (result,)
        for ref, res in zip(out_refs, results):
            ref._data = res
        return out_refs[0] if len(out_refs) == 1 else out_refs


_SIG_RE = re.compile(
    r"^\s*(?P<const>const\s+)?(?P<type>\w+)\s*(?P<ptr>\*)?\s*(?P<name>\w+)\s*$")

_CTYPE_DT = {"float": np.float32, "double": np.float64, "int": np.int32,  # tpulint: disable=dtype-drift -- C ABI signature table, host-side
             "long": np.int64, "half": np.float16, "bfloat16": jnp.bfloat16,
             "uint8": np.uint8, "int8": np.int8}


def _parse_signature(sig: str):
    spec = []
    for part in sig.split(","):
        m = _SIG_RE.match(part)
        if not m:
            raise MXNetError("cannot parse signature fragment %r" % part)
        base = m.group("type")
        dt = _CTYPE_DT.get(base)
        if dt is None:
            dt = np_dtype(base)
        is_out = bool(m.group("ptr")) and not m.group("const")
        spec.append((m.group("name"), np.dtype(dt) if dt is not jnp.bfloat16
                     else jnp.bfloat16, is_out))
    return spec


class PallasModule(object):
    """Compile Pallas kernel source at runtime (reference rtc.py:CudaModule).

    ``source`` is Python code with ``pl``, ``jnp``, ``jax`` and ``np`` in
    scope, defining one function per kernel; ``exports`` lists the kernel
    names retrievable with :meth:`get_kernel`.

    Example::

        mod = mx.rtc.PallasModule('''
        def axpy(a_ref, x_ref, y_ref, out_ref):
            out_ref[...] = a_ref[0] * x_ref[...] + y_ref[...]
        ''', exports=["axpy"])
        k = mod.get_kernel("axpy", "const float *a, const float *x, "
                                   "const float *y, float *out")
        k.launch((a, x, y, out))
    """

    def __init__(self, source: str, options: Sequence[str] = (),
                 exports: Sequence[str] = ()):
        del options  # NVRTC flags have no Mosaic equivalent; kept for parity
        from jax.experimental import pallas as pl

        self._namespace: Dict[str, object] = {
            "pl": pl, "jnp": jnp, "jax": jax, "np": np}
        try:
            exec(compile(source, "<mxnet_tpu.rtc>", "exec"), self._namespace)
        except SyntaxError as exc:
            raise MXNetError("PallasModule: kernel source does not compile: %s"
                             % exc) from exc
        self._exports = tuple(exports) or tuple(
            n for n, v in self._namespace.items()
            if callable(v) and not n.startswith("_") and n not in
            ("pl", "jnp", "jax", "np"))
        for name in self._exports:
            if name not in self._namespace:
                raise MXNetError("PallasModule: exported kernel %r not "
                                 "defined by source" % name)

    def get_kernel(self, name: str, signature: str) -> Kernel:
        """Bind a kernel by name + C-style signature (reference
        rtc.py:CudaModule.get_kernel)."""
        if name not in self._exports:
            raise MXNetError("kernel %r not exported (exports: %s)"
                             % (name, list(self._exports)))
        return Kernel(self._namespace[name], name, _parse_signature(signature))


#: Reference-compatible alias: code written against ``mx.rtc.CudaModule``
#: gets the Pallas substrate transparently.
CudaModule = PallasModule
