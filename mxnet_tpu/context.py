"""Device contexts: ``mx.cpu()``, ``mx.gpu()``, ``mx.tpu()``.

Re-design of the reference's ``python/mxnet/context.py`` (Context,
default-context thread-local) with TPU as a first-class device. A Context
maps onto a concrete ``jax.Device``; ``gpu()`` is accepted for source
compatibility and resolves to the platform accelerator (TPU here).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """Execution device. ``Context('tpu', 0)`` designates TPU chip 0.

    Mirrors the user surface of reference ``python/mxnet/context.py:Context``
    (devtype2str/devstr2type, ``with ctx:`` scoping, equality/hash) while the
    backing runtime is a jax.Device rather than an mshadow stream.
    """

    # dev_type codes kept for .params compat (reference context.py devtype2str)
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_type = device_type
            self.device_id = int(device_id)
        self._old_ctx: Optional[Context] = None

    # -- identity ----------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devstr2type[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    # -- scoping -----------------------------------------------------------
    def __enter__(self):
        if not hasattr(self._default_ctx, "value"):
            self._default_ctx.value = Context("cpu", 0)
        self._old_ctx = self._default_ctx.value
        self._default_ctx.value = self
        return self

    def __exit__(self, *args):
        self._default_ctx.value = self._old_ctx

    # -- jax mapping -------------------------------------------------------
    def jax_device(self) -> "jax.Device":
        """Resolve this context to a concrete jax.Device."""
        if self.device_type == "cpu" or self.device_type in ("cpu_pinned", "cpu_shared"):
            devs = _devices_by_platform("cpu")
        else:
            devs = _accelerator_devices()
            if not devs:  # no accelerator present: fall back to host
                devs = _devices_by_platform("cpu")
        if not devs:
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Parity with reference Context.empty_cache; XLA manages HBM pools."""
        # jax manages its own HBM allocator; nothing to do, kept for API parity.
        return


def _devices_by_platform(platform: str):
    """Addressable devices of a platform. Under ``jax.distributed`` a context
    names a device of THIS process (the reference's ``mx.gpu(i)`` is likewise
    worker-local); other processes' devices are only reachable through
    collectives, so they never back an NDArray."""
    try:
        devs = jax.devices(platform)
    except RuntimeError:
        return []
    local = [d for d in devs if d.process_index == jax.process_index()]
    return local or devs


_ACCEL_CACHE = None


def _accelerator_devices():
    """Process-local non-CPU jax devices (TPU first), cached."""
    global _ACCEL_CACHE
    if _ACCEL_CACHE is None:
        devs = [d for d in jax.local_devices() if d.platform != "cpu"]
        _ACCEL_CACHE = devs
    return _ACCEL_CACHE


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accepted for source compatibility with reference scripts; resolves to
    the platform accelerator (TPU on this stack)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_accelerator_devices())


def num_tpus() -> int:
    return len(_accelerator_devices())


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
