"""Training callbacks — API parity with reference ``python/mxnet/callback.py``
(Speedometer :120, do_checkpoint :55, module_checkpoint :27, ProgressBar
:180), re-implemented for this runtime.

Contracts: epoch-end callbacks are called as ``cb(epoch, symbol, arg_params,
aux_params)``; batch-end callbacks receive a ``BatchEndParam``-style object
with ``epoch``, ``nbatch``, ``eval_metric`` and ``locals`` attributes.
"""
from __future__ import annotations

import logging
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def _every(period):
    """Normalized positive period for the *-checkpoint factories."""
    return max(1, int(period))


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback checkpointing a Module (reference callback.py:27)."""
    n = _every(period)

    def _cb(epoch, sym=None, arg=None, aux=None):
        done = epoch + 1
        if done % n == 0:
            mod.save_checkpoint(prefix, done, save_optimizer_states)

    return _cb


def do_checkpoint(prefix, period=1):
    """Epoch-end callback writing ``prefix-symbol.json`` +
    ``prefix-NNNN.params`` (reference callback.py:55)."""
    from . import model

    n = _every(period)

    def _cb(epoch, sym, arg, aux):
        done = epoch + 1
        if done % n == 0:
            model.save_checkpoint(prefix, done, sym, arg, aux)

    return _cb


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the training metric every ``period``
    batches (reference callback.py:93)."""

    def _cb(param):
        metric = param.eval_metric
        if metric is None or param.nbatch % period != 0:
            return
        for name, value in metric.get_name_value():
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset:
            metric.reset()

    return _cb


class Speedometer:
    """Logs samples/sec (and the running metric) every ``frequent`` batches
    (reference callback.py:120).

    Internal state is a single ``(batch_count, timestamp)`` mark taken at the
    previous report; throughput = batches-since-mark × batch_size / elapsed,
    on a monotonic clock so wall-clock adjustments can't produce negative
    speeds. A batch counter that goes backwards (new epoch) re-arms the mark.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._mark = None  # (nbatch, monotonic time) of the last report

    def __call__(self, param):
        count = param.nbatch
        if self._mark is None or count < self._mark[0]:
            self._mark = (count, time.monotonic())
            return
        if count % self.frequent != 0 or count == self._mark[0]:
            return
        elapsed = time.monotonic() - self._mark[1]
        done = (count - self._mark[0]) * self.batch_size
        speed = done / elapsed if elapsed > 0 else float("inf")
        metric = param.eval_metric
        if metric is not None:
            pairs = metric.get_name_value()
            if self.auto_reset:
                metric.reset()
            tail = "".join("\t%s=%f" % nv for nv in pairs)
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         param.epoch, count, speed, tail)
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, count, speed)
        self._mark = (count, time.monotonic())


class ProgressBar:
    """Text progress bar over ``total`` batches (reference callback.py:180)."""

    def __init__(self, total, length=80):
        self.total = total
        self.length = length

    def __call__(self, param):
        frac = min(1.0, param.nbatch / float(self.total))
        fill = int(self.length * frac + 0.5)
        bar = "=" * fill + "-" * (self.length - fill)
        logging.info("[%s] %d%%\r", bar, int(frac * 100 + 0.999))


class LogValidationMetricsCallback:
    """Eval-end callback logging validation metrics (reference
    callback.py:210)."""

    def __call__(self, param):
        metric = param.eval_metric
        if metric is None:
            return
        for name, value in metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
