"""Executor: whole-graph compiled execution of a Symbol.

Re-design of the reference GraphExecutor (`src/executor/graph_executor.cc`)
and its Python wrapper (`python/mxnet/executor.py`). Where the reference
interprets the nnvm graph node-by-node through the dependency engine, this
executor lowers the ENTIRE forward graph — and, for training, the fused
forward+backward via jax.vjp — into single jitted XLA HloModules
(SURVEY.md §7.1 north star). Memory planning (PlanMemory pass,
graph_executor.cc:636) is delegated to XLA's buffer assignment.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import _fused, _global
from . import telemetry as _telemetry
from .base import MXNetError, get_env
from .context import Context, current_context
from .ndarray.ndarray import NDArray

__all__ = ["Executor"]


class Executor(object):
    """Bound computation graph (reference executor.py:45).

    Parameters mirror ``Symbol.bind``: ``args``/``args_grad``/``aux_states``
    are dicts or lists of NDArrays in ``list_arguments()`` order.
    """

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else current_context()
        # group2ctx: manual model-parallel placement — ctx_group attrs map
        # onto jax devices as in-graph placement constraints (the reference
        # partitions the graph with _CrossDeviceCopy nodes,
        # graph_executor.cc:1577; XLA inserts the transfers here)
        self._group2dev = {g: (c.jax_device() if isinstance(c, Context) else c)
                           for g, c in group2ctx.items()} if group2ctx else None
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        self.arg_dict = self._as_dict(args, self.arg_names, "args")
        self.arg_arrays = [self.arg_dict[n] for n in self.arg_names]
        if args_grad is None:
            self.grad_dict = {}
        else:
            self.grad_dict = self._as_dict(args_grad, self.arg_names, "args_grad",
                                           allow_missing=True)
        self.grad_arrays = [self.grad_dict.get(n) for n in self.arg_names]
        self.aux_dict = self._as_dict(aux_states or {}, self.aux_names, "aux_states")
        self.aux_arrays = [self.aux_dict[n] for n in self.aux_names]

        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(self.arg_names, grad_req))
        else:
            self.grad_req = dict(grad_req)

        self.outputs: List[NDArray] = []
        self._monitor_callback = None
        self._fwd_cache: Dict[Any, Any] = {}
        self._residuals = None
        self._bwd_pair = None
        self._output_shapes = None

    @staticmethod
    def _as_dict(vals, names, what, allow_missing=False):
        if isinstance(vals, dict):
            missing = [n for n in names if n not in vals]
            if missing and not allow_missing:
                raise MXNetError("%s: missing bindings for %s" % (what, missing))
            return {n: vals[n] for n in names if n in vals}
        vals = list(vals)
        if len(vals) != len(names):
            raise MXNetError(
                "%s: expected %d arrays, got %d" % (what, len(names), len(vals)))
        return dict(zip(names, vals))

    # ------------------------------------------------------------------
    def _graph_fn(self, is_train):
        """Jitted (arg_vals, aux_vals, rng) -> (outputs, aux_updates)."""
        if is_train in self._fwd_cache:
            return self._fwd_cache[is_train]
        sym = self._symbol

        def fn(arg_vals, aux_vals, rng):
            prev = _global.set_train(is_train)
            _global.push_rng_key(rng)
            try:
                vm = dict(arg_vals)
                vm.update(aux_vals)
                aux_updates = {}
                outs = sym.eval_jax(vm, aux_updates=aux_updates,
                                    group2dev=self._group2dev)
            finally:
                _global.pop_rng_key()
                _global.set_train(prev)
            return tuple(outs), aux_updates

        jit_fn = jax.jit(fn)
        self._fwd_cache[is_train] = jit_fn
        return jit_fn

    def _train_pair(self, diff_names, shape_sig):
        """Cached (fwd_jit, bwd_jit) pair for training: fwd returns
        (outputs, aux_updates, residuals); bwd maps (residuals, cotangents)
        to input gradients. Residuals are hoisted out of the vjp closure so
        both halves compile exactly once. Keyed on the input shape
        signature: a reshaped executor gets a fresh pair rather than a
        backward replaying a stale jaxpr."""
        key = ("fb", diff_names, shape_sig)
        if key in self._fwd_cache:
            return self._fwd_cache[key]
        sym = self._symbol
        cell = {}

        def run_graph(arg_vals, aux_vals, rng):
            prev = _global.set_train(True)
            _global.push_rng_key(rng)
            try:
                vm = dict(arg_vals)
                vm.update(aux_vals)
                aux_updates = {}
                outs = sym.eval_jax(vm, aux_updates=aux_updates,
                                    group2dev=self._group2dev)
            finally:
                _global.pop_rng_key()
                _global.set_train(prev)
            return tuple(outs), aux_updates

        # rematerialization: recompute activations in backward instead of
        # keeping residuals in HBM — the reference's mirror-for-recompute
        # policy (MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:259), realized
        # as jax.checkpoint over the whole graph function
        do_mirror = bool(get_env("MXNET_BACKWARD_DO_MIRROR", 0, int))

        def fwd(diff_vals, const_args, aux_vals, rng):
            def f(dv):
                av = dict(const_args)
                av.update(zip(diff_names, dv))
                return run_graph(av, aux_vals, rng)

            if do_mirror:
                f = jax.checkpoint(f)
            outs, vjp_fn, aux = jax.vjp(f, list(diff_vals), has_aux=True)

            def vjp_flat(*cts_flat):
                return vjp_fn(tuple(cts_flat))

            examples = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]
            vjp_pure, res = _fused.convert_closure(vjp_flat, *examples)
            cell["bwd"] = vjp_pure
            return outs, aux, res

        def bwd(res, cts, donate=False):
            # residual donation: the vjp residuals (the stored-activation
            # set, the largest training buffer) are consumed exactly once —
            # donating them lets XLA reuse that HBM for the gradient
            # computation. Only when the caller proved no residual aliases
            # a buffer it still holds (forward() checks — XLA may alias
            # identical jit outputs) and the backend implements donation.
            key = "bwd_jit_donate" if donate else "bwd_jit"
            if key not in cell:
                raw = cell["bwd"]
                cell[key] = jax.jit(
                    lambda res, cts: raw(res, *cts),
                    donate_argnums=(0,) if donate else ())
            (grads,) = _telemetry.jit_call("executor.backward",
                                           cell[key],
                                           list(res), list(cts))
            return grads

        # fwd deliberately donates nothing: every input (params, aux, rng)
        # outlives the call — params persist across steps, aux buffers are
        # replaced (not consumed) after the call returns
        pair = {"fwd": jax.jit(fwd), "bwd": bwd}
        self._fwd_cache[key] = pair
        return pair

    @_telemetry.traced(
        "executor", lambda self, *a, **kw: "forward(%s)" % self._symbol.name)
    def forward(self, is_train=False, **kwargs):
        """Run forward (reference executor.py:114). kwargs update arg data."""
        for name, val in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError("unknown argument %r" % name)
            src = val._data if isinstance(val, NDArray) else val
            self.arg_dict[name]._data = src

        arg_vals = {n: a._data for n, a in self.arg_dict.items()}
        aux_vals = {n: a._data for n, a in self.aux_dict.items()}
        rng = _global.next_key()

        if is_train:
            # fused fwd+bwd: outputs + vjp residuals from ONE compiled
            # module; backward is a second compiled module (reference
            # GraphExecutor full fwd+bwd graph, graph_executor.cc:231-295)
            diff_names = tuple(n for n in self.arg_names
                               if self.grad_req.get(n, "null") != "null"
                               and n in self.grad_dict)
            shape_sig = tuple(sorted(
                (n, v.shape, str(v.dtype)) for n, v in arg_vals.items()))
            pair = self._train_pair(diff_names, shape_sig)
            const_args = {n: v for n, v in arg_vals.items()
                          if n not in diff_names}
            outputs, aux_updates, self._residuals = _telemetry.jit_call(
                "executor.train_forward", pair["fwd"],
                [arg_vals[n] for n in diff_names], const_args, aux_vals, rng)
            self._bwd_pair = pair
            self._diff_names = diff_names
            self._bwd_donate = self._residuals_donatable(
                outputs, aux_updates, list(arg_vals.values()))
        else:
            outputs, aux_updates = _telemetry.jit_call(
                "executor.forward", self._graph_fn(False),
                arg_vals, aux_vals, rng)
            self._residuals = None
        for name, val in aux_updates.items():
            if name in self.aux_dict:
                self.aux_dict[name]._data = val

        self.outputs = [NDArray(o, self._ctx) for o in outputs]
        self._output_shapes = [o.shape for o in outputs]
        if self._monitor_callback is not None:
            for name, out in zip(self.output_names, self.outputs):
                self._monitor_callback(name, out)
        return self.outputs

    def _residuals_donatable(self, outputs, aux_updates, inputs):
        """Donation-safety guard for the backward jit: a runtime may alias
        jit outputs onto one buffer (identical outputs, or an unmodified
        input passed through), so a residual can share device memory with
        a forward output/input the caller still holds — donating it would
        corrupt that live array — or with ANOTHER residual — donating the
        same buffer at two argument positions is a runtime error.
        Residuals are donatable only when their buffers are pairwise
        distinct AND disjoint from every output/aux/param buffer (and
        donation is on for a backend that implements it)."""
        from . import fastpath
        from .fastpath.fused import _buf_ptr

        if not fastpath.donation_argnums_ok():
            return False
        held = [_buf_ptr(b) for b in
                list(outputs) + list(aux_updates.values()) + list(inputs)]
        ptrs = [_buf_ptr(r) for r in self._residuals]
        if None in ptrs or None in held:  # unprobeable => no donation
            return False
        return len(set(ptrs)) == len(ptrs) and \
            not set(ptrs) & set(held)

    @_telemetry.traced(
        "executor", lambda self, *a, **kw: "backward(%s)" % self._symbol.name)
    def backward(self, out_grads=None, is_train=True):
        """Run backward (reference executor.py:155); accumulates into
        grad_arrays honoring per-arg grad_req write/add."""
        import jax.numpy as jnp

        if self._residuals is None:
            raise MXNetError(
                "backward needs a fresh forward(is_train=True): none has "
                "run, or the previous backward consumed (donated) the "
                "residuals")
        if out_grads is None:
            cts = tuple(jnp.ones(s, dtype=o._data.dtype)
                        for s, o in zip(self._output_shapes, self.outputs))
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                        for g in out_grads)
        donate = bool(getattr(self, "_bwd_donate", False))
        grads = self._bwd_pair["bwd"](self._residuals, list(cts),
                                      donate=donate)
        if donate:
            # residuals were donated: invalidate the handle so a second
            # backward raises cleanly instead of replaying dead buffers
            self._residuals = None
        for name, g in zip(self._diff_names, grads):
            tgt = self.grad_dict.get(name)
            if tgt is None:
                continue
            if self.grad_req.get(name) == "add":
                tgt._data = tgt._data + g
            else:
                tgt._data = g

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor for new input shapes (reference
        executor.py:372). XLA recompiles per shape automatically; arrays are
        reallocated here."""
        from .ndarray import ndarray as nd_mod

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, shape in zip(self.arg_names, arg_shapes):
            old = self.arg_dict[name]
            if shape == old.shape:
                new_args[name] = old
            else:
                new_args[name] = nd_mod.zeros(shape, ctx=self._ctx,
                                              dtype=old.dtype)
        new_grads = None
        if self.grad_dict:
            new_grads = {}
            for name in self.grad_dict:
                shape = new_args[name].shape
                new_grads[name] = nd_mod.zeros(shape, ctx=self._ctx)
        new_aux = {}
        for name, shape in zip(self.aux_names, aux_shapes):
            old = self.aux_dict[name]
            new_aux[name] = old if shape == old.shape else nd_mod.zeros(
                shape, ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self.grad_req, new_aux,
                        group2ctx=self._group2dev)  # devices pass through

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Copy parameters (reference executor.py:copy_params_from)."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._data = array._data
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" that is not in the arguments" % name)
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._data = array._data
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" that is not in the auxiliary states" % name)

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    @property
    def output_dict(self):
        return dict(zip(self.output_names, self.outputs))

    def debug_str(self):
        return "Symbolic executor over %d args, %d outputs (whole-graph XLA)" % (
            len(self.arg_names), len(self.output_names))
