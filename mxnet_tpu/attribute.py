"""Attribute scoping for symbol construction.

API parity with the reference ``python/mxnet/attribute.py`` (AttrScope:
a with-block whose attributes — ``ctx_group``, ``__lr_mult__``, custom
``__key__`` attrs — attach to every Symbol created inside it; nested scopes
merge, inner wins). The executor consumes ``ctx_group`` for group2ctx
placement and the Gluon/Module layers consume the ``__*__`` multipliers.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current"]


class AttrScope(object):
    """Attach attributes to symbols created within the scope
    (reference attribute.py:AttrScope)."""

    _tls = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("attributes need to be strings")
        self._attr = kwargs
        self._old_scope: Optional[AttrScope] = None

    def get(self, attr: Optional[Dict[str, str]]) -> Dict[str, str]:
        """Merge the scope's attrs under explicitly-given ones."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(AttrScope._tls, "value"):
            AttrScope._tls.value = AttrScope()
        self._old_scope = AttrScope._tls.value
        merged = self._old_scope._attr.copy()
        merged.update(self._attr)
        self._attr = merged
        AttrScope._tls.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope is not None
        AttrScope._tls.value = self._old_scope

    @classmethod
    def current(cls) -> "AttrScope":
        if not hasattr(cls._tls, "value"):
            cls._tls.value = AttrScope()
        return cls._tls.value


def current() -> AttrScope:
    return AttrScope.current()
