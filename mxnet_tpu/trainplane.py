"""The training plane: ONE compiled SPMD step behind the high-level APIs.

BENCH_TPU_PARTIAL_r05 measured eager ResNet-50 training at 0.6% MFU on a
v5e chip; PR 5 collapsed the *update* plane to one fused jit, but the
forward/backward still ran outside ``parallel.TrainStep``. This module
turns the fused update plane into a fused *step* plane: the whole training
step — forward + loss + backward + data-parallel all-reduce + optimizer
update — compiles into ONE XLA module (the reference framework's single
scheduled graph per step: GraphExecutor fwd+bwd + kvstore reduce + fused
optimizer ops; the same end-to-end-compilation argument TVM makes,
PAPERS.md), and the high-level training APIs route through it:

* ``TrainPlane`` — drives a ``gluon.Trainer``-owned model. ``plane.step``
  replaces the canonical record/forward/backward/``Trainer.step`` loop
  body; :func:`fit` is the epoch-loop convenience on top.
* ``module_plane`` — the same plane for ``Module.fit`` (and therefore
  ``model.fit``/``FeedForward.fit``), built over the Symbol graph.

Bit-identity discipline (PR-5, one level up): the in-graph step consumes
the SAME host scalar prologue (``Optimizer._update_count`` +
``_host_scalars``) and traces the SAME per-parameter kernel
(``fastpath.tree_kernel`` over ``Optimizer._leaf_step``) as the eager
fused apply, and seeds the backward with the same all-ones cotangents
``loss.backward()`` would — so fp32 training through the graph plane is
bit-identical to the eager fastpath (asserted in tests/test_trainplane.py).
The optimizer's ``num_update``/per-index counters stay the single source
of truth, so eager and in-graph steps can interleave without lr-schedule
drift.

Knobs (docs/env_var.md):

* ``MXNET_TRAINSTEP`` — ``auto`` (default: compile when traceable, fall
  back silently), ``1`` (compile, warn on fallback), ``0`` (eager always).
  Non-traceable models — plain ``Block``s, host-dependent control flow —
  fall back to the eager path automatically; never a crash.
* ``MXNET_TRAIN_DTYPE`` — ``bf16`` casts the model to bfloat16 at plane
  activation and turns on the fp32 master-weight multi-precision path in
  the optimizer (states are kept f32; the MXU-rate training mode).
* ``MXNET_SHARDED_FEED`` — default on: :func:`fit` stages batches through
  ``io.DevicePrefetchIter`` pre-laid-out over the mesh's ``dp`` axis, so
  the step's own shard check is a no-op instead of a dispatch-serializing
  ``device_put``.

Multi-chip: the default mesh spans every local device whose count divides
the batch; under a launcher (``MXNET_COORDINATOR_*``) construction joins
the multi-process jax runtime via ``kvstore.init_distributed`` and the
same step spans the slice (GSPMD inserts the ICI collectives).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import autograd, telemetry
from .telemetry import devprof as _devprof
from . import optimizer as opt_mod
from .base import get_env
from .context import cpu
from .ndarray.ndarray import NDArray

__all__ = ["TrainPlane", "fit", "module_plane", "mode", "train_dtype",
           "sharded_feed"]

_LOG = logging.getLogger(__name__)

#: why planes fell back to eager, by coarse reason — the operator-visible
#: record that MXNET_TRAINSTEP=auto quietly declined to compile something
FALLBACKS = telemetry.counter(
    "mxnet_trainplane_fallbacks_total",
    "training-plane graph compilations declined, by reason",
    labels=("reason",))


def mode() -> str:
    """``MXNET_TRAINSTEP``: ``auto`` | ``1`` | ``0`` (re-read per call)."""
    raw = str(get_env("MXNET_TRAINSTEP", "auto", str, cache=False)).lower()
    return raw if raw in ("auto", "1", "0") else "auto"


def train_dtype() -> str:
    """``MXNET_TRAIN_DTYPE``: ``fp32`` (default) | ``bf16``."""
    raw = str(get_env("MXNET_TRAIN_DTYPE", "fp32", str, cache=False)).lower()
    return "bf16" if raw in ("bf16", "bfloat16") else "fp32"


def sharded_feed() -> bool:
    """Whether :func:`fit` pre-shards batches over the mesh
    (``MXNET_SHARDED_FEED``, default on)."""
    return bool(get_env("MXNET_SHARDED_FEED", 1, int, cache=False))


def _f32(x):
    return jnp.asarray(x, dtype=jnp.float32)


def _default_mesh(batch_size: int):
    """Mesh over all local devices, shrunk to the largest count that
    divides the batch (a batch XLA cannot split evenly would otherwise
    fail to shard)."""
    from . import parallel

    devices = jax.devices()
    n = len(devices)
    while n > 1 and batch_size % n:
        n -= 1
    return parallel.device_mesh(n)


def _aval(x):
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)) \
        if not hasattr(x, "dtype") else jax.ShapeDtypeStruct(
            jnp.shape(x), x.dtype)


class _Ineligible(Exception):
    """Internal: the graph plane cannot serve this model/config."""


class _PlaneBase(object):
    """Shared jit plumbing of the gluon and Module planes: host prologue,
    donation bookkeeping, dispatch accounting."""

    @staticmethod
    def _probe_optimizer(opt):
        """Throwaway copy for the trace probe: ``_update_count`` /
        ``_host_scalars`` mutate schedule state (Nadam's m_schedule, rng
        draws), and a failed probe must leave the real optimizer
        untouched. ``param_dict`` holds live Parameters (device arrays) —
        shared by reference, it is only read for lr/wd multipliers."""
        import copy

        pd, opt.param_dict = opt.param_dict, {}
        try:
            probe = copy.deepcopy(opt)
        finally:
            opt.param_dict = pd
        probe.param_dict = pd
        return probe

    def _host_prologue(self, optimizer, indices):
        """Per-index counting + scalar prologue — EXACTLY the sequence the
        eager ``fastpath.fused_apply`` runs, in the same order, so the
        in-graph update consumes bit-identical scalars (Adam's host f64
        bias correction included)."""
        ts, lrs, wds, extras = [], [], [], []
        for i in indices:
            optimizer._update_count(i)
            lr, wd, ex = optimizer._host_scalars(i)
            ts.append(_f32(optimizer._index_update_count[i]))
            lrs.append(_f32(lr))
            wds.append(_f32(wd))
            extras.append(tuple(ex))
        return ts, lrs, wds, extras

    def _donation(self, diff_vals, states):
        """(argnums_ok, consumed) — the shared ``fastpath.fused`` donation
        discipline, single-sourced."""
        from .fastpath.fused import donation_prep

        return donation_prep(diff_vals, states)

    def _invalidate_consumed(self, consumed, live):
        from .fastpath.fused import invalidate_consumed

        invalidate_consumed(consumed, (live,))


# ---------------------------------------------------------------------------
# gluon plane
# ---------------------------------------------------------------------------


class TrainPlane(_PlaneBase):
    """One training step through whichever plane the model supports.

    ``plane = TrainPlane(net, loss_fn, trainer)`` then
    ``loss = plane.step(data, label)`` replaces the canonical eager loop
    body::

        with autograd.record():
            loss = loss_fn(net(data), label)
        loss.backward()
        trainer.step(batch_size)

    With ``MXNET_TRAINSTEP`` at ``auto``/``1`` and a traceable
    (hybridizable) net, the step runs as ONE compiled SPMD module —
    forward, loss, backward, dp all-reduce over the mesh and the optimizer
    update — with the batch sharded over the mesh's ``dp`` axis and
    parameters/optimizer state replicated. Otherwise the exact eager loop
    above runs, so the call site never changes.

    The trainer stays the owner of the optimizer and its state
    (``trainer._updaters[0].states``): checkpoints via
    ``Trainer.save_states`` keep working, and eager/in-graph steps can be
    mixed freely (one step counter, no schedule drift).

    Parameters
    ----------
    net : Block — trained model (HybridBlock for the compiled plane)
    loss_fn : gluon Loss (or callable ``(out, label) -> loss`` NDArray)
    trainer : gluon.Trainer over ``net.collect_params()``
    mesh : optional jax Mesh; default spans all local devices whose count
        divides the batch size
    batch_axis : batch axis of data/label
    """

    def __init__(self, net, loss_fn, trainer, mesh=None, batch_axis=0):
        from . import kvstore as kvs_mod

        self._net = net
        self._loss = loss_fn
        self._trainer = trainer
        self._mesh = mesh
        self._batch_axis = batch_axis
        self._plane: Optional[str] = None  # 'graph' | 'eager'
        self._why_eager: Optional[str] = None
        self._cast = None                  # jnp.bfloat16 under bf16 mode
        self._rows = None                  # [(trainer idx, Parameter)]
        self._const_names = None
        self._zero_broken = None           # sticky zero-trace failure
        self._jits: Dict[Any, Any] = {}
        self.step_count = 0
        # multi-host: join the distributed runtime when a launcher planted
        # MXNET_COORDINATOR_*; no-op (False) in single-process mode
        kvs_mod.init_distributed()

    # -- plane selection -----------------------------------------------
    @property
    def plane(self) -> str:
        return self._plane or "undecided"

    def _demote(self, reason: str):
        FALLBACKS.inc(reason=reason)
        # black box: a plane demotion changes the performance regime —
        # post-mortems must see it next to whatever broke afterwards
        from .telemetry import flightrec

        flightrec.record("trainplane.fallback", reason=reason)
        self._plane = "eager"
        self._why_eager = reason
        if mode() == "1":
            _LOG.warning(
                "MXNET_TRAINSTEP=1 but the graph plane is unavailable "
                "(%s); training continues on the eager path", reason)

    def _ineligible_reason(self, data_nd) -> Optional[str]:
        from . import fastpath

        tr = self._trainer
        if not fastpath.enabled():
            # the legacy escape hatch must reach ALL the way down: with
            # MXNET_FASTPATH=0 an operator is ruling out the fused kernels,
            # and the graph plane is built on the same tree_kernel
            return "MXNET_FASTPATH=0 (legacy escape hatch)"
        if not hasattr(self._net, "_base_fn"):
            return "net is not a HybridBlock (no traceable base_fn)"
        if len(tr._contexts) != 1:
            return "multi-context trainer (eager split_and_load path)"
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._update_on_kvstore:
            return "update_on_kvstore"
        opt = tr._optimizer
        if not getattr(opt, "fastpath_capable", False):
            return "optimizer has no pure _leaf_step kernel"
        params = self._net.collect_params()
        for name, p in params.items():
            if p.grad_req not in ("null", "write"):
                return "grad_req %r on %s" % (p.grad_req, name)
            if p.grad_req != "null" and name not in tr._param2idx:
                return "net parameter %s not owned by the trainer" % name
        return None

    def _activate(self, data_nd, label_nd, batch_size):
        # bf16-by-default training mode: cast the model once, keep fp32
        # master weights in the optimizer state (multi-precision) — a
        # dtype knob, not a plane knob: applies on BOTH planes (including
        # the MXNET_TRAINSTEP=0 eager path)
        if train_dtype() == "bf16":
            self._cast = jnp.bfloat16
            self._materialize(data_nd)
            ctx = self._trainer._contexts[0]
            anyp = next(iter(self._net.collect_params().values()), None)
            if anyp is not None and \
                    anyp.data(ctx)._data.dtype != jnp.bfloat16:
                self._net.cast("bfloat16")
            self._trainer._optimizer.multi_precision = True
        if mode() == "0":
            self._plane = "eager"
            self._why_eager = "MXNET_TRAINSTEP=0"
            return
        reason = self._ineligible_reason(data_nd)
        if reason is not None:
            self._demote(reason)
            return
        try:
            self._prepare_graph(data_nd, label_nd, batch_size)
            self._plane = "graph"
        except Exception as exc:  # noqa: BLE001 - auto-fallback contract:
            # a non-traceable model (host-sync in hybrid_forward, shape-
            # dependent python control flow, ...) must train, not crash
            self._demote("trace: %s" % type(exc).__name__)

    # -- graph plane ----------------------------------------------------
    def _materialize(self, data_nd):
        """Finish deferred init so every parameter has a value."""
        params = self._net.collect_params()
        try:
            for p in params.values():
                p.data(self._trainer._contexts[0])
        except Exception:  # DeferredInitializationError
            with autograd.pause():
                self._net(data_nd)

    def _prepare_graph(self, data_nd, label_nd, batch_size):
        """Resolve rows/mesh and PROBE the whole-step trace (eval_shape:
        no FLOPs, no device buffers, no counter mutation) before the plane
        commits to compiling."""
        tr = self._trainer
        opt = tr._optimizer
        self._materialize(data_nd)
        if self._mesh is None:
            self._mesh = _default_mesh(int(data_nd.shape[self._batch_axis]))
        params = self._net.collect_params()
        rows = []
        for i, p in enumerate(tr._params):
            if p.grad_req != "null":
                rows.append((i, p))
        if not rows:
            raise _Ineligible("no trainable parameters")
        self._rows = rows
        diff_names = {p.name for _, p in rows}
        self._const_names = tuple(n for n in params if n not in diff_names)

        # states must exist for the probe; created EXACTLY as the eager
        # Updater would (same layout, same mp pairs), so a later eager step
        # adopts them unchanged
        updater = tr._updaters[0]
        ctx = tr._contexts[0]
        for i, p in rows:
            w = p.data(ctx)
            if i not in updater.states:
                updater.states[i] = opt.create_state_multi_precision(i, w)
                updater.states_synced[i] = True
            else:
                updater.states[i] = opt_mod.ensure_mp_state(
                    opt, i, w, updater.states[i])

        probe_opt = self._probe_optimizer(opt)
        probe_opt.rescale_grad = tr._scale / batch_size
        ts, lrs, wds, extras = self._host_prologue(
            probe_opt, [i for i, _ in rows])
        step_fn = self._build_step(probe_opt, tuple(
            self._mp_flags(probe_opt, updater)))
        # probe on the CURRENT values' avals, NOT on _gather's output: a
        # failed probe must leave params un-replicated, or the eager
        # fallback would mix mesh-committed params with single-device
        # batches (replication happens in _graph_step, after the plane
        # commits)
        raw_diff = [p.data(ctx)._data for _, p in rows]
        raw_const = {n: params[n].data(ctx)._data
                     for n in self._const_names}
        raw_states = [updater.states[i] for i, _ in rows]
        d = data_nd._data if isinstance(data_nd, NDArray) \
            else jnp.asarray(data_nd)
        l = label_nd._data if isinstance(label_nd, NDArray) \
            else jnp.asarray(label_nd)
        avals = jax.tree_util.tree_map(
            _aval, (raw_diff, raw_const, raw_states,
                    ts, lrs, wds, extras, d, l, _global_key()))
        jax.eval_shape(step_fn, *avals)

    def _mp_flags(self, optimizer, updater):
        from .fastpath.fused import _is_mp_state

        ctx = self._trainer._contexts[0]
        return [_is_mp_state(optimizer, i, p.data(ctx), updater.states[i])
                for i, p in self._rows]

    def _gather(self, updater, with_states=True):
        """Current param/state values as jax arrays, replicated over the
        mesh (fresh buffer on first touch — later steps' outputs come back
        replicated and skip the put). With the ZeRO plane active the
        optimizer state lives dp-sharded in the plane's buckets and is
        NOT gathered here (``with_states=False``) — replicating it would
        silently undo the sharding."""
        from . import parallel

        ctx = self._trainer._contexts[0]
        params = self._net.collect_params()
        repl = NamedSharding(self._mesh, P())

        def repl_val(nd):
            v = nd._data
            sh = getattr(v, "sharding", None)
            if sh is None or not sh.is_equivalent_to(repl, v.ndim):
                v = parallel.fresh_replicate(v, self._mesh)
                nd._data = v
            return v

        diff = [repl_val(p.data(ctx)) for _, p in self._rows]
        const = {n: repl_val(params[n].data(ctx)) for n in self._const_names}
        out = {"diff": diff, "const": const}
        if with_states:
            states = [jax.tree_util.tree_map(
                lambda x: x if getattr(x, "sharding", None) is not None
                and x.sharding.is_equivalent_to(repl, x.ndim)
                else parallel.fresh_replicate(x, self._mesh),
                updater.states[i]) for i, _ in self._rows]
            for (i, _), s in zip(self._rows, states):
                updater.states[i] = s
            out["states"] = states
        return out

    def _build_step(self, optimizer, mp_flags):
        """The whole-step function: fwd + loss + bwd (+ GSPMD-inserted dp
        all-reduce) + the fastpath tree kernel, traced as ONE program."""
        from . import fastpath

        base_fn = self._net._base_fn([0], train=True)
        kernel = fastpath.tree_kernel(optimizer, mp_flags)
        diff_names = tuple(p.name for _, p in self._rows)
        loss_fn = self._loss
        cast = self._cast

        def step(diff_vals, const_vals, states, ts, lrs, wds, extras,
                 data, label, rng):
            if cast is not None and jnp.issubdtype(data.dtype, jnp.floating):
                data = data.astype(cast)

            def f(dv):
                pv = dict(const_vals)
                pv.update(zip(diff_names, dv))
                outs, aux = base_fn(pv, rng, data)
                out0 = outs[0] if isinstance(outs, tuple) else outs
                with autograd._RecordingStateScope(False, None):
                    l_nd = loss_fn(NDArray(out0, cpu()),
                                   NDArray(label, cpu()))
                return l_nd._data, aux

            loss, vjp_fn, aux = jax.vjp(f, list(diff_vals), has_aux=True)
            # the same all-ones cotangent loss.backward() seeds eagerly
            (grads,) = vjp_fn(jnp.ones(loss.shape, loss.dtype))
            new_ws, new_sts = kernel(
                list(diff_vals), grads, states, ts, lrs, wds, extras)
            return loss, new_ws, new_sts, aux

        return step

    # -- ZeRO: the sharded state plane inside the step jit ---------------
    def _zero_acquire(self, opt, updater):
        """The updater's ZeroPlane for this step, or None for the
        replicated layout — decided per call so a flipped ``MXNET_ZERO``
        takes effect (and materializes) without re-activation. Every
        decline lands in ``mxnet_zero_fallbacks_total``."""
        from .fastpath import zero

        lv = zero.level()
        if lv == 0 or self._zero_broken is not None:
            if zero.plane_of(updater) is not None:
                zero.materialize_updater(updater)
            return None
        reason = zero.eligible_reason(opt, len(self._mesh.devices.flat))
        if reason is not None:
            zero.note_fallback(reason)
            if zero.plane_of(updater) is not None:
                zero.materialize_updater(updater)
            return None
        ctx = self._trainer._contexts[0]
        weights = [p.data(ctx) for _, p in self._rows]
        try:
            return zero.acquire_plane(updater, opt, self._mesh, lv,
                                      [i for i, _ in self._rows], weights)
        except Exception as exc:  # noqa: BLE001 - never-a-crash: a failed
            # adopt falls back to the replicated layout, counted
            zero.note_fallback("adopt: %s" % type(exc).__name__)
            zero.materialize_updater(updater)
            return None

    def _build_zero_step(self, optimizer, zp):
        """The whole-step function over the SHARDED state plane: fwd +
        loss + bwd, then ``fastpath.zero.traced_update`` — the packed
        gradients constrained to the dp shards (GSPMD lowers the pending
        batch-axis reduction to a reduce-scatter), the shard-local bucket
        kernel, and an all-gather of ONLY the updated weights — traced
        as ONE program."""
        base_fn = self._net._base_fn([0], train=True)
        diff_names = tuple(p.name for _, p in self._rows)
        loss_fn = self._loss
        cast = self._cast

        def step(diff_vals, const_vals, buckets, tvs, lrvs, wdvs,
                 data, label, rng):
            if cast is not None and jnp.issubdtype(data.dtype, jnp.floating):
                data = data.astype(cast)

            def f(dv):
                pv = dict(const_vals)
                pv.update(zip(diff_names, dv))
                outs, aux = base_fn(pv, rng, data)
                out0 = outs[0] if isinstance(outs, tuple) else outs
                with autograd._RecordingStateScope(False, None):
                    l_nd = loss_fn(NDArray(out0, cpu()),
                                   NDArray(label, cpu()))
                return l_nd._data, aux

            loss, vjp_fn, aux = jax.vjp(f, list(diff_vals), has_aux=True)
            (grads,) = vjp_fn(jnp.ones(loss.shape, loss.dtype))
            new_ws, new_buckets = zp.traced_update(
                optimizer, list(diff_vals), grads, buckets,
                tvs, lrvs, wdvs)
            return loss, new_ws, new_buckets, aux

        return step

    def _zero_graph_call(self, zp, opt, updater, fts, flrs, fwds,
                         d, l, rng):
        """Dispatch one sharded whole-step jit and commit its outputs:
        weights replicated back onto the params, state buckets staying in
        their dp shards (``updater.states`` keeps the handles)."""
        ctx = self._trainer._contexts[0]
        args = self._gather(updater, with_states=False)
        tvs, lrvs, wdvs = zp.expand_scalars(fts, flrs, fwds)
        argnums, consumed = self._donation(args["diff"], zp.buckets)
        # zp.sig carries indices/plan/level/mesh/mp — the sharded twin of
        # the replicated key's mp_flags: a row added after activation (or
        # any relayout) must miss here, not reuse a jit whose closure
        # holds the OLD plane's diff names and bucket layout
        key = ("zero", zp.sig, tuple(d.shape), str(d.dtype),
               tuple(l.shape), str(l.dtype), opt.rescale_grad,
               opt.clip_gradient, argnums)
        fn = self._jits.get(key)
        if fn is None:
            repl = NamedSharding(self._mesh, P())
            fn = jax.jit(
                self._build_zero_step(opt, zp),
                out_shardings=(repl, [repl] * len(self._rows),
                               zp.sharding_tree(), repl),
                donate_argnums=(0, 2) if argnums else ())
            self._jits[key] = fn
        loss, new_ws, new_buckets, aux = telemetry.jit_call(
            "trainplane.step", fn, args["diff"], args["const"],
            zp.buckets, tvs, lrvs, wdvs, d, l, rng)
        zp.buckets = new_buckets

        params = self._net.collect_params()
        for (_i, p), nw in zip(self._rows, new_ws):
            p.data(ctx)._data = nw
        for name, val in aux.items():
            params[name].data(ctx)._data = val
        self._invalidate_consumed(consumed, (new_ws, new_buckets))
        telemetry.STEP_DISPATCHES.inc(plane="graph")
        telemetry.sample_hbm()
        return NDArray(loss, ctx)

    def _graph_step(self, data_nd, label_nd, batch_size):
        tr = self._trainer
        opt = tr._optimizer
        updater = tr._updaters[0]
        ctx = tr._contexts[0]
        from . import parallel
        from .fastpath import zero as zero_mod

        opt.rescale_grad = tr._scale / batch_size  # Trainer.step parity
        for i, p in self._rows:  # states for rows added after activation
            if i not in updater.states:
                updater.states[i] = opt.create_state_multi_precision(
                    i, p.data(ctx))
                updater.states_synced[i] = True
        d = parallel.shard_to_mesh(data_nd, self._mesh, self._batch_axis)
        l = parallel.shard_to_mesh(label_nd, self._mesh, self._batch_axis)
        rng = _global_key()
        indices = [i for i, _ in self._rows]

        zp = self._zero_acquire(opt, updater)
        if zp is not None:
            # zero's float prologue — the SAME count/scalars sequence,
            # plain floats for expand_scalars (no device scalar bounce)
            fts, flrs, fwds = [], [], []
            for i in indices:
                opt._update_count(i)
                lr, wd, _ex = opt._host_scalars(i)
                fts.append(float(opt._index_update_count[i]))
                flrs.append(float(lr))
                fwds.append(float(wd))
            try:
                return self._zero_graph_call(zp, opt, updater,
                                             fts, flrs, fwds, d, l, rng)
            except Exception as exc:  # noqa: BLE001 - never-a-crash: the
                # sharded trace failing must not kill training; the
                # replicated step below reuses the SAME prologue scalars
                # (counters already advanced — no double count)
                from .resilience import hbm as hbm_mod

                # a ZeRO-step OOM commits the structured HBM diagnostic
                # (bucket-bytes bound included) to a flightrec dump and
                # latches the governor BEFORE the fallback — no-op for
                # every non-OOM trace failure
                hbm_mod.oom_survival("fastpath.zero", exc, dump=True)
                zero_mod.note_fallback("trace: %s" % type(exc).__name__)
                zero_mod.materialize_updater(updater)
                self._zero_broken = type(exc).__name__
                # a state lost to a failed DONATED execution cannot be
                # materialized — recreate it fresh so the replicated step
                # below still runs (momenta reset beats a dead run)
                for i, p in self._rows:
                    if i not in updater.states:
                        updater.states[i] = \
                            opt.create_state_multi_precision(i, p.data(ctx))
                        updater.states_synced[i] = True
                ts = [_f32(t) for t in fts]
                lrs = [_f32(x) for x in flrs]
                wds = [_f32(x) for x in fwds]
                extras = [() for _ in indices]
        else:
            ts, lrs, wds, extras = self._host_prologue(opt, indices)
        mp_flags = tuple(self._mp_flags(opt, updater))
        args = self._gather(updater)

        argnums, consumed = self._donation(args["diff"], args["states"])
        key = (tuple(d.shape), str(d.dtype), tuple(l.shape), str(l.dtype),
               opt.rescale_grad, opt.clip_gradient, mp_flags, argnums,
               tuple(len(e) for e in extras))
        fn = self._jits.get(key)
        if fn is None:
            repl = NamedSharding(self._mesh, P())
            fn = jax.jit(self._build_step(opt, mp_flags),
                         out_shardings=(repl, repl, repl, repl),
                         donate_argnums=(0, 2) if argnums else ())
            self._jits[key] = fn
        loss, new_ws, new_sts, aux = telemetry.jit_call(
            "trainplane.step", fn, args["diff"], args["const"],
            args["states"], ts, lrs, wds, extras, d, l, rng)

        params = self._net.collect_params()
        for (i, p), nw, ns in zip(self._rows, new_ws, new_sts):
            p.data(ctx)._data = nw
            updater.states[i] = ns
        for name, val in aux.items():
            params[name].data(ctx)._data = val
        self._invalidate_consumed(consumed, (new_ws, new_sts))
        telemetry.STEP_DISPATCHES.inc(plane="graph")
        telemetry.sample_hbm()
        return NDArray(loss, ctx)

    # -- eager plane ----------------------------------------------------
    def _eager_step(self, data_nd, label_nd, batch_size):
        if self._cast is not None and \
                jnp.issubdtype(data_nd._data.dtype, jnp.floating):
            data_nd = NDArray(data_nd._data.astype(self._cast),
                              data_nd.context)
        with autograd.record():
            out = self._net(data_nd)
            loss = self._loss(out, label_nd)
        loss.backward()
        self._trainer.step(batch_size)
        telemetry.STEP_DISPATCHES.inc(plane="eager")
        return loss

    # -- entry ----------------------------------------------------------
    def step(self, data, label, batch_size=None):
        """Run one training step; returns the (per-sample) loss NDArray."""
        data_nd = data if isinstance(data, NDArray) else NDArray(
            jnp.asarray(data), cpu())
        label_nd = label if isinstance(label, NDArray) else NDArray(
            jnp.asarray(label), cpu())
        if batch_size is None:
            batch_size = int(data_nd.shape[self._batch_axis])
        if self._plane is None:
            self._activate(data_nd, label_nd, batch_size)
        self.step_count += 1
        if self._plane == "graph":
            # devprof step scope: one coherent sampling decision for the
            # whole step so its device/host_gap split is honest. Eager
            # plane is deliberately unscoped — it dispatches op-by-op
            # outside jit_call, so there is no device time to attribute.
            if _devprof.tick_begin():
                t0 = time.perf_counter()
                try:
                    return self._graph_step_guarded(data_nd, label_nd,
                                                    batch_size)
                finally:
                    _devprof.note_train_step(
                        (time.perf_counter() - t0) * 1e3)
            return self._graph_step_guarded(data_nd, label_nd, batch_size)
        return self._eager_step(data_nd, label_nd, batch_size)

    def _graph_step_guarded(self, data_nd, label_nd, batch_size):
        """Never-a-crash at the graph plane's own dispatch: a step
        failure that classifies as OOM (real ``RESOURCE_EXHAUSTED`` or
        chaos ``action=oom``) first lands the structured HBM diagnostic
        — per-plane registered bounds + watermark history — in a
        flight-recorder dump (``hbm.oom_survival``), then demotes to the
        eager plane and runs the step there: training continues, the
        post-mortem is on disk. Anything non-OOM still propagates —
        a programming error must fail fast, not hide behind a fallback.
        Best-effort caveat: a real OOM *mid-execution* may have consumed
        donated param buffers (nothing can resurrect those); the
        injected-OOM path raises before dispatch and always survives."""
        from .resilience import hbm as hbm_mod

        try:
            return self._graph_step(data_nd, label_nd, batch_size)
        except Exception as exc:  # noqa: BLE001 - OOM-only survival
            if not hbm_mod.oom_survival("trainplane.step", exc,
                                        dump=True):
                raise
            self._demote("oom: %s" % type(exc).__name__)
            return self._eager_step(data_nd, label_nd, batch_size)

    @property
    def mesh(self):
        return self._mesh

    def feed_sharding(self, ndim: int):
        """The NamedSharding batches should arrive in (pre-sharded feed)."""
        from . import parallel

        if self._mesh is None:
            return None
        return parallel.batch_sharding(self._mesh, ndim, self._batch_axis)


def _global_key():
    from . import _global

    return _global.next_key()


# ---------------------------------------------------------------------------
# epoch-loop convenience
# ---------------------------------------------------------------------------


def fit(net, loss_fn, trainer, train_data, epochs=1, batch_axis=0,
        mesh=None, batch_end_callback=None, checkpoint=None,
        checkpoint_every=1, resume=True):
    """Train ``net`` over ``train_data`` through the active plane.

    ``train_data`` yields ``io.DataBatch``es (any ``DataIter``) or
    ``(data, label)`` pairs. With the graph plane active and
    ``MXNET_SHARDED_FEED`` on, batches are staged ahead of the step by a
    ``DevicePrefetchIter`` laid out over the mesh's ``dp`` axis, so the
    step never pays a dispatch-serializing ``device_put``. Returns the
    :class:`TrainPlane` (inspect ``plane.plane`` for which path ran).

    ``checkpoint`` (an ``elastic.CheckpointManager``) makes the loop
    preemption-aware: it resumes net/trainer/iterator/RNG from the
    latest committed epoch (``resume=True`` — mid-epoch preemption saves
    resume mid-epoch, replaying nothing), calls
    ``elastic.step_boundary`` before every batch (the stall heartbeat,
    the kill-at-step chaos site, and the SIGTERM/preemption-file
    checkpoint-now), and commits an async sharded-aware checkpoint every
    ``checkpoint_every`` epochs. Wrap the whole call in
    ``elastic.run_elastic`` for supervised restarts.
    """
    from . import io as io_mod

    plane = TrainPlane(net, loss_fn, trainer, mesh=mesh,
                       batch_axis=batch_axis)
    feed = train_data
    if sharded_feed() and mode() != "0" and \
            isinstance(train_data, io_mod.DataIter) and \
            not isinstance(train_data, io_mod.DevicePrefetchIter) and \
            getattr(train_data, "provide_data", None):
        bs = train_data.provide_data[0].shape[batch_axis]
        if plane._mesh is None:
            plane._mesh = _default_mesh(int(bs))
        feed = io_mod.DevicePrefetchIter(
            train_data, sharding=plane.feed_sharding)

    start, mid = 0, False
    if checkpoint is not None and resume:
        from . import elastic

        restored = checkpoint.restore_training(net=net, trainer=trainer,
                                               train_iter=feed)
        if restored >= 0:
            extra = checkpoint.last_restored_extra or {}
            mid = bool(extra.get("mid_epoch"))
            start = restored if mid else restored + 1

    first_pass = True
    for epoch in range(start, epochs):
        # reset before every epoch except the very first pass when the
        # iterator is fresh — or carries a restored mid-epoch cursor
        if hasattr(feed, "reset") and (not first_pass
                                       or (epoch and not mid)):
            feed.reset()
        first_pass = False
        nbatch = 0
        feed_iter = iter(feed)
        while True:
            if checkpoint is not None:
                from . import elastic

                # BEFORE the fetch: a preemption save here records an
                # iterator cursor where every consumed batch was trained
                elastic.step_boundary(
                    manager=checkpoint,
                    save_fn=lambda: checkpoint.save_training(
                        epoch, net=net, trainer=trainer, train_iter=feed,
                        extra={"mid_epoch": True}))
            try:
                batch = next(feed_iter)
            except StopIteration:
                break
            if isinstance(batch, io_mod.DataBatch):
                data, label = batch.data[0], batch.label[0]
            else:
                data, label = batch
            loss = plane.step(data, label)
            nbatch += 1
            if batch_end_callback is not None:
                batch_end_callback(epoch, nbatch, loss)
        if checkpoint is not None and (
                (epoch + 1) % max(1, checkpoint_every) == 0
                or epoch == epochs - 1):
            checkpoint.save_training(epoch, net=net, trainer=trainer,
                                     train_iter=feed,
                                     extra={"mid_epoch": False},
                                     async_save=True)
    if checkpoint is not None:
        checkpoint.wait()
    return plane


# ---------------------------------------------------------------------------
# Module plane (Module.fit / model.fit / FeedForward.fit)
# ---------------------------------------------------------------------------


class _ModulePlane(_PlaneBase):
    """Whole-step jit over a bound ``Module``: the Symbol graph's forward,
    the all-ones-seeded backward and the fastpath update kernel in one
    compiled module per batch signature. Single-context modules only (the
    multi-context Module path stays on the eager executor group); the step
    still collapses forward/backward/update into ONE dispatch."""

    def __init__(self, module):
        self._m = module
        self._exec = module._exec_group.execs[0]
        self._ctx = module._context[0]
        exec_ = self._exec
        param_names = [n for n in module._symbol.list_arguments()
                       if n in module._param_names]
        self._entries = []
        for idx, name in enumerate(param_names):
            req = exec_.grad_req.get(name, "null")
            if name in exec_.grad_dict and req == "write":
                self._entries.append((idx, name))
            elif req not in ("null", "write"):
                # 'add' (and anything else) accumulates across calls — a
                # host-visible side effect the compiled step can't honor.
                # Demote rather than silently freezing the param as a jit
                # constant while the eager path would keep training it.
                raise _Ineligible("grad_req %r on %s" % (req, name))
        if not self._entries:
            raise _Ineligible("no trainable parameters")
        self._diff_names = tuple(n for _, n in self._entries)
        self._jits: Dict[Any, Any] = {}
        self._sig = None        # cached const-signature for the jit key —
        self._sig_batch = None  # only the batch arrays ever change shape
        self._probe()

    def _probe(self):
        m = self._m
        exec_ = self._exec
        opt = self._probe_optimizer(m._optimizer)
        updater = m._updater
        for idx, name in self._entries:
            if idx not in updater.states:
                updater.states[idx] = m._optimizer \
                    .create_state_multi_precision(idx, exec_.arg_dict[name])
                updater.states_synced[idx] = True
        ts, lrs, wds, extras = self._host_prologue(
            opt, [i for i, _ in self._entries])
        step_fn = self._build_step(opt, tuple(self._mp_flags(opt)))
        args = self._args()
        avals = jax.tree_util.tree_map(
            _aval, (args["diff"], args["const"], args["aux"],
                    args["states"], ts, lrs, wds, extras, _global_key()))
        jax.eval_shape(step_fn, *avals)

    def _mp_flags(self, optimizer):
        from .fastpath.fused import _is_mp_state

        updater = self._m._updater
        return [_is_mp_state(optimizer, i, self._exec.arg_dict[n],
                             updater.states[i]) for i, n in self._entries]

    def _args(self):
        exec_ = self._exec
        updater = self._m._updater
        diff = [exec_.arg_dict[n]._data for _, n in self._entries]
        const = {n: a._data for n, a in exec_.arg_dict.items()
                 if n not in self._diff_names}
        aux = {n: a._data for n, a in exec_.aux_dict.items()}
        states = [updater.states[i] for i, _ in self._entries]
        return {"diff": diff, "const": const, "aux": aux, "states": states}

    def _build_step(self, optimizer, mp_flags):
        from . import _global, fastpath

        sym = self._m._symbol
        kernel = fastpath.tree_kernel(optimizer, mp_flags)
        diff_names = self._diff_names

        def run_graph(arg_vals, aux_vals, rng):
            prev = _global.set_train(True)
            _global.push_rng_key(rng)
            try:
                vm = dict(arg_vals)
                vm.update(aux_vals)
                aux_updates = {}
                outs = sym.eval_jax(vm, aux_updates=aux_updates)
            finally:
                _global.pop_rng_key()
                _global.set_train(prev)
            return tuple(outs), aux_updates

        def step(diff_vals, const_vals, aux_vals, states, ts, lrs, wds,
                 extras, rng):
            def f(dv):
                av = dict(const_vals)
                av.update(zip(diff_names, dv))
                return run_graph(av, aux_vals, rng)

            outs, vjp_fn, aux_updates = jax.vjp(
                f, list(diff_vals), has_aux=True)
            # backward(out_grads=None) parity: all-ones head gradients
            (grads,) = vjp_fn(tuple(
                jnp.ones(o.shape, o.dtype) for o in outs))
            new_ws, new_sts = kernel(
                list(diff_vals), grads, states, ts, lrs, wds, extras)
            return outs, aux_updates, new_ws, new_sts

        return step

    def step(self, batch):
        """One whole-graph training step for a DataBatch; fills the
        executor's outputs so ``update_metric`` reads them as usual."""
        if _devprof.tick_begin():
            t0 = time.perf_counter()
            try:
                return self._step(batch)
            finally:
                _devprof.note_train_step((time.perf_counter() - t0) * 1e3)
        return self._step(batch)

    def _step(self, batch):
        m = self._m
        exec_ = self._exec
        opt = m._optimizer
        updater = m._updater
        group = m._exec_group
        # stage the batch into the (traced-operand) arg values
        for name, arr in zip(group.data_names, batch.data):
            exec_.arg_dict[name]._data = arr._data
        if group.label_names and batch.label:
            for name, arr in zip(group.label_names, batch.label):
                exec_.arg_dict[name]._data = arr._data
        for idx, name in self._entries:
            if idx not in updater.states:
                updater.states[idx] = opt.create_state_multi_precision(
                    idx, exec_.arg_dict[name])
                updater.states_synced[idx] = True
        ts, lrs, wds, extras = self._host_prologue(
            opt, [i for i, _ in self._entries])
        mp_flags = tuple(self._mp_flags(opt))
        args = self._args()
        rng = _global_key()
        argnums, consumed = self._donation(args["diff"], args["states"])
        # const = fixed params + the staged batch; only the batch arrays
        # can change shape between steps, so the sorted full-signature walk
        # (O(n log n) host work on the one-dispatch hot path) is rebuilt
        # only when the batch signature does
        batch_sig = tuple((tuple(a.shape), str(a.dtype))
                          for b in (batch.data, batch.label or ())
                          for a in b)
        if batch_sig != self._sig_batch:
            self._sig = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                                     for n, v in args["const"].items()))
            self._sig_batch = batch_sig
        key = (self._sig, opt.rescale_grad, opt.clip_gradient, mp_flags,
               argnums, tuple(len(e) for e in extras))
        fn = self._jits.get(key)
        if fn is None:
            fn = jax.jit(self._build_step(opt, mp_flags),
                         donate_argnums=(0, 3) if argnums else ())
            self._jits[key] = fn
        outs, aux_updates, new_ws, new_sts = telemetry.jit_call(
            "trainplane.module_step", fn, args["diff"], args["const"],
            args["aux"], args["states"], ts, lrs, wds, extras, rng)

        for (i, n), nw, ns in zip(self._entries, new_ws, new_sts):
            exec_.arg_dict[n]._data = nw
            updater.states[i] = ns
        for name, val in aux_updates.items():
            if name in exec_.aux_dict:
                exec_.aux_dict[name]._data = val
        exec_.outputs = [NDArray(o, self._ctx) for o in outs]
        exec_._output_shapes = [o.shape for o in outs]
        exec_._residuals = None
        m._params_dirty = True
        self._invalidate_consumed(consumed, (new_ws, new_sts))
        telemetry.STEP_DISPATCHES.inc(plane="graph")
        return exec_.outputs


def module_plane(module):
    """Build the whole-step graph plane for a bound, optimizer-initialized
    ``Module`` — or return ``None`` when the eager executor path must run
    (``MXNET_TRAINSTEP=0``, multi-context, kvstore exchange, custom
    grad_req, non-traceable graph, ...). ``BaseModule.fit`` calls this once
    per fit and falls back silently: routing must never break training."""
    if mode() == "0":
        return None
    try:
        from .module.module import Module
    except ImportError:
        return None
    if type(module) is not Module:
        return None
    from . import fastpath

    try:
        if not fastpath.enabled() \
                or len(module._context) != 1 or module._kvstore is not None \
                or module._update_on_kvstore \
                or not isinstance(module._updater, opt_mod.Updater) \
                or not getattr(module._optimizer, "fastpath_capable", False) \
                or module._exec_group is None \
                or len(module._exec_group.execs) != 1 \
                or module._exec_group.state_names \
                or module.inputs_need_grad:
            FALLBACKS.inc(reason="module-config")
            return None
        return _ModulePlane(module)
    except Exception as exc:  # noqa: BLE001 - auto-fallback contract
        FALLBACKS.inc(reason="module-trace: %s" % type(exc).__name__)
        if mode() == "1":
            _LOG.warning(
                "MXNET_TRAINSTEP=1 but Module.fit cannot use the graph "
                "plane (%s); the eager executor path runs instead", exc)
        return None
