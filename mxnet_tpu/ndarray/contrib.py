"""Eager control flow: ``mx.nd.contrib.foreach/while_loop/cond``.

Reference ``python/mxnet/ndarray/contrib.py:134,230,398``. The eager path
unrolls the loop in Python exactly like the reference's imperative mode —
every iteration's ops land on the autograd tape, so gradients flow through
loop state AND free variables with no special casing. The compiled
(Symbol / hybridized) path instead lowers to one lax.scan / masked-scan /
lax.cond via ``ops/control_flow.py``.
"""
from __future__ import annotations

from ..base import MXNetError, flatten_list as _flatten, regroup_list as _regroup
from .ndarray import NDArray
from . import ndarray as nd_mod

__all__ = ["foreach", "while_loop", "cond"]


def _to_scalar(x, type_, what):
    if isinstance(x, NDArray):
        x = x.asnumpy().reshape(-1)[0]
    try:
        return type_(x)
    except (TypeError, ValueError):
        raise MXNetError("Cannot convert %s to python %s"
                         % (what, type_.__name__))


def foreach(body, data, init_states):
    """Unrolled for-loop over axis 0 (reference ndarray/contrib.py:134):
    ``out, states = body(data_slice, states)``; outputs stacked on a new
    leading axis, final states returned."""
    flat_data, data_fmt = _flatten(data)
    if not flat_data or not all(isinstance(d, NDArray) for d in flat_data):
        raise MXNetError("data should be an NDArray or nested list of them")
    num_iters = flat_data[0].shape[0]
    if num_iters == 0:
        raise MXNetError("foreach: data must have a non-empty leading axis")
    if any(d.shape[0] != num_iters for d in flat_data):
        raise MXNetError(
            "foreach: all data arrays must share the same leading dimension; "
            "got %s" % ([d.shape[0] for d in flat_data],))
    states = init_states
    outputs = []
    out_fmt = 0
    for i in range(num_iters):
        eles, _ = _regroup([d[i] for d in flat_data], data_fmt)
        outs, states = body(eles, states)
        outs, out_fmt = _flatten(outs)
        outputs.append(outs)
    stacked = [nd_mod.stack(*col) for col in zip(*outputs)]
    outputs, _ = _regroup(stacked, out_fmt)
    return outputs, states


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Bounded while (reference ndarray/contrib.py:230): iterate
    ``step_out, loop_vars = func(*loop_vars)`` while ``cond(*loop_vars)``
    and fewer than ``max_iterations`` steps; outputs are stacked into
    buffers with leading size max_iterations (rows past the last executed
    step are zero; the reference leaves them undefined)."""
    if max_iterations is None:
        raise MXNetError("max_iterations should be specified")
    max_iterations = _to_scalar(max_iterations, int, "max_iterations")
    flat_vars, var_fmt = _flatten(loop_vars)
    if not flat_vars:
        raise MXNetError("loop_vars should contain at least one element")

    steps = 0
    outputs = []
    out_fmt = None
    cur = list(flat_vars)
    while steps < max_iterations and \
            _to_scalar(cond(*cur), bool, "return value of cond"):
        step_out, new_vars = func(*cur)
        if step_out is None:
            step_out = []
        step_out, out_fmt = _flatten(step_out)
        new_vars, _ = _flatten(new_vars)
        if len(new_vars) != len(cur):
            raise MXNetError(
                "the length of loop_vars should be consistent during the loop")
        cur = list(new_vars)
        outputs.append(step_out)
        steps += 1
        if len(step_out) != len(outputs[0]):
            raise MXNetError("number of elements in step_output should be "
                             "the same in each step")
    stacked = []
    for items in zip(*outputs):
        buf = nd_mod.stack(*items)
        if steps_pad := max_iterations - len(items):
            pad = nd_mod.zeros((steps_pad,) + tuple(items[0].shape),
                               dtype=items[0].dtype, ctx=items[0].context)
            buf = nd_mod.concat(buf, pad, dim=0)
        stacked.append(buf)
    if out_fmt is not None and outputs:
        outputs, _ = _regroup(stacked, out_fmt)
    else:
        outputs = []
    final_vars, _ = _regroup(cur, var_fmt)
    return outputs, final_vars


def cond(pred, then_func, else_func):
    """Eager branch (reference ndarray/contrib.py:398): evaluates ``pred``
    to a host bool and runs exactly one branch — the reference's imperative
    semantics (the compiled path uses lax.cond instead)."""
    if _to_scalar(pred, bool, "pred"):
        return then_func()
    return else_func()


def _export_contrib_ops():
    """Expose every registered _contrib_* op under its short name here
    (reference mx.nd.contrib.box_nms etc.)."""
    import sys

    pkg = sys.modules["mxnet_tpu.ndarray"]
    for flat in dir(pkg):
        if flat.startswith("_contrib_"):
            globals().setdefault(flat[len("_contrib_"):], getattr(pkg, flat))


_export_contrib_ops()
