"""`mx.nd` namespace: NDArray plus one generated function per registered op —
the counterpart of the reference's import-time codegen from the C op registry
(`python/mxnet/ndarray/register.py`)."""
import sys as _sys

from ..ops.registry import OP_REGISTRY as _REG
from .ndarray import (
    NDArray,
    invoke,
    array,
    zeros,
    ones,
    full,
    empty,
    arange,
    eye,
    concat,
    stack,
    waitall,
    onehot_encode,
    concatenate,
    moveaxis,
    histogram,
    logical_and,
    logical_or,
    logical_xor,
    modulo,
    true_divide,
    imdecode,
    to_dlpack_for_read,
    to_dlpack_for_write,
    from_dlpack,
)
from . import random  # noqa: F401
from . import sparse  # noqa: F401
from .sparse import RowSparseNDArray, CSRNDArray
from . import io_utils  # noqa: F401
from .io_utils import save, load


def _make_op_func(_name):
    _param_names = list(_REG[_name].params.keys())

    def _fn(*args, out=None, **kwargs):
        # MXNet generated-wrapper convention: leading positional args that are
        # arrays are op inputs; trailing positional scalars map onto the op's
        # parameters in declaration order (e.g. nd.clip(x, 0, 1)).
        arrays = []
        scalars = []
        for a in args:
            if isinstance(a, NDArray) or a is None or (
                not isinstance(a, (int, float, str, tuple, list, bool)) and hasattr(a, "shape")
            ):
                arrays.append(a)
            else:
                scalars.append(a)
        if scalars:
            free = [p for p in _param_names if p not in kwargs]
            for p, v in zip(free, scalars):
                kwargs[p] = v
        return invoke(_name, *arrays, out=out, **kwargs)

    _fn.__name__ = _name
    _fn.__qualname__ = _name
    _fn.__doc__ = _REG[_name].doc
    return _fn


_mod = _sys.modules[__name__]
for _opname in list(_REG):
    if not hasattr(_mod, _opname):
        setattr(_mod, _opname, _make_op_func(_opname))

# common aliases kept by the reference nd namespace
add = getattr(_mod, "broadcast_add")
subtract = getattr(_mod, "broadcast_sub")
multiply = getattr(_mod, "broadcast_mul")
divide = getattr(_mod, "broadcast_div")
power = getattr(_mod, "broadcast_power")
maximum = getattr(_mod, "broadcast_maximum")
minimum = getattr(_mod, "broadcast_minimum")
equal = getattr(_mod, "broadcast_equal")
not_equal = getattr(_mod, "broadcast_not_equal")
greater = getattr(_mod, "broadcast_greater")
greater_equal = getattr(_mod, "broadcast_greater_equal")
lesser = getattr(_mod, "broadcast_lesser")
lesser_equal = getattr(_mod, "broadcast_lesser_equal")
negative = getattr(_mod, "negative")
split = getattr(_mod, "SliceChannel")

from . import contrib  # noqa: E402,F401  (control flow: foreach/while_loop/cond)
