"""Sparse NDArray storage types: row_sparse and csr.

Capability parity with reference `include/mxnet/ndarray.h:62-66` +
`python/mxnet/ndarray/sparse.py`. XLA has no native sparse storage
(SURVEY.md §7.3), so these are index+value pairs whose ops lower to
gather/scatter/segment-sum — the TPU-idiomatic encoding. They exist for the
embedding/optimizer workflows: sparse gradients (Embedding sparse_grad),
lazy sparse optimizer updates, and row_sparse_pull in KVStore.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, array, invoke

__all__ = [
    "RowSparseNDArray",
    "CSRNDArray",
    "row_sparse_array",
    "csr_matrix",
    "cast_storage",
    "retain",
    "dot",
]


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """values (nnz_rows, *row_shape) + sorted unique row indices (nnz_rows,).

    reference: row_sparse chunks in ndarray.h; used for embedding grads and
    PS-style on-demand row pulls."""

    __slots__ = ("_values", "_indices", "_full_shape")

    def __init__(self, values, indices, shape, ctx: Optional[Context] = None):
        ctx = ctx or current_context()
        # indices must be in canonical (ascending) form: the sparse ex
        # kernels binary-search them. row_sparse_array() sorts user input
        # on the host; internal producers emit sorted indices by
        # construction, so no device sync happens here.
        self._values = values if not isinstance(values, NDArray) else values._data
        self._indices = indices if not isinstance(indices, NDArray) else indices._data
        self._full_shape = tuple(shape)
        dense = jnp.zeros(shape, dtype=self._values.dtype).at[self._indices.astype(jnp.int32)].set(self._values)
        super().__init__(dense, ctx)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._indices, self._ctx)

    @property
    def data(self) -> NDArray:
        return NDArray(self._values, self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cannot cast row_sparse to %r" % stype)

    def retain(self, row_ids) -> "RowSparseNDArray":
        return invoke("_sparse_retain", self, row_ids)

    def __repr__(self):
        return "\n<RowSparseNDArray %s @%s>" % ("x".join(map(str, self.shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (data, indices, indptr)."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr", "_full_shape")

    def __init__(self, data, indices, indptr, shape, ctx: Optional[Context] = None):
        ctx = ctx or current_context()
        self._csr_data = data if not isinstance(data, NDArray) else data._data
        self._csr_indices = indices if not isinstance(indices, NDArray) else indices._data
        self._csr_indptr = indptr if not isinstance(indptr, NDArray) else indptr._data
        self._full_shape = tuple(shape)
        dense = _csr_to_dense(self._csr_data, self._csr_indices, self._csr_indptr, shape)
        super().__init__(dense, ctx)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self) -> NDArray:
        return NDArray(self._csr_data, self._ctx)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._csr_indices, self._ctx)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._csr_indptr, self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return NDArray(self._data, self._ctx)
        raise MXNetError("cannot cast csr to %r" % stype)

    def __repr__(self):
        return "\n<CSRNDArray %s @%s>" % ("x".join(map(str, self.shape)), self._ctx)


def _csr_to_dense(data, indices, indptr, shape):
    np_data = np.asarray(data)
    np_ind = np.asarray(indices).astype(np.int64)
    np_ptr = np.asarray(indptr).astype(np.int64)
    rows = np.repeat(np.arange(shape[0]), np.diff(np_ptr))
    out = np.zeros(shape, dtype=np_data.dtype)
    out[rows, np_ind] = np_data
    return jnp.asarray(out)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2:
        values, indices = arg1
        # canonicalize on the host BEFORE device upload (ascending rows —
        # the ex kernels binary-search; no device round-trip this way)
        idx_np = np.asarray(indices, np.int64)
        val_np = np.asarray(values)
        if idx_np.size > 1 and np.any(np.diff(idx_np) < 0):
            order = np.argsort(idx_np, kind="stable")
            idx_np = idx_np[order]
            val_np = val_np[order]
        v = array(val_np, ctx=ctx, dtype=dtype)._data
        i = array(idx_np, ctx=ctx, dtype="int64")._data
        return RowSparseNDArray(v, i, shape, ctx)
    dense = array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = arg1
        d = array(data, ctx=ctx, dtype=dtype)._data
        i = array(indices, ctx=ctx, dtype="int64")._data
        p = array(indptr, ctx=ctx, dtype="int64")._data
        return CSRNDArray(d, i, p, shape, ctx)
    dense = array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr: NDArray, stype: str):
    """Registered op ``cast_storage`` (reference
    src/operator/tensor/cast_storage-inl.h) — dispatches the FComputeEx
    kernel in :mod:`mxnet_tpu.ops.sparse`."""
    return invoke("cast_storage", arr, stype=stype)


def retain(arr: RowSparseNDArray, row_ids):
    """Registered op ``_sparse_retain`` (reference sparse_retain-inl.h)."""
    return invoke("_sparse_retain", arr, row_ids)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse dot (reference mx.nd.sparse.dot → dot-inl.h sparse kernels)."""
    return invoke("dot", lhs, rhs, transpose_a=transpose_a,
                  transpose_b=transpose_b)


def zeros(stype, shape, ctx=None, dtype=None):
    from .ndarray import zeros as dense_zeros

    d = dense_zeros(shape, ctx=ctx, dtype=dtype)
    return cast_storage(d, stype) if stype != "default" else d
