"""`mx.nd.random` namespace (reference python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from ..base import dtype_name
from .ndarray import NDArray, invoke

__all__ = [
    "uniform", "normal", "randn", "exponential", "gamma", "poisson",
    "negative_binomial", "generalized_negative_binomial", "multinomial",
    "shuffle", "randint",
]


def _sample(op, shape, dtype, ctx, **kw):
    kwargs = dict(kw)
    if shape is not None:
        kwargs["shape"] = shape if isinstance(shape, (tuple, list)) else (shape,)
    if dtype is not None:
        kwargs["dtype"] = dtype if isinstance(dtype, str) else dtype_name(dtype)
    if ctx is not None:
        kwargs["ctx"] = ctx
    return invoke(op, **kwargs)


def uniform(low=0.0, high=1.0, shape=(1,), dtype=None, ctx=None, out=None, **kw):
    if isinstance(low, NDArray):
        return invoke("_sample_uniform", low, high, shape=None if shape == (1,) else shape, out=out)
    return _sample("_random_uniform", shape, dtype, ctx, low=low, high=high)


def normal(loc=0.0, scale=1.0, shape=(1,), dtype=None, ctx=None, out=None, **kw):
    if isinstance(loc, NDArray):
        return invoke("_sample_normal", loc, scale, shape=None if shape == (1,) else shape, out=out)
    return _sample("_random_normal", shape, dtype, ctx, loc=loc, scale=scale)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None):
    return _sample("_random_normal", shape or (1,), dtype, ctx, loc=loc, scale=scale)


def exponential(scale=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    return _sample("_random_exponential", shape, dtype, ctx, lam=1.0 / scale)


def gamma(alpha=1.0, beta=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    if isinstance(alpha, NDArray):
        return invoke("_sample_gamma", alpha, beta, shape=None if shape == (1,) else shape, out=out)
    return _sample("_random_gamma", shape, dtype, ctx, alpha=alpha, beta=beta)


def poisson(lam=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    if isinstance(lam, NDArray):
        return invoke("_sample_poisson", lam, shape=None if shape == (1,) else shape, out=out)
    return _sample("_random_poisson", shape, dtype, ctx, lam=lam)


def negative_binomial(k=1, p=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    return _sample("_random_negative_binomial", shape, dtype, ctx, k=k, p=p)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,), dtype=None, ctx=None, out=None):
    return _sample("_random_generalized_negative_binomial", shape, dtype, ctx, mu=mu, alpha=alpha)


def multinomial(data, shape=(1,), get_prob=False, dtype="int32", out=None):
    return invoke("_sample_multinomial", data, shape=shape, get_prob=get_prob, dtype=dtype, out=out)


def shuffle(data, out=None):
    return invoke("_shuffle", data, out=out)


def randint(low, high, shape=(1,), dtype=None, ctx=None, out=None):
    return _sample("_random_randint", shape, dtype, ctx, low=low, high=high)
