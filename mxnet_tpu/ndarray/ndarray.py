"""NDArray: the imperative value type, backed by a jax.Array on CPU or TPU.

Re-design of reference `include/mxnet/ndarray.h` + `python/mxnet/ndarray/
ndarray.py`. The reference NDArray is a ref-counted chunk plus an engine
variable for async RW-dependency scheduling; on this stack the XLA/PJRT
runtime already executes asynchronously and tracks buffer dependencies, so
`wait_to_read` maps to `jax.Array.block_until_ready` and the dependency
engine bookkeeping disappears from the hot path (SURVEY.md §7.1).

Known deviation: basic `__getitem__` returns a copy, not an aliasing view
(jax buffers are immutable); `__setitem__` rebinds the underlying buffer via
a functional scatter.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import _global
from ..base import MXNetError, dtype_name, np_dtype
from ..context import Context, current_context
from ..ops.registry import get_op

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty", "arange", "eye", "concat", "stack", "waitall"]


class NDArray:
    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_entry", "_marked",
                 "_fresh_grad", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None):
        if isinstance(data, NDArray):
            data = data._data
        self._data = data
        self._ctx = ctx or current_context()
        self._grad: Optional["NDArray"] = None
        self._grad_req = "null"
        self._entry: Optional[Tuple[Any, int]] = None  # (tape node, output index)
        self._marked = False  # True once attach_grad() marks this as a leaf

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype) if self._data.dtype != jnp.bfloat16 else self._data.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        from .. import autograd as _ag
        if _ag.is_recording() and self._in_graph:
            # differentiable like reference transpose (FGradient = transpose
            # back); same tape-bypass class of bug as __getitem__
            return invoke("transpose", self)
        return NDArray(jnp.transpose(self._data), self._ctx)

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def data_jax(self):
        """The underlying jax.Array (TPU-native escape hatch)."""
        return self._data

    # ------------------------------------------------------------------
    # conversion / sync
    # ------------------------------------------------------------------
    def asnumpy(self) -> np.ndarray:
        # chaos site transfer.asnumpy: under an active schedule the
        # device->host copy can fault and retries under the policy (the
        # copy reads committed buffers, so a re-run is identical); with
        # chaos off this is one module-global boolean — asnumpy is far too
        # hot for anything more
        if _resilience.chaos.ENABLED:
            def attempt():
                _resilience.chaos.maybe_fail("transfer.asnumpy")
                return np.asarray(self._data)

            out = _resilience.call("transfer.asnumpy", attempt)
        else:
            out = np.asarray(self._data)
        # host-sync accounting: asnumpy is THE implicit device->host sync
        # tpulint can only flag statically; the telemetry counter measures
        # how much of it a run actually does (free when telemetry is off)
        _telemetry.record_transfer("asnumpy", (out,))
        return out

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    item = asscalar

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def wait_to_read(self):
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    # ------------------------------------------------------------------
    # DLPack interop (reference c_api.cc MXNDArrayToDLPack /
    # MXNDArrayFromDLPack; SURVEY §2.2 keeps dlpack as the interop ABI)
    # ------------------------------------------------------------------
    def __dlpack__(self, **kwargs):
        self._data.block_until_ready()
        return self._data.__dlpack__(**kwargs)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def to_dlpack_for_read(self):
        """Zero-copy DLPack capsule of this array (reference
        mx.nd.to_dlpack_for_read). ``__dlpack__`` syncs first, so the
        consumer sees completed data."""
        return self.__dlpack__()

    def to_dlpack_for_write(self):
        """NOT supported: XLA buffers are immutable and may be shared, so
        an external in-place write through a capsule would corrupt every
        alias invisibly. Known deviation from the reference (which hands
        out mutable views); consumers should write into their own tensor
        and re-import via from_dlpack."""
        raise MXNetError(
            "to_dlpack_for_write is not supported on immutable XLA "
            "buffers; use to_dlpack_for_read and re-import the modified "
            "tensor with from_dlpack")

    def astype(self, dtype, copy=True) -> "NDArray":
        d = np_dtype(dtype) if isinstance(dtype, str) else dtype
        if not copy and self._data.dtype == d:
            return self
        return invoke("Cast", self, dtype=dtype_name(d))

    def copy(self) -> "NDArray":
        return invoke("_copy", self)

    def copyto(self, other) -> "NDArray":
        if isinstance(other, Context):
            out = NDArray(jax.device_put(self._data, other.jax_device()), other)
            return out
        if isinstance(other, NDArray):
            other._data = jax.device_put(self._data, other._ctx.jax_device())
            return other
        raise MXNetError("copyto: unsupported target %r" % (other,))

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def astuple(self):
        return tuple(self.asnumpy())

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage

        return cast_storage(self, stype)

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Mark this array as a gradient leaf (reference ndarray.py:2122)."""
        self._marked = True
        self._grad_req = grad_req
        self._entry = None  # attaching grad detaches from any recorded graph
        self._grad = NDArray(jnp.zeros_like(self._data), self._ctx)
        # reference fresh_grad starts False: a leaf no backward has reached
        # yet is stale, so Trainer's ignore_stale_grad contract holds from
        # the FIRST step (autograd.backward flips it True)
        self._fresh_grad = False

    def detach(self) -> "NDArray":
        out = NDArray(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    @property
    def _in_graph(self) -> bool:
        return self._marked or self._entry is not None

    # ------------------------------------------------------------------
    # shape ops (methods mirror reference NDArray methods)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape:
            shape = kwargs.get("shape")
        return invoke("Reshape", self, shape=shape, reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return invoke("reshape_like", self, other)

    def expand_dims(self, axis):
        return invoke("expand_dims", self, axis=axis)

    def squeeze(self, axis=None):
        return invoke("squeeze", self, axis=axis)

    def flatten(self):
        return invoke("Flatten", self)

    def transpose(self, axes=None):
        return invoke("transpose", self, axes=axes)

    def swapaxes(self, dim1, dim2):
        return invoke("SwapAxis", self, dim1=dim1, dim2=dim2)

    def flip(self, axis):
        return invoke("reverse", self, axis=axis)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("SliceChannel", self, num_outputs=num_outputs, axis=axis, squeeze_axis=squeeze_axis)

    def slice(self, begin, end, step=None):
        return invoke("slice", self, begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", self, indices, axis=axis, mode=mode)

    def pick(self, index, axis=-1, keepdims=False):
        return invoke("pick", self, index, axis=axis, keepdims=keepdims)

    def one_hot(self, depth, **kw):
        return invoke("one_hot", self, depth=depth, **kw)

    def tile(self, reps):
        return invoke("tile", self, reps=reps)

    def repeat(self, repeats, axis=None):
        return invoke("repeat", self, repeats=repeats, axis=axis)

    def broadcast_to(self, shape):
        return invoke("broadcast_to", self, shape=shape)

    def broadcast_like(self, other):
        return invoke("broadcast_like", self, other)

    def pad(self, mode, pad_width, constant_value=0.0):
        return invoke("Pad", self, mode=mode, pad_width=pad_width, constant_value=constant_value)

    def clip(self, a_min, a_max):
        return invoke("clip", self, a_min=a_min, a_max=a_max)

    def abs(self):
        return invoke("abs", self)

    def sign(self):
        return invoke("sign", self)

    def sqrt(self):
        return invoke("sqrt", self)

    def square(self):
        return invoke("square", self)

    def exp(self):
        return invoke("exp", self)

    def log(self):
        return invoke("log", self)

    def relu(self):
        return invoke("relu", self)

    def sigmoid(self):
        return invoke("sigmoid", self)

    def tanh(self):
        return invoke("tanh", self)

    def softmax(self, axis=-1):
        return invoke("softmax", self, axis=axis)

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", self, axis=axis)

    def sum(self, axis=None, keepdims=False, **kw):
        return invoke("sum", self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return invoke("mean", self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return invoke("prod", self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return invoke("max", self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return invoke("min", self, axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", self, ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", self, axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", self, axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return invoke("sort", self, axis=axis, is_ascend=is_ascend)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke("topk", self, axis=axis, k=k, ret_typ=ret_typ, is_ascend=is_ascend)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke("dot", self, other, transpose_a=transpose_a, transpose_b=transpose_b)

    # ------------------------------------------------------------------
    # arithmetic operators (broadcast semantics, reference ndarray.py)
    # ------------------------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(op, a, b)
        if isinstance(other, (int, float, np.generic)):
            return invoke(scalar_op, self, scalar=float(other))
        if isinstance(other, np.ndarray):
            o = array(other, ctx=self._ctx, dtype=self._data.dtype)
            a, b = (o, self) if reverse else (self, o)
            return invoke(op, a, b)
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, (int, float, np.generic)):
            return invoke("_rminus_scalar", self, scalar=float(o))
        return self._binop(o, "broadcast_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        if isinstance(o, (int, float, np.generic)):
            return invoke("_rdiv_scalar", self, scalar=float(o))
        return self._binop(o, "broadcast_div", "_div_scalar", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, o):
        if isinstance(o, (int, float, np.generic)):
            return invoke("_rmod_scalar", self, scalar=float(o))
        return self._binop(o, "broadcast_mod", "_mod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __rpow__(self, o):
        if isinstance(o, (int, float, np.generic)):
            return invoke("_rpower_scalar", self, scalar=float(o))
        return NotImplemented

    def __neg__(self):
        return invoke("negative", self)

    def __abs__(self):
        return invoke("abs", self)

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place: rebind the buffer (XLA buffers are immutable)
    def __iadd__(self, o):
        out = self.__add__(o)
        self._data = out._data
        self._entry = out._entry
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._data = out._data
        self._entry = out._entry
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._data = out._data
        self._entry = out._entry
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._data = out._data
        self._entry = out._entry
        return self

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _conv_index(self, key):
        if isinstance(key, NDArray):
            return key._data.astype(jnp.int32) if jnp.issubdtype(key._data.dtype, jnp.floating) else key._data
        if isinstance(key, tuple):
            return tuple(self._conv_index(k) for k in key)
        if isinstance(key, list):
            return np.asarray(key)
        return key

    def __getitem__(self, key):
        jkey = self._conv_index(key)
        from .. import autograd as _ag
        if (_ag.is_recording() and self._in_graph
                and jnp.issubdtype(self._data.dtype, jnp.inexact)):
            # basic/advanced indexing is differentiable (reference: slice /
            # gather ops with FGradient -> scatter-add); tape a vjp closure
            # so x[...] inside record doesn't silently detach the graph
            def _compute(attrs, x, _k=jkey):
                return x[_k]
            return _taped_call("getitem", None, [self._data], [self], [0],
                               _compute, self._ctx)
        return NDArray(self._data[jkey], self._ctx)

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, (int, float, np.generic)):
            v = value
        else:
            v = jnp.asarray(value)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            if isinstance(v, (int, float)):
                self._data = jnp.full_like(self._data, v)
            else:
                self._data = jnp.broadcast_to(jnp.asarray(v, dtype=self._data.dtype), self.shape)
            return
        self._data = self._data.at[self._conv_index(key)].set(v)

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()),
            "x".join(str(s) for s in self.shape),
            self._ctx,
        )

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


# ---------------------------------------------------------------------------
# eager dispatch
# ---------------------------------------------------------------------------


from .. import profiler as _profiler
from .. import engine as _engine
from .. import resilience as _resilience
from .. import telemetry as _telemetry


@_profiler.profiled("operator", lambda op_name, *i, **kw: op_name)
def invoke(op_name: str, *inputs, out=None, **kwargs):
    """Eager op invocation — counterpart of the reference's
    `MXImperativeInvokeEx` → `Imperative::Invoke` path
    (`src/c_api/c_api_ndarray.cc:132`, `src/imperative/imperative.cc:87`).
    Dispatches the registered fcompute on jax arrays and, when autograd is
    recording, tapes a jax.vjp closure (the whole-graph XLA equivalent of the
    reference's per-op FGradient)."""
    opdef = get_op(op_name)
    attrs = opdef.parse_attrs(kwargs)
    # storage-type dispatch: route to the op's FComputeEx when a sparse
    # NDArray is involved (or the op always dispatches ex, e.g. cast_storage
    # whose OUTPUT storage is the sparse one) — reference DispatchMode
    # selection in imperative_utils.h:98-176
    if opdef.fcompute_ex is not None:
        from . import sparse as _sp
        if (opdef.dispatch_ex_always
                or any(isinstance(i, _sp.BaseSparseNDArray) for i in inputs)):
            from .. import autograd as _ag

            # a non-differentiable ex kernel must not swallow the tape:
            # when recording with a dense in-graph operand, fall through to
            # the dense FCompute (sparse inputs densify via their _data
            # cache) so jax.vjp tapes the op as before. Only ops whose
            # dense FCompute is a full equivalent opt in (ex_grad_fallback)
            needs_tape = (opdef.ex_grad_fallback
                          and not opdef.ex_differentiable
                          and not opdef.dispatch_ex_always
                          and _ag.is_recording()
                          and any(isinstance(i, NDArray)
                                  and not isinstance(i, _sp.BaseSparseNDArray)
                                  and i._in_graph for i in inputs))
            if not needs_tape:
                return _invoke_ex(opdef, attrs, inputs, out)
    nd_inputs: List[Optional[NDArray]] = []
    datas = []
    for i in inputs:
        if isinstance(i, NDArray):
            nd_inputs.append(i)
            datas.append(i._data)
        elif i is None:
            nd_inputs.append(None)
            datas.append(None)
        else:
            nd_inputs.append(None)
            datas.append(jnp.asarray(i))

    ctx = None
    for nd in nd_inputs:
        if nd is not None:
            ctx = nd._ctx
            break
    if ctx is None:
        ctx = kwargs.get("ctx") or current_context()
        if isinstance(ctx, str) and ctx:
            dev, _, idx = ctx.partition("(")
            ctx = Context(dev, int(idx.rstrip(")")) if idx else 0)
        elif not isinstance(ctx, Context):
            ctx = current_context()

    from .. import autograd

    record = autograd.is_recording() and any(
        nd is not None and nd._in_graph for nd in nd_inputs
    )

    if record:
        diff_pos = [k for k, nd in enumerate(nd_inputs) if nd is not None]
        result = _taped_call(op_name, attrs, datas, nd_inputs, diff_pos,
                             opdef.fcompute, ctx)
    else:
        outputs = opdef.fcompute(attrs, *datas)
        # nullary ops (init/random) materialize on the default device; honor
        # the requested context explicitly
        if not any(nd is not None for nd in nd_inputs):
            dev = ctx.jax_device()
            if isinstance(outputs, (tuple, list)):
                outputs = [jax.device_put(o, dev) for o in outputs]
            else:
                outputs = jax.device_put(outputs, dev)
        if isinstance(outputs, (tuple, list)):
            result = [NDArray(o, ctx) for o in outputs]
        else:
            result = NDArray(outputs, ctx)

    result = _bind_out(out, result)
    # NaiveEngine debug mode (MXNET_ENGINE_TYPE=NaiveEngine): block until the
    # op completes so failures surface here, not at a later wait — reference
    # src/engine/naive_engine.cc:50 semantics.
    _engine.maybe_sync_eager(result)
    return result


def _taped_call(op_name, attrs, datas, nd_inputs, diff_pos, compute, ctx):
    """Shared autograd-record path for FCompute and FComputeEx dispatch:
    jax.vjp over ``compute`` w.r.t. the inputs at ``diff_pos`` (non-diff
    inputs — constants, sparse operands — stay closed over), tape node
    attached to every output."""
    from .. import autograd

    diff_datas = [datas[k] for k in diff_pos]

    def fn(*xs):
        full = list(datas)
        for p, x in zip(diff_pos, xs):
            full[p] = x
        return compute(attrs, *full)

    outputs, vjp_fn = jax.vjp(fn, *diff_datas)
    single = not isinstance(outputs, (tuple, list))
    outs_t = (outputs,) if single else tuple(outputs)
    nd_outs = [NDArray(o, ctx) for o in outs_t]
    node = autograd._TapeNode(
        vjp_fn=vjp_fn,
        inputs=[nd_inputs[k] for k in diff_pos],
        out_shapes=[(o.shape, o.dtype) for o in outs_t],
        single=single,
        op_name=op_name,
        fwd_fn=fn,
    )
    for idx, nd in enumerate(nd_outs):
        nd._entry = (node, idx)
    return nd_outs[0] if single else nd_outs


def _bind_out(out, result):
    """Rebind ``out=`` targets to the result. Sparse storage is refused:
    BaseSparseNDArray keeps _values/_indices/_csr_* alongside _data, and a
    _data-only overwrite would leave those components describing the OLD
    contents — silent corruption for the next ex-dispatched op."""
    if out is None:
        return result
    from . import sparse as _sp

    outs = out if isinstance(out, (list, tuple)) else [out]
    results = result if isinstance(result, (list, tuple)) else [result]
    for o, r in zip(outs, results):
        if isinstance(o, _sp.BaseSparseNDArray) \
                or isinstance(r, _sp.BaseSparseNDArray):
            raise MXNetError(
                "out= is not supported for sparse storage; rebind the "
                "result instead (sparse NDArrays are immutable views)")
    if isinstance(out, NDArray) and isinstance(result, NDArray):
        out._data = result._data
        out._entry = result._entry
        return out
    if isinstance(out, (list, tuple)):
        for o, r in zip(out, result):
            o._data = r._data
            o._entry = r._entry
        return out
    return result


def _invoke_ex(opdef, attrs, inputs, out):
    """FComputeEx eager dispatch: sparse NDArrays become SparseRep views,
    sparse outputs come back as sparse NDArrays. Differentiable ex kernels
    (sparse dot) are taped w.r.t. their dense inputs only — the sparse
    operand gets grad_req=null, the reference's sparse-dot contract."""
    from .. import autograd
    from ..ops.sparse import SparseRep
    from . import sparse as _sp

    nd_inputs: List[Optional[NDArray]] = []
    datas = []
    for i in inputs:
        if isinstance(i, _sp.RowSparseNDArray):
            nd_inputs.append(i)
            datas.append(SparseRep("row_sparse", i._values, i._indices,
                                   None, i._full_shape))
        elif isinstance(i, _sp.CSRNDArray):
            nd_inputs.append(i)
            datas.append(SparseRep("csr", i._csr_data, i._csr_indices,
                                   i._csr_indptr, i._full_shape))
        elif isinstance(i, NDArray):
            nd_inputs.append(i)
            datas.append(i._data)
        elif i is None:
            nd_inputs.append(None)
            datas.append(None)
        else:
            nd_inputs.append(None)
            datas.append(jnp.asarray(i))
    ctx = next((nd._ctx for nd in nd_inputs if nd is not None), None) \
        or current_context()

    def wrap(o):
        if isinstance(o, SparseRep):
            if o.stype == "row_sparse":
                return _sp.RowSparseNDArray(o.data, o.indices, o.shape, ctx)
            return _sp.CSRNDArray(o.data, o.indices, o.indptr, o.shape, ctx)
        return NDArray(o, ctx)

    record = (opdef.ex_differentiable and autograd.is_recording()
              and any(nd is not None
                      and not isinstance(nd, _sp.BaseSparseNDArray)
                      and nd._in_graph for nd in nd_inputs))
    if record:
        diff_pos = [k for k, nd in enumerate(nd_inputs)
                    if nd is not None
                    and not isinstance(nd, _sp.BaseSparseNDArray)]
        result = _taped_call(opdef.name, attrs, datas, nd_inputs, diff_pos,
                             opdef.fcompute_ex, ctx)
    else:
        outputs = opdef.fcompute_ex(attrs, *datas)
        if isinstance(outputs, (tuple, list)) \
                and not isinstance(outputs, SparseRep):
            result = [wrap(o) for o in outputs]
        else:
            result = wrap(outputs)
    result = _bind_out(out, result)
    _engine.maybe_sync_eager(result)
    return result


# ---------------------------------------------------------------------------
# creation functions (reference python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------


def _put(npdata, ctx):
    ctx = ctx or current_context()
    return NDArray(jax.device_put(npdata, ctx.jax_device()), ctx)


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    if isinstance(source, NDArray):
        data = source.asnumpy()
    else:
        data = np.asarray(source)
    if dtype is None:
        dtype = data.dtype if data.dtype != np.float64 else np.float32  # tpulint: disable=dtype-drift -- this IS the f64 downcast guard
    d = np_dtype(dtype) if isinstance(dtype, str) else dtype
    return _put(data.astype(d) if data.dtype != d else data, ctx)


def zeros(shape, ctx=None, dtype=None, **kw) -> NDArray:
    return _put(np.zeros(shape, dtype=_npd(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kw) -> NDArray:
    return _put(np.ones(shape, dtype=_npd(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kw) -> NDArray:
    return _put(np.full(shape, val, dtype=_npd(dtype)), ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    a = np.arange(start, stop, step, dtype=_npd(dtype))
    if repeat > 1:
        a = np.repeat(a, repeat)
    return _put(a, ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    return _put(np.eye(N, M or None, k, dtype=_npd(dtype)), ctx)


def _npd(dtype):
    if dtype is None:
        return np.float32
    d = np_dtype(dtype) if isinstance(dtype, str) else dtype
    return d


def concat(*args, dim=1):
    arrs = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    return invoke("Concat", *arrs, num_args=len(arrs), dim=dim)


def stack_arrays(*args, axis=0):
    arrs = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    return invoke("stack", *arrs, num_args=len(arrs), axis=axis)


stack = stack_arrays


def waitall():
    """Block until all async computation completes (reference mx.nd.waitall →
    Engine::WaitForAll): drains the JAX dispatch stream and the native host
    engine, re-raising any pending async failure from the latter."""
    (jax.device_put(0.0) + 0).block_until_ready()
    _engine.wait_for_all()


def onehot_encode(indices, out):
    res = invoke("one_hot", indices, depth=out.shape[1])
    out._data = res._data
    return out


# -- module-level convenience functions closing the reference nd surface ----
# (reference python/mxnet/ndarray/ndarray.py:2439,3436,3617,3824)


def concatenate(arrays, axis=0, always_copy=True):
    """Concatenate along an axis (reference nd.concatenate)."""
    del always_copy  # functional arrays: result is always a fresh buffer
    return invoke("Concat", *arrays, dim=axis, num_args=len(arrays))


def moveaxis(tensor, source, destination):
    """Move axes like np.moveaxis (reference nd.moveaxis)."""
    ndim = len(tensor.shape)
    src = [source] if isinstance(source, int) else list(source)
    dst = [destination] if isinstance(destination, int) else list(destination)
    src = [s % ndim for s in src]
    dst = [d % ndim for d in dst]
    order = [a for a in range(ndim) if a not in src]
    for d, s in sorted(zip(dst, src)):
        order.insert(d, s)
    return invoke("transpose", tensor, axes=tuple(order))


def histogram(a, bins=10, range=None):
    """Histogram (reference nd.histogram): returns (counts, bin_edges)."""
    if isinstance(bins, NDArray):
        counts, edges = invoke("_histogram", a, bins,
                               bin_cnt=len(bins.asnumpy()) - 1)
        return counts, edges
    if range is None:
        amin = float(a.min().asnumpy())
        amax = float(a.max().asnumpy())
        range = (amin, amax if amax > amin else amin + 1.0)
    edges = np.linspace(range[0], range[1], bins + 1).astype(np.float32)
    counts, edges_out = invoke("_histogram", a, array(edges), bin_cnt=bins)
    return counts, edges_out


def logical_and(lhs, rhs):
    return invoke("broadcast_logical_and", lhs, rhs)


def logical_or(lhs, rhs):
    return invoke("broadcast_logical_or", lhs, rhs)


def logical_xor(lhs, rhs):
    return invoke("broadcast_logical_xor", lhs, rhs)


def modulo(lhs, rhs):
    return lhs % rhs


def true_divide(lhs, rhs):
    return lhs / rhs


def imdecode(buf, **kwargs):
    """Decode an image byte buffer (reference nd.imdecode → image pipeline)."""
    from .. import image as _image

    return _image.imdecode(buf, **kwargs)


def to_dlpack_for_read(data: "NDArray"):
    """Module-level form (reference mx.nd.to_dlpack_for_read)."""
    return data.to_dlpack_for_read()


def to_dlpack_for_write(data: "NDArray"):
    return data.to_dlpack_for_write()


def from_dlpack(obj, ctx: Optional[Context] = None) -> "NDArray":
    """Wrap a DLPack-compatible external tensor (a capsule or any object
    with __dlpack__, e.g. a torch tensor) as an NDArray without a host
    round-trip (reference mx.nd.from_dlpack)."""
    arr = jax.dlpack.from_dlpack(obj)
    return NDArray(arr, ctx or current_context())
