"""MXNet ``.params`` file (de)serialization.

Byte-compatible with the reference container format so model-zoo artifacts
transfer (SURVEY.md Appendix B; reference ``src/ndarray/ndarray.cc:1537``
NDArray::Save and ``:1733`` list save):

    file      := uint64 kMXAPINDArrayListMagic(0x112) | uint64 reserved
               | vec<ndarray> | vec<string names>
    vec<T>    := uint64 count | T*count                       (dmlc::Stream)
    ndarray   := uint32 NDARRAY_V2_MAGIC(0xF993fac9) | int32 stype(=1 dense)
               | tshape | int32 dev_type | int32 dev_id | int32 type_flag
               | raw data bytes
    tshape    := uint32 ndim | int64*ndim                     (int64 TShape)

Legacy V1 (int64 shape, no stype) and pre-V1 (uint32 ndim leading) load
paths are kept, mirroring ``NDArray::LegacyLoad``.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union

import numpy as np

from ..base import MXNetError

kMXAPINDArrayListMagic = 0x112
NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9

# mshadow type codes (reference include/mxnet/base.h / mshadow dtype flags)
_TYPE_FLAG_TO_NP = {
    0: np.float32,
    1: np.float64,
    2: np.float16,
    3: np.uint8,
    4: np.int32,
    5: np.int8,
    6: np.int64,
}
_NP_TO_TYPE_FLAG = {np.dtype(v): k for k, v in _TYPE_FLAG_TO_NP.items()}


def _np_of(arr) -> np.ndarray:
    if hasattr(arr, "asnumpy"):
        return arr.asnumpy()
    return np.ascontiguousarray(arr)


def _save_one(parts: List[bytes], a: np.ndarray):
    dt = np.dtype(a.dtype)
    if dt.name == "bfloat16":  # no mshadow code for bf16 in 1.x files
        a = a.astype(np.float32)
        dt = np.dtype(np.float32)
    if a.ndim == 0:
        # the reference format has no 0-d arrays (ndim 0 marks a "none"
        # NDArray with no payload, ndarray.cc:1556); persist as (1,)
        a = a.reshape(1)
    if dt not in _NP_TO_TYPE_FLAG:
        raise MXNetError("dtype %s not serializable to .params" % dt)
    parts.append(struct.pack("<I", NDARRAY_V2_MAGIC))
    parts.append(struct.pack("<i", 0))  # kDefaultStorage (ndarray.h:63)
    parts.append(struct.pack("<I", a.ndim))
    parts.append(struct.pack("<%dq" % a.ndim, *a.shape))
    parts.append(struct.pack("<ii", 1, 0))  # Context: cpu(0)
    parts.append(struct.pack("<i", _NP_TO_TYPE_FLAG[dt]))
    parts.append(np.ascontiguousarray(a).tobytes())


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, fmt: str):
        size = struct.calcsize(fmt)
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += size
        return vals if len(vals) > 1 else vals[0]

    def read_bytes(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out


def _load_shape_v2(r: _Reader) -> Tuple[int, ...]:
    ndim = r.read("I")
    if ndim == 0:
        return ()
    return tuple(r.read("%dq" % ndim) if ndim > 1 else (r.read("q"),))


def _load_sparse_v2(r: _Reader, stype: int) -> np.ndarray:
    """Parse a V2 sparse entry (row_sparse=1: 1 aux [indices]; csr=2: 2 aux
    [indptr, indices] — reference ndarray.cc NDArray::Save) and densify."""
    nad = 1 if stype == 1 else 2
    storage_shape = _load_shape_v2(r)
    shape = _load_shape_v2(r)
    if len(shape) == 0:
        return np.zeros((), dtype=np.float32)
    r.read("ii")  # context
    type_flag = r.read("i")
    aux = []
    for _ in range(nad):
        aux_type = r.read("i")
        aux_shape = _load_shape_v2(r)
        aux.append((np.dtype(_TYPE_FLAG_TO_NP[aux_type]), aux_shape))
    dt = np.dtype(_TYPE_FLAG_TO_NP[type_flag])
    count = int(np.prod(storage_shape)) if storage_shape else 0
    data = np.frombuffer(r.read_bytes(count * dt.itemsize), dtype=dt)
    data = data.reshape(storage_shape) if storage_shape else data
    aux_data = []
    for adt, ashape in aux:
        acount = int(np.prod(ashape)) if ashape else 0
        ad = np.frombuffer(r.read_bytes(acount * adt.itemsize), dtype=adt)
        aux_data.append(ad.reshape(ashape) if ashape else ad)
    dense = np.zeros(shape, dtype=dt)
    if stype == 1:  # row_sparse: aux[0] = row indices
        if aux_data[0].size:
            dense[aux_data[0].astype(np.int64)] = data
    else:  # csr: aux[0] = indptr, aux[1] = col indices
        indptr, indices = aux_data
        for row in range(shape[0]):
            lo, hi = int(indptr[row]), int(indptr[row + 1])
            if hi > lo:
                dense[row, indices[lo:hi].astype(np.int64)] = data[lo:hi]
    return dense


def _load_one(r: _Reader) -> np.ndarray:
    magic = r.read("I")
    if magic == NDARRAY_V2_MAGIC:
        stype = r.read("i")
        if stype not in (0, 1, 2):
            raise MXNetError("unknown storage type in .params (stype=%d)" % stype)
        if stype != 0:
            return _load_sparse_v2(r, stype)
        shape = _load_shape_v2(r)
    elif magic == NDARRAY_V1_MAGIC:
        shape = _load_shape_v2(r)
    else:
        # pre-V1: magic itself is ndim, uint32 dims
        ndim = magic
        shape = tuple(r.read("%dI" % ndim)) if ndim > 1 else ((r.read("I"),) if ndim else ())
    if len(shape) == 0:
        return np.zeros((), dtype=np.float32)
    r.read("ii")  # context
    type_flag = r.read("i")
    dt = np.dtype(_TYPE_FLAG_TO_NP[type_flag])
    count = int(np.prod(shape))
    data = np.frombuffer(r.read_bytes(count * dt.itemsize), dtype=dt).reshape(shape)
    return data.copy()


def save(fname: str, data) -> None:
    """Save dict-of-NDArray / list-of-NDArray / single NDArray
    (reference ``mx.nd.save``)."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    names: List[str] = []
    arrays: List[np.ndarray] = []
    if isinstance(data, dict):
        for k, v in data.items():
            names.append(k)
            arrays.append(_np_of(v))
    elif isinstance(data, (list, tuple)):
        arrays = [_np_of(v) for v in data]
    else:
        raise MXNetError("save expects dict, list, or NDArray")

    parts: List[bytes] = [struct.pack("<QQ", kMXAPINDArrayListMagic, 0)]
    parts.append(struct.pack("<Q", len(arrays)))
    for a in arrays:
        _save_one(parts, a)
    parts.append(struct.pack("<Q", len(names)))
    for n in names:
        nb = n.encode("utf-8")
        parts.append(struct.pack("<Q", len(nb)))
        parts.append(nb)
    with open(fname, "wb") as f:
        f.write(b"".join(parts))


def load_np(fname: str) -> Union[Dict[str, np.ndarray], List[np.ndarray]]:
    """Load a .params file into numpy arrays (names preserved)."""
    with open(fname, "rb") as f:
        buf = f.read()
    r = _Reader(buf)
    header, _reserved = r.read("QQ")
    if header != kMXAPINDArrayListMagic:
        raise MXNetError("Invalid NDArray file format (bad magic 0x%x)" % header)
    n_arrays = r.read("Q")
    arrays = [_load_one(r) for _ in range(n_arrays)]
    n_names = r.read("Q")
    names = []
    for _ in range(n_names):
        ln = r.read("Q")
        names.append(r.read_bytes(ln).decode("utf-8"))
    if names:
        if len(names) != len(arrays):
            raise MXNetError("Invalid NDArray file format (names/arrays mismatch)")
        return dict(zip(names, arrays))
    return arrays


def load(fname: str):
    """Load a .params file into NDArrays (reference ``mx.nd.load``)."""
    from . import ndarray as nd

    out = load_np(fname)
    if isinstance(out, dict):
        return {k: nd.array(v, dtype=v.dtype) for k, v in out.items()}
    return [nd.array(v, dtype=v.dtype) for v in out]
