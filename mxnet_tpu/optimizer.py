"""Optimizers.

API parity with reference ``python/mxnet/optimizer.py`` (Optimizer registry,
SGD/NAG/Signum/FTML/Adam/AdaGrad/RMSProp/AdaDelta/Ftrl/Adamax/Nadam/SGLD/
DCASGD/LBSGD, lr/wd multipliers, ``num_update`` bookkeeping, ``Updater`` with
state (de)serialization).

TPU-native design: the reference accelerates updates with hand-fused CUDA ops
(reference ``src/operator/optimizer_op.cc`` — sgd_mom_update, adam_update, …).
Here every optimizer is split into two pieces:

* a host-side scalar prologue :meth:`Optimizer._host_scalars` — per-index
  lr/wd multipliers plus any schedule transform computed in python (Adam's
  bias correction, Nadam's momentum schedule);
* a pure per-parameter kernel :meth:`Optimizer._leaf_step`
  ``(w, g, state, t, lr, wd, *extras) -> (new_w, new_state)`` on jax arrays
  only.

The generic :meth:`Optimizer.update` jits the kernel once per optimizer
(lr/wd/t enter as traced scalars, so LR schedules never retrace) — XLA fuses
the whole rescale → clip → wd → momentum → assign chain into one kernel, the
direct equivalent of the reference's fused ops. The SAME kernel is what
``mxnet_tpu.fastpath`` composes over the whole parameter tree (ONE jit per
step instead of one per parameter) and — where the math permits — what
``parallel.TrainStep`` traces in-graph, so the three update paths cannot
drift apart numerically: they are one function traced in three places.
"""
from __future__ import annotations

import math
import pickle
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import telemetry as _telemetry
from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = [
    "Optimizer", "register", "create", "get_updater", "Updater",
    "SGD", "NAG", "Signum", "SignSGD", "FTML", "DCASGD", "SGLD", "LBSGD",
    "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam",
    "Test",
]


def _as_jax(x):
    return x._data if isinstance(x, NDArray) else x


def _f32(x):
    return jnp.asarray(x, dtype=jnp.float32)


def _is_mp_dtype(dtype):
    """Dtypes that keep an fp32 master copy under ``multi_precision``:
    float16 (reference mp_sgd_update) and bfloat16 (the TPU-native low
    precision — same master-weight rationale, MXU-rate storage)."""
    return dtype == np.float16 or dtype == jnp.bfloat16


def _base_state_structure(optimizer, index, weight):
    """Pytree structure of ``create_state`` for this weight, without
    allocating (eval_shape); cached per (shape, dtype) on the instance."""
    cache = optimizer.__dict__.setdefault("_state_struct_cache", {})
    key = (tuple(weight.shape), str(_as_jax(weight).dtype))
    if key not in cache:
        cache[key] = jax.tree_util.tree_structure(jax.eval_shape(
            lambda: optimizer.create_state(index, weight)))
    return cache[key]


def _is_mp_pair(optimizer, index, weight, state):
    """Whether ``state`` is an ``(fp32 master, base_state)`` pair for this
    weight — the layout ``create_state_multi_precision`` produces.

    A structural dtype/shape test alone is ambiguous: Adam-family plain
    states are ALSO 2-tuples of fp32 weight-shaped arrays, and treating a
    resumed ``(m, v)`` as ``(master, base)`` would silently install the
    first moment as the weight. Disambiguation: in a true pair the SECOND
    element has ``create_state``'s pytree structure while the whole state
    does not."""
    if not (isinstance(state, tuple) and len(state) == 2
            and getattr(state[0], "dtype", None) == jnp.float32
            and getattr(state[0], "shape", None) == tuple(weight.shape)):
        return False
    expected = _base_state_structure(optimizer, index, weight)
    whole = jax.tree_util.tree_structure(state)
    second = jax.tree_util.tree_structure(state[1])
    if whole == expected and second != expected:
        return False  # the state IS a plain create_state tuple (Adam (m,v))
    return second == expected


def ensure_mp_state(optimizer, index, weight, state):
    """Adopt the fp32-master layout for a low-precision weight whose state
    predates it (e.g. a bf16 optimizer checkpoint saved before
    ``multi_precision`` covered bfloat16, when bf16 silently took the
    non-master branch, or an fp32 run resumed onto bf16-cast weights): the
    current weight becomes the master, the loaded state stays as the base.
    No-op when mp doesn't apply or the state is already a pair."""
    if not (optimizer.multi_precision and _is_mp_dtype(weight.dtype)):
        return state
    if _is_mp_pair(optimizer, index, weight, state):
        return state
    return (jnp.asarray(_as_jax(weight), dtype=jnp.float32), state)


class Optimizer(object):
    """Base optimizer (reference optimizer.py:35).

    Subclasses implement :meth:`create_state` and the pure
    :meth:`_leaf_step` kernel (plus :meth:`_host_scalars` when the update
    needs host-computed schedule scalars); the base class handles registry,
    per-index lr/wd multipliers, update counting, jit caching and the
    generic :meth:`update` dispatch.
    """

    opt_registry: Dict[str, type] = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise MXNetError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise MXNetError("param_idx2name should be a dict of param indexes to names.")
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None else ()
        self.param_dict = param_dict if param_dict else {}
        self._step_cache: Dict[Any, Any] = {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------
    def create_state(self, index, weight):
        """Return optimizer state for one parameter (None / array / tuple)."""
        return None

    def create_state_multi_precision(self, index, weight):
        """fp16/bf16 weights get an fp32 master copy (reference
        create_state_multi_precision; mp_sgd_update parity)."""
        weight_master_copy = None
        if self.multi_precision and _is_mp_dtype(weight.dtype):
            weight_master_copy = jnp.asarray(_as_jax(weight), dtype=jnp.float32)
            return (weight_master_copy, self.create_state(index, weight))
        return self.create_state(index, weight)

    # ------------------------------------------------------------------
    # the update protocol: host scalar prologue + pure per-leaf kernel
    # ------------------------------------------------------------------
    def _host_scalars(self, index):
        """Host-side scalar prologue for one parameter's update, run AFTER
        :meth:`_update_count`: returns ``(lr, wd, extras)``. ``lr`` carries
        any host-computed schedule transform (Adam's bias correction,
        Adamax's warmup divisor); ``extras`` are additional traced operands
        :meth:`_leaf_step` consumes (Nadam's momentum schedule, SGLD's rng
        key). Shared verbatim by the per-parameter path and the fastpath
        fused tree-apply, so the two stay bit-identical."""
        return self._get_lr(index), self._get_wd(index), ()

    def _leaf_step(self, w, g, state, t, lr, wd, *extras):
        """Pure per-parameter kernel on jax arrays:
        ``(new_weight, new_state)``. ``t`` is the traced 1-based update
        count of this index; ``lr``/``wd`` come from :meth:`_host_scalars`.
        Traced by :meth:`update` (one jit per parameter), by
        ``fastpath.fused_apply`` (one jit per tree) and — via
        :meth:`pure_step` where aliased — by the in-graph SPMD step."""
        raise NotImplementedError(
            "%s does not implement _leaf_step" % self.__class__.__name__)

    #: True when :meth:`_host_scalars` mutates optimizer state or consumes
    #: a host stream (Nadam's ``m_schedule`` recurrence, SGLD's rng keys):
    #: its call ORDER is then observable, so the fused path must preserve
    #: the legacy param-outer/device-inner ordering — with multiple device
    #: positions it cannot, and ``fastpath.supports`` falls back.
    _host_scalars_stateful = False

    #: True when :meth:`_leaf_step` is element-wise over the weight — no
    #: cross-element math (LBSGD's layer-wise norms), no shape-dependent
    #: randomness (SGLD's noise draw). The ZeRO plane (``fastpath.zero``)
    #: may then run the kernel over a flattened 1/N dp-shard of the
    #: concatenated parameter buckets and get bit-identical per-element
    #: results; subclasses with cross-element math MUST set this False or
    #: sharded updates would silently change the math.
    _leaf_step_pointwise = True

    @property
    def fastpath_capable(self):
        """Whether ``fastpath.fused_apply`` can fold this optimizer's whole
        update into one tree-level jit."""
        return type(self)._leaf_step is not Optimizer._leaf_step

    def update(self, index, weight, grad, state):
        """Apply one parameter's update (reference optimizer.py:update).

        Generic over the protocol above: bookkeeping + host scalars, then
        ONE jitted fused kernel per optimizer class (cached across
        parameters and steps; lr/wd/t are traced operands)."""
        if not self.fastpath_capable:
            raise NotImplementedError()
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd, extras = self._host_scalars(index)

        def step(w, g, s, t, lr, wd, *ex):
            return self._leaf_step(w, g, s, t, lr, wd, *ex)

        _telemetry.OPT_DISPATCHES.inc(path="perparam")
        new_w, new_state = self._fused(type(self).__name__, step)(
            _as_jax(weight), _as_jax(grad), state, _f32(t), _f32(lr),
            _f32(wd), *extras)
        weight._data = new_w
        return new_state

    def pure_step(self, w, g, state, t, lr, wd):
        """Pure functional update used by the in-graph SPMD training step
        (``mxnet_tpu.parallel.TrainStep``): returns ``(new_w, new_state)``
        from jax arrays only. ``t`` is the traced 1-based update count so
        bias-corrected optimizers (Adam family) compile once and stay
        correct on every step. Optimizers whose kernel needs no host-side
        schedule work alias this to :meth:`_leaf_step`; the Adam family
        overrides it with the bias correction traced on-device."""
        raise MXNetError(
            "%s does not implement pure_step; it cannot be fused into an "
            "SPMD train step — use Trainer/Updater instead"
            % self.__class__.__name__)

    def update_multi_precision(self, index, weight, grad, state):
        """fp16/bf16 weights: run the update on the fp32 master copy, then
        cast back (reference mp_sgd_update semantics). Returns the new
        state."""
        if self.multi_precision and _is_mp_dtype(weight.dtype):
            state = ensure_mp_state(self, index, weight, state)
            master, base_state = state
            g32 = NDArray(jnp.asarray(_as_jax(grad), jnp.float32), weight._ctx)
            w32 = NDArray(master, weight._ctx)
            new_base = self.update(index, w32, g32, base_state)
            weight._data = jnp.asarray(w32._data, dtype=_as_jax(weight).dtype)
            return (w32._data, new_base if new_base is not None else base_state)
        new_state = self.update(index, weight, grad, state)
        return new_state if new_state is not None else state

    # ------------------------------------------------------------------
    # lr / wd plumbing (reference optimizer.py:200-320)
    # ------------------------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("LRScheduler of the optimizer has already been defined.")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def sync_num_update(self, t):
        """Single source of truth for the step counter when an in-graph
        step plane (``parallel.TrainStep`` / ``trainplane``) interleaves
        with eager ``Trainer.step``/``Updater`` updates (warmup or eval
        phases mixed into a compiled run): advance ``num_update`` to ``t``
        AND align every per-index count, so the next eager update continues
        at ``t + 1`` instead of replaying the eager-only count — without
        this, an ``lr_scheduler`` reading ``num_update`` would see the two
        paths drift apart (regression-tested in tests/test_trainplane.py).
        """
        t = int(t)
        self.num_update = max(self.num_update, t)
        # begin_num_update seeds indices _update_count has not seen yet
        # (graph-only steps never touch _index_update_count): without
        # advancing it, a param first updated eagerly AFTER t graph steps
        # would restart its per-index count — and e.g. Adam's bias
        # correction — at 1 instead of t + 1.
        self.begin_num_update = max(self.begin_num_update, t)
        for idx in self._index_update_count:
            self._index_update_count[idx] = max(
                self._index_update_count[idx], t)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # ------------------------------------------------------------------
    # jit-fused step dispatch
    # ------------------------------------------------------------------
    def _preprocess(self, grad, weight, wd):
        """Shared rescale → clip → weight-decay prologue, traced into the
        fused kernel (the reference bakes the same sequence into each
        optimizer_op.cc kernel)."""
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = jnp.clip(grad, -self.clip_gradient, self.clip_gradient)
        return grad + wd * weight

    def _preprocess_wd_in_clip(self, grad, weight, wd):
        """rescale → +wd·weight → clip: the adam/ftml/rmsprop/adamax/nadam
        family folds weight decay into the gradient BEFORE clipping
        (reference optimizer.py Adam :1037 ``clip(grad*rescale + wd*weight)``,
        optimizer_op-inl.h AdamUpdate/FTMLKernel/RMSProp kernels), unlike the
        sgd family which clips the bare gradient (``_preprocess``)."""
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = jnp.clip(grad, -self.clip_gradient, self.clip_gradient)
        return grad

    def _preprocess_no_wd(self, grad):
        """rescale → clip, weight decay applied separately at the weight
        update (reference AdaGrad :1105-1108, AdaDelta :1271-1284, DCASGD
        :909-920 — wd never enters the gradient statistics)."""
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = jnp.clip(grad, -self.clip_gradient, self.clip_gradient)
        return grad

    def _fused(self, key, fn):
        """jit-compile ``fn`` once per (variant, rescale_grad, clip) key.

        rescale_grad/clip_gradient are read by the step closures at trace
        time, so they are part of the cache key: Trainer.step() mutates
        rescale_grad per batch size, and a changed value must retrace rather
        than silently reuse the first-traced constant. (State-structure
        variants — momentum on/off, centered RMSProp — need no key of their
        own: jax.jit retraces per input pytree structure.)"""
        key = (key, self.rescale_grad, self.clip_gradient)
        if key not in self._step_cache:
            self._step_cache[key] = jax.jit(fn)
        return self._step_cache[key]

    def __getstate__(self):
        st = self.__dict__.copy()
        st["_step_cache"] = {}
        st.pop("_tree_cache", None)  # fastpath jit variants (fused.py)
        st.pop("_state_struct_cache", None)
        return st


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class Test(Optimizer):
    """Trivial debug optimizer: w -= lr * grad, state keeps a weight copy
    (reference optimizer.py:Test)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return jnp.zeros_like(_as_jax(weight))

    def _leaf_step(self, w, g, state, t, lr, wd):
        return w - lr * g * self.rescale_grad, state

    pure_step = _leaf_step


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision (reference optimizer.py:445;
    fused-op parity: sgd_update/sgd_mom_update/mp_sgd_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros_like(_as_jax(weight))

    def _leaf_step(self, w, g, state, t, lr, wd):
        g = self._preprocess(g, w, wd)
        if state is None:
            return w - lr * g, None
        m = self.momentum * state - lr * g
        return w + m, m

    pure_step = _leaf_step


@register
class ccSGD(SGD):  # noqa: N801 - reference name (optimizer.py:ccSGD)
    """Deprecated alias of SGD kept for reference-code compatibility."""


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference optimizer.py:NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros_like(_as_jax(weight))

    def _leaf_step(self, w, g, state, t, lr, wd):
        g = self._preprocess(g, w, wd)
        if state is None:
            return w - lr * g, None
        m = self.momentum * state + g
        return w - lr * (self.momentum * m + g), m

    pure_step = _leaf_step


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference optimizer.py:SGLD).
    The injected-noise key is drawn on the host per update (one
    ``_global.next_key()`` per parameter per step, the same stream the
    per-parameter path always consumed) and enters the kernel as a traced
    extra."""

    _host_scalars_stateful = True  # consumes the host rng stream in order
    _leaf_step_pointwise = False   # noise draw depends on the weight SHAPE

    def _host_scalars(self, index):
        from . import _global

        return (self._get_lr(index), self._get_wd(index),
                (_global.next_key(),))

    def _leaf_step(self, w, g, state, t, lr, wd, key):
        g = self._preprocess(g, w, wd)
        noise = jax.random.normal(key, w.shape, dtype=w.dtype) * jnp.sqrt(lr)
        return w - lr / 2 * g + noise, state


@register
class SignSGD(Optimizer):
    """Take the sign of the gradient (reference optimizer.py:Signum family)."""

    def _leaf_step(self, w, g, state, t, lr, wd):
        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return w - lr * (jnp.sign(g) + wd * w), state

    pure_step = _leaf_step


@register
class Signum(Optimizer):
    """Sign of momentum SGD (reference optimizer.py:550)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros_like(_as_jax(weight))

    def _leaf_step(self, w, g, state, t, lr, wd):
        if state is None:
            g = g * self.rescale_grad
            if self.clip_gradient is not None:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            return w - lr * (jnp.sign(g) + wd * w), None
        g = self._preprocess(g, w, wd)
        m = self.momentum * state - (1 - self.momentum) * g
        return w + lr * jnp.sign(m) - lr * self.wd_lh * w, m

    pure_step = _leaf_step


@register
class FTML(Optimizer):
    """Follow the Moving Leader (reference optimizer.py:616)."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros_like(w))

    def _leaf_step(self, w, g, state, t, lr, wd):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        d, v, z = state
        g = self._preprocess_wd_in_clip(g, w, wd)
        v = b2 * v + (1 - b2) * g * g
        bc1 = 1 - jnp.power(b1, t)
        bc2 = 1 - jnp.power(b2, t)
        d_t = bc1 / lr * (jnp.sqrt(v / bc2) + eps)
        sigma = d_t - b1 * d
        z = b1 * z + (1 - b1) * g - sigma * w
        return -z / d_t, (d_t, v, z)

    pure_step = _leaf_step


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py:DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        w = _as_jax(weight)
        if self.momentum == 0.0:
            return (None, jnp.asarray(w))
        return (jnp.zeros_like(w), jnp.asarray(w))

    def _leaf_step(self, w, g, state, t, lr, wd):
        mom, prev = state
        g = self._preprocess_no_wd(g)
        if mom is None:
            upd = -lr * (g + wd * w + self.lamda * g * g * (w - prev))
            return w + upd, (None, w)
        m = self.momentum * mom - lr * (
            g + wd * w + self.lamda * g * g * (w - prev))
        return w + m, (m, w)

    pure_step = _leaf_step


@register
class LBSGD(Optimizer):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (reference optimizer.py:672, simplified to the lars core)."""

    def __init__(self, momentum=0.0, multi_precision=False, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum

    _leaf_step_pointwise = False  # layer-wise w/g norms are cross-element

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return jnp.zeros_like(_as_jax(weight))

    def _leaf_step(self, w, g, state, t, lr, wd):
        g = self._preprocess(g, w, wd)
        wnorm = jnp.linalg.norm(w.ravel())
        gnorm = jnp.linalg.norm(g.ravel())
        lars = jnp.where(
            (wnorm > 0) & (gnorm > 0), wnorm / (gnorm + 1e-9), 1.0)
        eff_lr = lr * lars
        if state is None:
            return w - eff_lr * g, None
        m = self.momentum * state - eff_lr * g
        return w + m, m

    pure_step = _leaf_step


@register
class Adam(Optimizer):
    """Adam (reference optimizer.py:1014; fused-op parity adam_update).

    The bias correction is a host-side scalar transform of the learning
    rate (:meth:`_host_scalars`, reference optimizer.py:1037) so the kernel
    itself stays schedule-free; the in-graph :meth:`pure_step` traces the
    same correction on-device from the scanned ``t``."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def _host_scalars(self, index):
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        lr = lr * math.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        return lr, self._get_wd(index), ()

    def _leaf_step(self, w, g, state, t, lr, wd):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m, v = state
        g = self._preprocess_wd_in_clip(g, w, wd)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        return w - lr * m / (jnp.sqrt(v) + eps), (m, v)

    def pure_step(self, w, g, state, t, lr, wd):
        b1, b2 = self.beta1, self.beta2
        lr = lr * jnp.sqrt(1.0 - jnp.power(b2, t)) / (1.0 - jnp.power(b1, t))
        return self._leaf_step(w, g, state, t, lr, wd)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:AdaGrad; sparse lazy path collapses to
    dense — XLA has no sparse, SURVEY §7.3)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return jnp.zeros_like(_as_jax(weight))

    def _leaf_step(self, w, g, state, t, lr, wd):
        g = self._preprocess_no_wd(g)
        h = state + g * g
        return w - lr * (g / jnp.sqrt(h + self.float_stable_eps) + wd * w), h

    pure_step = _leaf_step


@register
class RMSProp(Optimizer):
    """RMSProp, centered (Graves) and plain (reference optimizer.py:1155)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        w = _as_jax(weight)
        if self.centered:
            return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros_like(w))
        return (jnp.zeros_like(w),)

    def _leaf_step(self, w, g, state, t, lr, wd):
        g1, g2, eps = self.gamma1, self.gamma2, self.epsilon
        g = self._preprocess_wd_in_clip(g, w, wd)
        if len(state) == 1:
            (n,) = state
            n = (1 - g1) * g * g + g1 * n
            w = w - lr * g / jnp.sqrt(n + eps)
            if self.clip_weights:
                w = jnp.clip(w, -self.clip_weights, self.clip_weights)
            return w, (n,)
        n, mg, delta = state
        n = (1 - g1) * g * g + g1 * n
        mg = (1 - g1) * g + g1 * mg
        delta = g2 * delta - lr * g / jnp.sqrt(n - mg * mg + eps)
        w = w + delta
        if self.clip_weights:
            w = jnp.clip(w, -self.clip_weights, self.clip_weights)
        return w, (n, mg, delta)

    pure_step = _leaf_step


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def _leaf_step(self, w, g, state, t, lr, wd):
        rho, eps = self.rho, self.epsilon
        g = self._preprocess_no_wd(g)
        acc_g, acc_d = state
        acc_g = rho * acc_g + (1 - rho) * g * g
        delta = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g + eps) * g
        acc_d = rho * acc_d + (1 - rho) * delta * delta
        return w - (delta + wd * w), (acc_g, acc_d)

    pure_step = _leaf_step


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference optimizer.py:Ftrl; fused ftrl_update parity)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))  # (z, n)

    def _leaf_step(self, w, g, state, t, lr, wd):
        l1, beta = self.lamda1, self.beta
        g = g * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        z, n = state
        sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + g * g
        w = jnp.where(
            jnp.abs(z) > l1,
            -(z - jnp.sign(z) * l1) / ((beta + jnp.sqrt(n)) / lr + wd),
            0.0,
        ).astype(w.dtype)
        return w, (z, n)

    pure_step = _leaf_step


@register
class Adamax(Optimizer):
    """AdaMax (reference optimizer.py:Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def _host_scalars(self, index):
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        return lr, self._get_wd(index), ()

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def _leaf_step(self, w, g, state, t, lr, wd):
        b1, b2 = self.beta1, self.beta2
        g = self._preprocess_wd_in_clip(g, w, wd)
        m, u = state
        m = b1 * m + (1 - b1) * g
        u = jnp.maximum(b2 * u, jnp.abs(g))
        return w - lr * m / (u + 1e-8), (m, u)

    def pure_step(self, w, g, state, t, lr, wd):
        lr = lr / (1.0 - jnp.power(self.beta1, t))
        return self._leaf_step(w, g, state, t, lr, wd)


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference optimizer.py:Nadam). The momentum schedule
    is a host-side recurrence (``m_schedule`` multiplies up across updates),
    so its scalars enter the kernel as traced extras via
    :meth:`_host_scalars` — time-varying values never retrace."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    _host_scalars_stateful = True  # m_schedule multiplies up per call

    def create_state(self, index, weight):
        w = _as_jax(weight)
        return (jnp.zeros_like(w), jnp.zeros_like(w))

    def _host_scalars(self, index):
        t = self._index_update_count[index]
        momentum_t = self.beta1 * (1.0 - 0.5 * (0.96 ** (t * self.schedule_decay)))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * (0.96 ** ((t + 1) * self.schedule_decay)))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        return (self._get_lr(index), self._get_wd(index),
                (_f32(momentum_t), _f32(momentum_t_1), _f32(self.m_schedule),
                 _f32(m_schedule_next)))

    def _leaf_step(self, w, g, state, t, lr, wd, mt, mt1, ms, msn):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m, v = state
        g = self._preprocess_wd_in_clip(g, w, wd)
        g_prime = g / (1.0 - ms)
        m = b1 * m + (1.0 - b1) * g
        m_prime = m / (1.0 - msn)
        v = b2 * v + (1.0 - b2) * g * g
        v_prime = v / (1.0 - jnp.power(b2, t))
        m_bar = (1.0 - mt) * g_prime + mt1 * m_prime
        return w - lr * m_bar / (jnp.sqrt(v_prime) + eps), (m, v)


# ---------------------------------------------------------------------------
# Updater (reference optimizer.py:1506)
# ---------------------------------------------------------------------------


class Updater(object):
    """Applies an optimizer to (index, grad, weight) triples, owning the
    per-index state dict — reference optimizer.py:Updater (get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        elif getattr(self.states[index], "_is_zero_shard", False):
            # an eager per-param update interleaving with the ZeRO plane
            # must see the plain per-parameter layout — materialize the
            # whole plane (the next sharded step re-adopts)
            from .fastpath import zero

            zero.materialize_updater(self)
            if index not in self.states:  # lost to a failed donated step
                self.states[index] = \
                    self.optimizer.create_state_multi_precision(index,
                                                                weight)
                self.states_synced[index] = True
        self.states[index] = self.optimizer.update_multi_precision(
            index, weight, grad, self.states[index])

    def sync_state_context(self, state, context):
        return state

    def set_states(self, states):
        """Restore states from :meth:`get_states` bytes."""
        # a restore replaces the whole layout: drop any attached ZeRO
        # plane rather than letting a stale handle alias the old shards
        self._zero_plane = None
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        # stored as numpy; rehydrate to jax on first use
        self.states = {
            k: jax.tree_util.tree_map(
                lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a, v)
            for k, v in self.states.items()
        }
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def adopt_states(self, states: Dict, optimizer=None):
        """Install plain per-index ``states`` directly (no pickle round
        trip) — the sharded-checkpoint restore path: ``elastic`` rebuilds
        per-parameter trees from shard files and hands them here. Any
        attached ZeRO plane is dropped (its handles would alias a layout
        that no longer owns the state; the next sharded step re-adopts
        onto the live mesh), numpy leaves rehydrate to jax arrays, and
        ``optimizer`` — when given — replaces the owned optimizer so the
        restored step counters (``num_update``, per-index counts) become
        the live ones."""
        self._zero_plane = None
        if optimizer is not None:
            self.optimizer = optimizer
        self.states = {
            k: jax.tree_util.tree_map(
                lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a,
                v)
            for k, v in states.items()
        }
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        """Serialize states (optionally with the optimizer) to bytes.
        Sharded (ZeRO) states are materialized back to the plain
        per-parameter layout first — a checkpoint must never depend on
        the mesh it was trained on."""
        from .fastpath import zero

        zero.materialize_updater(self)
        host_states = {
            k: jax.tree_util.tree_map(
                lambda a: np.asarray(a) if isinstance(a, jnp.ndarray) else a, v)
            for k, v in self.states.items()
        }
        return pickle.dumps((host_states, self.optimizer) if dump_optimizer else host_states)


def get_updater(optimizer):
    return Updater(optimizer)
