"""Runtime feature detection.

Counterpart of the reference's build-feature surface (``MXGetVersion`` +
feature macros in ``include/mxnet/base.h``, surfaced per SURVEY §5.6 tier
3): instead of compile-time USE_CUDA/USE_MKLDNN flags, the TPU build's
features are discovered at runtime — which backend is live, whether the
native C++ runtime compiled, whether the distributed service is up.

>>> import mxnet_tpu as mx
>>> mx.runtime.Features()["TPU"].enabled
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict

__all__ = ["Feature", "Features", "feature_list"]


class Feature(object):
    def __init__(self, name: str, enabled: bool, note: str = ""):
        self.name = name
        self.enabled = bool(enabled)
        self.note = note

    def __repr__(self):
        return "%s %s" % ("✔" if self.enabled else "✖", self.name)


def _detect() -> Dict[str, Feature]:
    import jax

    feats = OrderedDict()

    def add(name, enabled, note=""):
        feats[name] = Feature(name, enabled, note)

    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - backend init failure
        backend = "none"
    add("TPU", backend == "tpu", "XLA TPU backend live")
    add("CPU", True, "XLA CPU backend")
    add("BF16", True, "bfloat16 compute (net.cast('bfloat16'))")
    add("INT8", True, "contrib.quantization symmetric int8")

    from . import _native

    add("NATIVE_RUNTIME", _native.native_available(),
        "C++ host engine/storage/recordio (src/)")
    from .libinfo import find_lib_path

    add("PREDICT_API", any("predict" in p for p in find_lib_path()),
        "C predict ABI (src/predict/)")

    try:
        from jax.experimental import pallas  # noqa: F401

        add("PALLAS", True, "custom kernels (interpret mode off-TPU)")
    except ImportError:  # pragma: no cover
        add("PALLAS", False)

    add("DISTRIBUTED", jax.process_count() > 1,
        "multi-process jax.distributed runtime active")
    add("SIGNAL_HANDLER", _native.native_available(),
        "segfault backtrace via MXNET_USE_SIGNAL_HANDLER=1")
    try:
        import torch  # noqa: F401

        add("TORCH_BRIDGE", True, "contrib.torch_bridge interop")
    except ImportError:
        add("TORCH_BRIDGE", False)
    tb = False
    for mod in ("torch.utils.tensorboard", "tensorboardX"):
        try:
            __import__(mod)
            tb = True
            break
        except ImportError:
            continue
    add("TENSORBOARD", tb, "contrib.tensorboard writer backend present")
    return feats


class Features(dict):
    """Mapping name → Feature (reference ``mx.runtime.Features``)."""

    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name: str) -> bool:
        return name in self and self[name].enabled

    def __repr__(self):
        return "[%s]" % ", ".join(repr(f) for f in self.values())


def feature_list():
    """List of Feature objects (reference ``mx.runtime.feature_list``)."""
    return list(Features().values())
