"""Symbolic control flow: ``mx.sym.contrib.foreach/while_loop/cond``.

Reference ``python/mxnet/symbol/contrib.py`` — the body/cond/func callables
are invoked ONCE on fresh subgraph variables to capture a subgraph Symbol,
which is stored in the op node's attrs; variables the subgraph uses that we
did not create (free variables, e.g. RNN weights) become extra op inputs
bound by name. Execution lowers through ``ops/control_flow.py`` to
lax.scan / masked-scan / lax.cond inside the enclosing executor's single
XLA module.
"""
from __future__ import annotations

from .base import MXNetError, flatten_list as _flatten, regroup_list as _regroup
from .name import NameManager

__all__ = ["foreach", "while_loop", "cond"]


def _free_syms(sub, bound_names):
    """Free variables of a subgraph = its inputs (arguments AND auxiliary
    states, e.g. BatchNorm moving stats) minus the loop-interface vars;
    returned as Symbols over the SAME underlying nodes so the outer graph
    binds them (reference contrib.py _cut_subgraph). Subgraph aux states
    are marked ``_forced_aux`` so the OUTER graph classifies them as aux
    too (no gradients, checkpoint aux partition) — the control-flow op's
    input slots carry subgraph variable names, so the slot-name heuristic
    in symbol._is_aux_node cannot see them. Note: moving stats inside a
    control-flow body are NOT updated during training (they would need to
    become loop carries); outputs are correct — train mode normalizes by
    batch stats — but the stats stay at their pre-loop values.
    """
    from .symbol import Symbol

    aux = set(sub.list_auxiliary_states())
    nodes = {n.name: n for n in sub._topo_nodes() if n.is_var()}
    for n in aux:
        nodes[n]._forced_aux = True
    order = [n for n in sub.list_inputs() if n not in bound_names]
    return order, [Symbol([(nodes[n], 0)]) for n in order]


def _check_single_output(flat, what):
    for s in flat:
        if len(s._outputs) != 1:
            raise MXNetError(
                "%s contains a multi-output Symbol (e.g. split()); unpack "
                "it into a list of single-output Symbols first" % what)
    return flat


def foreach(body, data, init_states, name=None, remat=False):
    """Symbolic scan over axis 0 (reference symbol/contrib.py:foreach):
    ``out, states = body(data_slice, states)``.

    ``remat=True`` rematerializes each step's activations in the backward
    (scan-granular jax.checkpoint) — sublinear training memory for deep
    stacks expressed as a scan (the memonger capability, example/memcost)."""
    from . import symbol as sym_mod

    name = NameManager.current().get(name, "foreach")
    data_list, data_fmt = _flatten(data)
    states_list, state_fmt = _flatten(init_states)

    data_vars = [sym_mod.var("%s_in_data%d" % (name, i))
                 for i in range(len(data_list))]
    state_vars = [sym_mod.var("%s_in_state%d" % (name, i))
                  for i in range(len(states_list))]
    data_arg, _ = _regroup(data_vars, data_fmt)
    state_arg, _ = _regroup(state_vars, state_fmt)

    outs, out_states = body(data_arg, state_arg)
    flat_outs, out_fmt = _flatten(outs)
    flat_ostates, _ = _flatten(out_states)
    _check_single_output(flat_outs, "foreach body output")
    _check_single_output(flat_ostates, "foreach body states")
    if len(flat_ostates) != len(states_list):
        raise MXNetError("foreach: body must return as many states as "
                         "init_states (%d vs %d)"
                         % (len(flat_ostates), len(states_list)))

    sub = sym_mod.Group(list(flat_outs) + list(flat_ostates))
    dnames = tuple(v.name for v in data_vars)
    snames = tuple(v.name for v in state_vars)
    free_names, free_symbols = _free_syms(sub, set(dnames) | set(snames))
    res = sym_mod._invoke(
        "_foreach", list(data_list) + list(states_list) + free_symbols,
        {"__subgraph__": sub, "data_names": dnames, "state_names": snames,
         "free_names": tuple(free_names), "num_out_data": len(flat_outs),
         "remat": remat},
        name=name)
    nod = len(flat_outs)
    outputs, _ = _regroup([res[i] for i in range(nod)], out_fmt)
    states, _ = _regroup([res[nod + i] for i in range(len(states_list))],
                         state_fmt)
    return outputs, states


def while_loop(cond, func, loop_vars, max_iterations=None, name=None):
    """Symbolic bounded while (reference symbol/contrib.py:while_loop):
    ``step_out, new_vars = func(*loop_vars)`` while ``cond(*loop_vars)``,
    at most ``max_iterations`` (required: XLA shapes are static)."""
    from . import symbol as sym_mod

    if max_iterations is None:
        raise MXNetError("max_iterations should be specified")
    name = NameManager.current().get(name, "while_loop")
    vars_list, var_fmt = _flatten(loop_vars)
    if not vars_list:
        raise MXNetError("loop_vars should contain at least one element")

    var_vars = [sym_mod.var("%s_in_var%d" % (name, i))
                for i in range(len(vars_list))]
    cond_out = cond(*var_vars)
    step_out, new_vars = func(*var_vars)
    if step_out is None:
        step_out = []
    flat_outs, out_fmt = _flatten(step_out)
    flat_nvars, _ = _flatten(new_vars)
    _check_single_output(flat_outs, "while_loop step output")
    _check_single_output(flat_nvars, "while_loop loop_vars")
    if len(flat_nvars) != len(vars_list):
        raise MXNetError("while_loop: func must return as many loop_vars "
                         "as it was given")

    cond_g = sym_mod.Group([cond_out])
    func_g = sym_mod.Group(list(flat_outs) + list(flat_nvars))
    vnames = tuple(v.name for v in var_vars)
    free = {}
    for g in (cond_g, func_g):
        names, syms = _free_syms(g, set(vnames))
        free.update(zip(names, syms))
    free_names = tuple(free)
    res = sym_mod._invoke(
        "_while_loop", list(vars_list) + [free[n] for n in free_names],
        {"__cond__": cond_g, "__func__": func_g, "loop_var_names": vnames,
         "free_names": free_names, "num_out_data": len(flat_outs),
         "max_iterations": int(max_iterations)},
        name=name)
    nod = len(flat_outs)
    outputs, _ = _regroup([res[i] for i in range(nod)], out_fmt)
    states, _ = _regroup([res[nod + i] for i in range(len(vars_list))],
                         var_fmt)
    return outputs, states


def cond(pred, then_func, else_func, name=None):
    """Symbolic branch (reference symbol/contrib.py:cond). ``pred`` is a
    scalar Symbol; then/else are nullary callables capturing their inputs."""
    from . import symbol as sym_mod

    name = NameManager.current().get(name, "cond")
    then_out = then_func()
    else_out = else_func()
    flat_then, out_fmt = _flatten(then_out)
    flat_else, _ = _flatten(else_out)
    _check_single_output(flat_then, "cond then output")
    _check_single_output(flat_else, "cond else output")
    if len(flat_then) != len(flat_else):
        raise MXNetError("cond: then/else must produce the same number of "
                         "outputs")

    pred_g = sym_mod.Group([pred])
    then_g = sym_mod.Group(list(flat_then))
    else_g = sym_mod.Group(list(flat_else))
    free = {}
    for g in (pred_g, then_g, else_g):
        names, syms = _free_syms(g, set())
        free.update(zip(names, syms))
    input_names = tuple(free)
    res = sym_mod._invoke(
        "_cond", [free[n] for n in input_names],
        {"__pred__": pred_g, "__then__": then_g, "__else__": else_g,
         "input_names": input_names, "num_out": len(flat_then)},
        name=name)
    n = len(flat_then)
    outs = [res[i] for i in range(n)] if n > 1 else [res]
    outputs, _ = _regroup(outs, out_fmt)
    return outputs


def _export_contrib_ops():
    """Expose every registered _contrib_* symbol op under its short name
    (reference mx.sym.contrib.MultiBoxPrior etc.)."""
    from . import symbol as sym_mod

    for flat in dir(sym_mod):
        if flat.startswith("_contrib_"):
            globals().setdefault(flat[len("_contrib_"):],
                                 getattr(sym_mod, flat))


_export_contrib_ops()
