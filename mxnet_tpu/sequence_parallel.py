"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference (MXNet 1.3) predates long-context training; its sequence
story is bucketing + fused RNNs (SURVEY §5.7). For the TPU build, sequence
scaling is a first-class NEW capability expressed through sharding: the
sequence axis of activations is sharded over a mesh axis, and attention —
the one op whose reduction spans the full sequence — is computed with
collectives instead of materializing any (S, S) block on one chip:

- :func:`ring_attention` — blockwise flash-style attention with K/V blocks
  rotating around the ring via ``ppermute`` while queries stay resident;
  per-step compute overlaps the neighbor exchange on ICI. Online-softmax
  (running max/denominator) accumulation keeps the math exact, so the
  result is bit-comparable (up to fp tolerance) to single-device softmax
  attention at ANY sequence length. Memory per chip: O(S/n · S/n) per
  step instead of O(S²).
- :func:`ulysses_attention` — the all-to-all alternative: resharding flips
  (seq-sharded → head-sharded) so each chip runs ordinary full attention
  on a subset of heads, then flips back. One collective each way; best
  when heads ≥ devices and S/n blocks fit in HBM.

Both run under ``jax.shard_map`` over a named mesh axis, compose with the
``dp`` data-parallel axis of :mod:`mxnet_tpu.parallel`, and are reverse-
mode differentiable (shard_map-of-collectives has well-defined vjps).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports it at the top level
    from jax import shard_map as _shard_map
except ImportError:  # the 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["ring_attention", "ulysses_attention", "sequence_mesh"]


def sequence_mesh(n_devices: Optional[int] = None, devices=None,
                  axis_name: str = "sp") -> Mesh:
    """A 1-D mesh over the sequence axis."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def _online_update(m, l, o, scores, v_blk):
    """Flash-attention accumulator update for one K/V block.

    m: (..., Sq, 1) running max; l: (..., Sq, 1) running denominator;
    o: (..., Sq, D) running numerator; scores: (..., Sq, Skv).
    """
    m_blk = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_blk)
    # rescale previous accumulators to the new max
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * alpha + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, *, axis_name, causal, scale, seq_len_local):
    """Per-device body: rotate K/V around the ring, accumulate online
    softmax. q/k/v: (B, H, Sl, D) local blocks."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    neg = jnp.asarray(-jnp.inf, q.dtype)

    row_pos = my_idx * seq_len_local + jnp.arange(sl)  # global query rows

    def step(i, carry):
        k_blk, v_blk, m, l, o = carry
        # the block we hold at step i originated on device (my_idx - i) % n
        src = (my_idx - i) % n
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            col_pos = src * seq_len_local + jnp.arange(sl)
            mask = row_pos[:, None] >= col_pos[None, :]
            scores = jnp.where(mask[None, None], scores, neg)
        m, l, o = _online_update(m, l, o, scores, v_blk)
        # rotate: send our current block to the next rank (overlaps with the
        # next step's compute under XLA's async collectives)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    # derive accumulators from q so their varying-axes (shard_map vma) match
    # the loop-carried K/V blocks — fresh jnp.zeros would be "replicated"
    # typed and reject the carry
    m0 = q[..., :1] * 0 + neg
    l0 = q[..., :1] * 0
    o0 = q * 0
    _, _, m, l, o = lax.fori_loop(0, n, step, (k, v, m0, l0, o0))
    # fully-masked rows (can't happen with causal self-attention, but keep
    # the math safe): l == 0 -> output 0
    return jnp.where(l > 0, o / jnp.maximum(l, 1e-30), 0.0)


def ring_attention(q, k, v, mesh: Optional[Mesh] = None, causal: bool = False,
                   axis_name: str = "sp", scale: Optional[float] = None):
    """Exact softmax attention with the sequence axis sharded over a ring.

    Parameters
    ----------
    q, k, v : (B, H, S, D) NDArrays or jax arrays; S must divide evenly by
        the mesh size. Inputs may be unsharded (they are scattered) or
        already sharded over ``axis_name``.
    mesh : 1-D Mesh over the sequence axis (default: all devices).
    causal : apply the autoregressive mask on GLOBAL positions.

    Returns an array sharded like ``q`` (sequence axis over the mesh).
    """
    qd = q._data if isinstance(q, NDArray) else jnp.asarray(q)
    kd = k._data if isinstance(k, NDArray) else jnp.asarray(k)
    vd = v._data if isinstance(v, NDArray) else jnp.asarray(v)
    if mesh is None:
        mesh = sequence_mesh(axis_name=axis_name)
    n = mesh.devices.size
    b_, h_, s, d = qd.shape
    if s % n != 0:
        raise MXNetError("ring_attention: seq len %d not divisible by %d "
                         "devices" % (s, n))
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    spec = P(None, None, axis_name, None)
    fn = _shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale, seq_len_local=s // n),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = fn(jax.device_put(qd, NamedSharding(mesh, spec)),
             jax.device_put(kd, NamedSharding(mesh, spec)),
             jax.device_put(vd, NamedSharding(mesh, spec)))
    if isinstance(q, NDArray):
        return NDArray(out, q.context)
    return out


def _ulysses_local(q, k, v, *, axis_name, causal, scale):
    """Per-device body: all-to-all seq->heads, full local attention over
    the complete sequence for this device's head subset, all-to-all back.
    Enters with local blocks (B, H, S/n, D); H must divide n devices."""
    n = lax.psum(1, axis_name)

    def seq_to_heads(x):
        # (B, H, Sl, D) -> gather seq, scatter heads -> (B, H/n, S, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
    if causal:
        s = scores.shape[-1]
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores,
                           jnp.asarray(-jnp.inf, scores.dtype))
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh: Optional[Mesh] = None,
                      causal: bool = False, axis_name: str = "sp",
                      scale: Optional[float] = None):
    """All-to-all (DeepSpeed-Ulysses style) sequence-parallel attention:
    reshard seq→heads, ordinary attention per head subset, reshard back.
    Requires ``H % n_devices == 0``."""
    qd = q._data if isinstance(q, NDArray) else jnp.asarray(q)
    kd = k._data if isinstance(k, NDArray) else jnp.asarray(k)
    vd = v._data if isinstance(v, NDArray) else jnp.asarray(v)
    if mesh is None:
        mesh = sequence_mesh(axis_name=axis_name)
    n = mesh.devices.size
    b_, h, s, d = qd.shape
    if s % n != 0 or h % n != 0:
        raise MXNetError("ulysses_attention: seq %d and heads %d must both "
                         "divide by %d devices" % (s, h, n))
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    spec = P(None, None, axis_name, None)
    fn = _shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = fn(jax.device_put(qd, NamedSharding(mesh, spec)),
             jax.device_put(kd, NamedSharding(mesh, spec)),
             jax.device_put(vd, NamedSharding(mesh, spec)))
    if isinstance(q, NDArray):
        return NDArray(out, q.context)
    return out
