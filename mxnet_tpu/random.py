"""`mx.random` — global seeding (reference python/mxnet/random.py)."""
from __future__ import annotations

import numpy as np

from . import _global
from .ndarray import random as _ndrandom

uniform = _ndrandom.uniform
normal = _ndrandom.normal
randn = _ndrandom.randn
randint = _ndrandom.randint
exponential = _ndrandom.exponential
gamma = _ndrandom.gamma
poisson = _ndrandom.poisson
negative_binomial = _ndrandom.negative_binomial
generalized_negative_binomial = _ndrandom.generalized_negative_binomial
multinomial = _ndrandom.multinomial
shuffle = _ndrandom.shuffle


_NP_RNG = np.random.RandomState()


def np_rng() -> np.random.RandomState:
    """Host-side RNG stream used for one-time setup work (weight init,
    dataset shuffling); seeded together with the device stream."""
    return _NP_RNG


def seed(seed_state, ctx="all"):
    """Seed the global RNG stream (reference mx.random.seed; per-ctx seeding
    collapses to one stream because jax PRNG keys are device-agnostic)."""
    _global.seed(seed_state)
    np.random.seed(seed_state % (2**32))
    _NP_RNG.seed(seed_state % (2**32))


def get_state():
    """Snapshot every RNG stream a training run draws from — the device
    key stream, the host setup stream (:func:`np_rng`) and the global
    numpy stream — as one picklable dict. Elastic checkpoints
    (``elastic.CheckpointManager.save_training``) carry it so a
    killed-and-resumed run replays randomness bit-identically."""
    return {
        "device_key": _global.rng_snapshot(),
        "np_rng": _NP_RNG.get_state(),
        "np_global": np.random.get_state(),
    }


def set_state(state):
    """Restore a :func:`get_state` snapshot (inverse, between steps)."""
    _global.restore_rng_snapshot(state["device_key"])
    _NP_RNG.set_state(state["np_rng"])
    np.random.set_state(state["np_global"])
