"""Subgraph framework: partition a Symbol graph and replace node groups
with fused subgraph ops.

TPU-native re-design of the reference's subgraph plugin API
(``src/operator/subgraph/subgraph_property.h:87-114`` — ``SubgraphSelector``
walks the graph seeding/growing node groups, ``SubgraphProperty::
CreateSubgraphNode`` replaces each group with one op executing the captured
subgraph; ``default_subgraph_op.cc`` provides the op-name-list property used
by the quantization pass and TensorRT partitioner).

Here the payoff is different from the reference's: XLA already fuses
elementwise chains, so the value of a subgraph op on TPU is *semantic*
grouping — marking a region for quantization, for a custom Pallas lowering,
or for checkpoint/remat boundaries — while execution stays one traced jax
program (the fused node's fcompute inlines the captured Symbol's jaxprs
under the enclosing jit, so partitioning never breaks whole-graph
compilation).

Partitioning contract (mirrors the reference):
- a property is registered under a backend name
  (``register_subgraph_property``); ``partition_graph(sym, prop)`` returns a
  new Symbol with every maximal *convex* group of selected nodes collapsed
  into one ``_subgraph_op`` node (non-convex groups — where a path between
  two members leaves the group — are split conservatively, like the
  reference's cycle check);
- the captured subgraph is stored in the node attrs as a Symbol and
  round-trips through graph JSON like control-flow ops.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError
from .ops.registry import REQUIRED, get_op, register
from .symbol import Symbol, _Node, var as sym_var

__all__ = [
    "SubgraphSelector", "SubgraphProperty", "DefaultSubgraphProperty",
    "register_subgraph_property", "get_subgraph_property", "partition_graph",
]


class SubgraphSelector(object):
    """Decides which nodes join a subgraph (reference SubgraphSelector,
    subgraph_property.h:40-85)."""

    def select(self, node) -> bool:
        """Seed: may this node start/join a subgraph?"""
        return False

    def select_input(self, node, input_node) -> bool:
        """Grow across the edge input_node → node (both already selected)."""
        return self.select(input_node)

    def select_output(self, node, output_node) -> bool:
        """Grow across the edge node → output_node."""
        return self.select(output_node)


class _OpNameSelector(SubgraphSelector):
    def __init__(self, op_names):
        self.op_names = frozenset(op_names)

    def select(self, node) -> bool:
        return node.op in self.op_names


class SubgraphProperty(object):
    """A partitioning policy (reference SubgraphProperty,
    subgraph_property.h:87)."""

    #: counter so every fused node gets a stable unique name
    _counter = 0

    def create_selector(self) -> SubgraphSelector:
        raise NotImplementedError

    def create_subgraph_node(self, subgraph_sym: Symbol, subgraph_id: int,
                             inputs: List[Tuple[_Node, int]]) -> _Node:
        """Build the replacement node. Default: a ``_subgraph_op`` node
        executing the captured Symbol (reference default_subgraph_op.cc)."""
        return _Node(
            "_subgraph_op",
            "subgraph%d" % subgraph_id,
            {
                "__subgraph__": subgraph_sym,
                "num_args": len(inputs),
                "num_outputs": len(subgraph_sym.list_outputs()),
            },
            list(inputs),
        )


class DefaultSubgraphProperty(SubgraphProperty):
    """Group maximal connected regions of whitelisted ops
    (reference ``mxnet.symbol.contrib._set_subgraph_backend`` default path)."""

    def __init__(self, op_names: Sequence[str]):
        self.op_names = tuple(op_names)

    def create_selector(self) -> SubgraphSelector:
        return _OpNameSelector(self.op_names)


_PROPERTIES: Dict[str, SubgraphProperty] = {}


def register_subgraph_property(name: str, prop: SubgraphProperty) -> None:
    """Register a backend partitioning property (reference
    MXNET_REGISTER_SUBGRAPH_PROPERTY)."""
    _PROPERTIES[name] = prop


def get_subgraph_property(name: str) -> SubgraphProperty:
    if name not in _PROPERTIES:
        raise MXNetError("unknown subgraph backend %r (registered: %s)"
                         % (name, sorted(_PROPERTIES)))
    return _PROPERTIES[name]


# ---------------------------------------------------------------------------
# the fused op
# ---------------------------------------------------------------------------


def _parse_subgraph(v):
    if isinstance(v, str):
        from .symbol import load_json

        return load_json(v)
    return v


def _sg_inputs(attrs):
    n = int(attrs.get("num_args", 1))  # hoisted out of the comprehension
    return ["arg%d" % i for i in range(n)]


def _sg_outputs(attrs):
    return int(attrs.get("num_outputs", 1))


@register(
    "_subgraph_op",
    params={
        "__subgraph__": (_parse_subgraph, REQUIRED),
        "num_args": (int, 1),
        "num_outputs": (int, 1),
    },
    inputs=_sg_inputs,
    num_outputs=_sg_outputs,
)
def _subgraph_op(attrs, *inputs):
    """Execute a captured subgraph (reference default_subgraph_op.cc:
    InvokeOperator over the inner graph; here the inner Symbol's ops trace
    into the SAME jaxpr as the outer graph, so XLA still fuses across the
    boundary)."""
    sub = attrs["__subgraph__"]
    names = sub.list_inputs()
    if len(names) != len(inputs):
        raise MXNetError("_subgraph_op: %d inputs for %d subgraph variables"
                         % (len(inputs), len(names)))
    # variables are named by position (arg0..argN) at capture time, so bind
    # positionally by name — list_inputs() topo order need not match
    vals = {"arg%d" % i: x for i, x in enumerate(inputs)}
    missing = set(names) - set(vals)
    if missing:
        raise MXNetError("_subgraph_op: unbound subgraph variables %s"
                         % sorted(missing))
    outs = sub.eval_jax(vals)
    return tuple(outs) if len(outs) > 1 else outs[0]


# ---------------------------------------------------------------------------
# partitioning pass
# ---------------------------------------------------------------------------


def partition_graph(sym: Symbol, prop) -> Symbol:
    """Return a new Symbol with selected node groups fused
    (reference ``BuildSubgraph`` pass, src/operator/subgraph/build_subgraph.cc).

    ``prop`` is a SubgraphProperty, a registered backend name, or a list of
    op names (sugar for DefaultSubgraphProperty).
    """
    if isinstance(prop, str):
        prop = get_subgraph_property(prop)
    elif isinstance(prop, (list, tuple, set, frozenset)):
        prop = DefaultSubgraphProperty(prop)
    selector = prop.create_selector()

    topo = sym._topo_nodes()
    topo_idx = {id(n): i for i, n in enumerate(topo)}
    selected = [n for n in topo if not n.is_var() and selector.select(n)]
    sel_ids = {id(n) for n in selected}

    # union-find over approved edges between selected nodes
    parent: Dict[int, int] = {id(n): id(n) for n in selected}

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    for n in selected:
        for src, _ in n.inputs:
            if id(src) in sel_ids and selector.select_input(n, src) \
                    and selector.select_output(src, n):
                union(id(n), id(src))

    groups: Dict[int, List[_Node]] = {}
    for n in selected:
        groups.setdefault(find(id(n)), []).append(n)
    # deterministic order; singletons are kept (a 1-op subgraph is still a
    # marked region, e.g. for quantization)
    comps = [sorted(g, key=lambda n: topo_idx[id(n)]) for g in groups.values()]
    comps.sort(key=lambda g: topo_idx[id(g[0])])

    # convexity: walking in topo order, a node outside the group that is a
    # descendant of the group AND an ancestor of a group member would create
    # a cycle after fusion. Split such groups at the offending member.
    kept: List[List[_Node]] = []
    for comp in comps:
        comp_ids = {id(n) for n in comp}
        desc: set = set()  # ids of outside nodes downstream of the group
        good: List[_Node] = []
        lo, hi = topo_idx[id(comp[0])], topo_idx[id(comp[-1])]
        for i in range(lo, hi + 1):
            n = topo[i]
            in_comp = id(n) in comp_ids
            feeds_from_desc = any(id(s) in desc for s, _ in n.inputs)
            from_comp = any(id(s) in comp_ids for s, _ in n.inputs)
            if in_comp:
                if feeds_from_desc:
                    # fusing would swallow a path that leaves the group:
                    # split — this member (and later ones) form their own
                    # groups
                    kept.extend([m] for m in comp[comp.index(n):])
                    comp_ids = {id(m) for m in good}
                    break
                good.append(n)
            elif from_comp or feeds_from_desc:
                desc.add(id(n))
        if good:
            kept.append(good)

    if not kept:
        return sym

    member_group: Dict[int, int] = {}
    for gi, comp in enumerate(kept):
        for n in comp:
            member_group[id(n)] = gi
    group_last = {gi: max(topo_idx[id(n)] for n in comp)
                  for gi, comp in enumerate(kept)}

    # rebuild the graph
    new_of: Dict[Tuple[int, int], Tuple[_Node, int]] = {}

    def remap(src, idx):
        return new_of[(id(src), idx)]

    for i, n in enumerate(topo):
        gi = member_group.get(id(n))
        if gi is None:
            clone = _Node(n.op, n.name, dict(n.attrs),
                          [remap(s, k) for s, k in n.inputs])
            clone._extra_attrs = dict(n._extra_attrs)
            for k in range(n.num_outputs() if not n.is_var() else 1):
                new_of[(id(n), k)] = (clone, k)
            continue
        if i != group_last[gi]:
            continue  # group materializes at its last member
        comp = kept[gi]
        comp_ids = {id(m) for m in comp}
        # external inputs in first-use order
        ext: List[Tuple[_Node, int]] = []
        ext_pos: Dict[Tuple[int, int], int] = {}
        for m in comp:
            for s, k in m.inputs:
                if id(s) not in comp_ids and (id(s), k) not in ext_pos:
                    ext_pos[(id(s), k)] = len(ext)
                    ext.append((s, k))
        # build the captured Symbol over fresh variables
        sub_vars = [sym_var("arg%d" % j) for j in range(len(ext))]
        sub_of: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
        for (sid, k), j in ext_pos.items():
            sub_of[(sid, k)] = (sub_vars[j]._outputs[0][0], 0)
        for m in comp:
            c = _Node(m.op, m.name, dict(m.attrs),
                      [sub_of[(id(s), k)] for s, k in m.inputs])
            c._extra_attrs = dict(m._extra_attrs)
            for k in range(m.num_outputs()):
                sub_of[(id(m), k)] = (c, k)
        # outputs: member outputs consumed outside the group or by sym heads
        out_pairs: List[Tuple[int, int]] = []
        consumed: set = set()
        for n2 in topo:
            if id(n2) in comp_ids:
                continue
            for s, k in n2.inputs:
                if id(s) in comp_ids:
                    consumed.add((id(s), k))
        for s, k in sym._outputs:
            if id(s) in comp_ids:
                consumed.add((id(s), k))
        for m in comp:
            for k in range(m.num_outputs()):
                if (id(m), k) in consumed:
                    out_pairs.append((id(m), k))
        if not out_pairs:  # dead group: keep last member's first output
            out_pairs = [(id(comp[-1]), 0)]
        sub_sym = Symbol([sub_of[p] for p in out_pairs])
        SubgraphProperty._counter += 1
        fused = prop.create_subgraph_node(
            sub_sym, SubgraphProperty._counter,
            [remap(s, k) for (s, k) in ext])
        for j, p in enumerate(out_pairs):
            new_of[p] = (fused, j)

    return Symbol([new_of[(id(s), k)] for s, k in sym._outputs])


# the reference's default_subgraph_op.cc registers the same executor under
# this name; alias for symbol-JSON compatibility
from .ops.registry import OP_REGISTRY as _REG  # noqa: E402

_REG.setdefault("_default_subgraph_op", _REG["_subgraph_op"])
