"""ctypes bridge to the native C++ runtime (``src/*.cc`` → ``libmxtpu.so``).

TPU-native re-design of the reference's C-ABI plumbing
(``python/mxnet/base.py`` ``_load_lib``/``check_call`` over
``include/mxnet/c_api.h``): a small flat C surface (storage pool, host
dependency engine, RecordIO) loaded with ctypes. Unlike the reference —
where the C library IS the framework — the compute path here is JAX/XLA and
the native layer only owns host-side work, so everything degrades to pure
Python when no C++ toolchain is available: every caller must handle
``get_lib() is None``.

The library is compiled on demand from the committed sources with g++ and
cached next to them (``src/build/libmxtpu.so``), rebuilt when any source is
newer than the binary.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import warnings
from pathlib import Path
from typing import Optional

from .base import MXNetError, get_env

__all__ = ["get_lib", "check_call", "native_available", "build_lib"]

_SRC_DIR = Path(__file__).resolve().parent.parent / "src"
_LIB_PATH = _SRC_DIR / "build" / "libmxtpu.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def build_lib(force: bool = False) -> Optional[Path]:
    """Compile ``src/*.cc`` into ``libmxtpu.so`` if missing or stale."""
    sources = sorted(_SRC_DIR.glob("*.cc"))
    if not sources:
        return None
    if not force and _LIB_PATH.exists():
        lib_mtime = _LIB_PATH.stat().st_mtime
        if all(s.stat().st_mtime <= lib_mtime for s in sources + [_SRC_DIR / "mxtpu.h"]):
            return _LIB_PATH
    _LIB_PATH.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        get_env("CXX", "g++", cache=False), "-std=c++17", "-O2", "-fPIC", "-shared",
        "-pthread", "-Wall", "-fvisibility=hidden",
        "-I", str(_SRC_DIR),
    ] + [str(s) for s in sources] + ["-o", str(_LIB_PATH)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True, timeout=300)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as exc:
        detail = getattr(exc, "stderr", "") or str(exc)
        warnings.warn("mxnet_tpu: native library build failed, falling back to "
                      "pure Python: %s" % detail.strip()[:500])
        return None
    return _LIB_PATH


def _configure(lib: ctypes.CDLL) -> None:
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    lib.MXTPUGetLastError.argtypes = []
    lib.MXTPUGetVersion.argtypes = [ctypes.POINTER(ctypes.c_int)]
    # storage
    lib.MXTPUStorageAlloc.argtypes = [ctypes.c_size_t, ctypes.POINTER(ctypes.c_void_p)]
    lib.MXTPUStorageFree.argtypes = [ctypes.c_void_p]
    lib.MXTPUStorageDirectFree.argtypes = [ctypes.c_void_p]
    lib.MXTPUStorageReleaseAll.argtypes = []
    lib.MXTPUStorageStats.argtypes = [u64p] * 5
    # engine
    lib.MXTPUEngineNewVar.argtypes = [u64p]
    lib.MXTPUEngineDeleteVar.argtypes = [ctypes.c_uint64]
    lib.MXTPUEnginePushAsync.argtypes = [
        ENGINE_FN_TYPE, ctypes.c_void_p, u64p, ctypes.c_int, u64p, ctypes.c_int,
        ctypes.c_int, u64p,
    ]
    lib.MXTPUEngineWaitForVar.argtypes = [ctypes.c_uint64]
    lib.MXTPUEngineWaitForAll.argtypes = []
    lib.MXTPUEngineNumWorkers.argtypes = [ctypes.POINTER(ctypes.c_int)]
    lib.MXTPUEngineIsNaive.argtypes = [ctypes.POINTER(ctypes.c_int)]
    # recordio
    vpp = ctypes.POINTER(ctypes.c_void_p)
    lib.MXTPURecordIOWriterCreate.argtypes = [ctypes.c_char_p, vpp]
    lib.MXTPURecordIOWriterWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                             ctypes.c_size_t, u64p]
    lib.MXTPURecordIOWriterTell.argtypes = [ctypes.c_void_p, u64p]
    lib.MXTPURecordIOWriterClose.argtypes = [ctypes.c_void_p]
    lib.MXTPURecordIOReaderCreate.argtypes = [ctypes.c_char_p, vpp]
    lib.MXTPURecordIOReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.MXTPURecordIOReaderNext.argtypes = [ctypes.c_void_p,
                                            ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                                            ctypes.POINTER(ctypes.c_size_t)]
    lib.MXTPURecordIOReaderTell.argtypes = [ctypes.c_void_p, u64p]
    lib.MXTPURecordIOReaderClose.argtypes = [ctypes.c_void_p]


#: Signature of an engine callback: ``int fn(void *arg)`` — nonzero return
#: taints the op's mutable vars (async exception propagation).
ENGINE_FN_TYPE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when unavailable.

    Disable explicitly with ``MXNET_USE_NATIVE=0``.
    """
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    with _lock:
        if _load_attempted:
            return _lib
        if get_env("MXNET_USE_NATIVE", "1", cache=False) == "0":
            _load_attempted = True
            return None
        path = build_lib()
        if path is not None:
            try:
                lib = ctypes.CDLL(str(path))
                _configure(lib)
                _lib = lib
            except OSError as exc:
                warnings.warn("mxnet_tpu: failed to load native library: %s" % exc)
        _load_attempted = True
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def check_call(rc: int) -> None:
    """Raise MXNetError with the thread-local native message on failure
    (reference: ``python/mxnet/base.py`` ``check_call`` / MXGetLastError)."""
    if rc != 0:
        lib = get_lib()
        msg = lib.MXTPUGetLastError().decode("utf-8") if lib is not None else "native call failed"
        raise MXNetError(msg)
