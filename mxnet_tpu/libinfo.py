"""Library metadata (reference python/mxnet/libinfo.py: __version__ and
find_lib_path resolving libmxnet.so)."""
from __future__ import annotations

import os

__all__ = ["__version__", "find_lib_path", "find_include_path"]

#: capability parity target: the reference checkout is MXNet 1.3.0
__version__ = "1.3.0+tpu"


def find_lib_path():
    """Paths of the native runtime libraries (reference find_lib_path —
    there libmxnet.so IS the framework; here the compute path is JAX/XLA
    and the native libs carry the host runtime + predict ABI)."""
    build = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "build")
    libs = [os.path.join(build, n)
            for n in ("libmxtpu.so", "libmxtpu_predict.so")]
    return [p for p in libs if os.path.isfile(p)]


def find_include_path():
    """Directory of the C ABI headers (reference include/mxnet)."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src")
