"""Paged KV cache: static device pools + a host-side page allocator.

The HBM discipline of autoregressive decode. A contiguous per-sequence KV
buffer must be sized for the longest sequence it might ever hold, so a
batch of mixed lengths strands most of its HBM in padding; and growing a
buffer changes its shape, which retraces. Paging fixes both at once
(Ragged Paged Attention, PAPERS.md): KV lives in ONE statically-shaped
pool of fixed-size pages per layer, a sequence owns whatever pages it
needs right now through a page table, and the ragged attention kernel
(:func:`mxnet_tpu.ops.pallas_kernels.paged_attention`) reads through the
table — so allocation is a host-side free-list operation that never
touches a device shape. Nothing recompiles as sequences come, grow and
go.

Split of responsibilities:

* **host side (this class)** — the free list, the per-slot page tables
  and lengths (numpy, static shapes), admission accounting, and the
  ``mxnet_kvcache_pages_in_use`` gauge;
* **device side (pure helpers)** — :func:`write_kv` scatters one step's
  new K/V rows into the pools at host-computed (page, offset) slots;
  traced inside the decode/prefill jit, static shapes throughout.

Page 0 is reserved as the *null page*: page-table padding and inactive
decode slots point at it (the BlockSpec index map must always name a
real page), and masked reads/garbage writes land there harmlessly. The
allocator never hands it out.

Knobs (``docs/env_var.md``): ``MXNET_KVCACHE_PAGE_SIZE`` (default 16
tokens/page), ``MXNET_KVCACHE_PAGES`` (0 = auto-size to the slot count x
max sequence length, + the null page).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..base import MXNetError, get_env

__all__ = ["PagedKVCache", "OutOfPagesError", "write_kv"]

_DEFAULT_PAGE_SIZE = 16

_T_PAGES = telemetry.gauge(
    "mxnet_kvcache_pages_in_use",
    "KV cache pages currently allocated to live sequences",
    labels=("cache",))
_T_CAPACITY = telemetry.gauge(
    "mxnet_kvcache_pages_capacity",
    "allocatable KV cache pages in the pool (excludes the null page)",
    labels=("cache",))


class OutOfPagesError(MXNetError):
    """The free list cannot cover the requested reservation; the caller
    (the decode engine's admission loop) defers the sequence instead of
    growing the pool — static shapes are the contract."""


def write_kv(k_pool, v_pool, layer: int, k_new, v_new, pages, offsets):
    """Scatter one batch of new K/V rows into the layer's pool pages.

    k_pool/v_pool: (L, P, page_size, KH, D) device pools (traced);
    k_new/v_new: (N, KH, D) rows; pages/offsets: (N,) int32 destinations
    (host-computed by :meth:`PagedKVCache.write_slots`). Returns the
    updated pools. Pure — trace it inside the step jit; every shape is
    static, so membership churn never recompiles. Rows whose destination
    is the null page (inactive slots, prompt padding) overwrite garbage
    with garbage by design.
    """
    k_pool = k_pool.at[layer, pages, offsets].set(k_new)
    v_pool = v_pool.at[layer, pages, offsets].set(v_new)
    return k_pool, v_pool


class PagedKVCache:
    """Fixed-size paged KV pools for ``num_slots`` concurrent sequences.

    Device state: ``k_pool``/``v_pool`` of shape ``(num_layers,
    num_pages, page_size, num_kv_heads, head_dim)`` — allocated once,
    shape-stable for the cache's lifetime. The decode engine threads the
    pools through its jitted step (functional update) and stores the
    returned arrays back via :meth:`swap_pools`.

    Host state per slot: a fixed-width page-table row (``max_pages``
    entries, unused entries = the null page 0) and a token count. The
    free list is LIFO — a page freed by one sequence is the next page
    another acquires, which the reuse regression test pins.
    """

    def __init__(self, num_slots: int, max_seq_len: int, num_layers: int,
                 num_kv_heads: int, head_dim: int, page_size: Optional[int]
                 = None, num_pages: Optional[int] = None, dtype="float32",
                 name: str = "decode"):
        import jax.numpy as jnp

        from ..base import np_dtype

        if page_size is None:
            page_size = get_env("MXNET_KVCACHE_PAGE_SIZE",
                                _DEFAULT_PAGE_SIZE, int, cache=False)
        if num_pages is None:
            num_pages = get_env("MXNET_KVCACHE_PAGES", 0, int, cache=False)
        self.page_size = max(1, int(page_size))
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        self.max_pages = -(-self.max_seq_len // self.page_size)
        if not num_pages:
            # worst case: every slot holds a max-length sequence; +1 null
            num_pages = self.num_slots * self.max_pages + 1
        if num_pages < 2:
            raise MXNetError("kvcache needs >= 2 pages (null + 1), got %d"
                             % num_pages)
        self.num_pages = int(num_pages)
        self.name = name
        shape = (int(num_layers), self.num_pages, self.page_size,
                 int(num_kv_heads), int(head_dim))
        self.k_pool = jnp.zeros(shape, np_dtype(dtype))
        self.v_pool = jnp.zeros(shape, np_dtype(dtype))
        # LIFO free list over pages 1..P-1; page 0 is the null page
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self.page_table = np.zeros((self.num_slots, self.max_pages),
                                   np.int32)
        self.seq_lens = np.zeros((self.num_slots,), np.int32)
        self._owned = [0] * self.num_slots  # pages held per slot
        # bumped on every table mutation (reserve/free): the decode
        # engine keys its cached DEVICE copy of the page table on it, so
        # steady decode ticks skip the host->device put entirely
        self.version = 0
        _T_CAPACITY.set(self.num_pages - 1, cache=self.name)
        _T_PAGES.set(0, cache=self.name)

    # -- accounting --------------------------------------------------------
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - 1 - len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        """Pages a sequence of ``n_tokens`` occupies."""
        return -(-int(n_tokens) // self.page_size)

    def pages_owned(self, slot: int) -> int:
        """Pages currently reserved by ``slot`` (0 after :meth:`free`) —
        the ground truth the per-tenant page accounting settles against."""
        return self._owned[int(slot)]

    def can_admit(self, n_tokens: int) -> bool:
        """Whether a full reservation for ``n_tokens`` fits right now."""
        return self.pages_for(n_tokens) <= len(self._free)

    # -- allocation --------------------------------------------------------
    def reserve(self, slot: int, n_tokens: int) -> None:
        """Grow ``slot``'s page run to cover ``n_tokens`` total tokens.

        The decode engine reserves a sequence's WORST CASE (prompt +
        max_new_tokens) at admission, so a sequence admitted can always
        finish — no mid-flight eviction for lack of pages. Raises
        :class:`OutOfPagesError` (leaving the slot unchanged) when the
        free list can't cover it.
        """
        if n_tokens > self.max_seq_len:
            raise MXNetError(
                "sequence of %d tokens exceeds max_seq_len %d"
                % (n_tokens, self.max_seq_len))
        need = self.pages_for(n_tokens) - self._owned[slot]
        if need <= 0:
            return
        if need > len(self._free):
            raise OutOfPagesError(
                "kvcache %r: need %d pages, %d free (pool %d)"
                % (self.name, need, len(self._free), self.num_pages - 1))
        for _ in range(need):
            page = self._free.pop()
            self.page_table[slot, self._owned[slot]] = page
            self._owned[slot] += 1
        self.version += 1
        _T_PAGES.set(self.pages_in_use, cache=self.name)

    def free(self, slot: int) -> None:
        """Return every page ``slot`` owns to the free list and reset its
        table row to the null page. Idempotent."""
        for i in range(self._owned[slot]):
            self._free.append(int(self.page_table[slot, i]))
        self.page_table[slot, :] = 0
        self.seq_lens[slot] = 0
        self._owned[slot] = 0
        self.version += 1
        _T_PAGES.set(self.pages_in_use, cache=self.name)

    # -- write-slot computation (host) -------------------------------------
    def write_slots(self, slot: int, start: int,
                    n_tokens: int) -> Tuple[np.ndarray, np.ndarray]:
        """(pages, offsets) int32 arrays addressing token positions
        ``start .. start+n_tokens`` of ``slot`` — the destinations
        :func:`write_kv` scatters into. Positions must be covered by a
        prior :meth:`reserve`."""
        pos = np.arange(start, start + n_tokens)
        if n_tokens and pos[-1] >= self._owned[slot] * self.page_size:
            raise MXNetError(
                "write past slot %d's reservation (pos %d, %d pages)"
                % (slot, int(pos[-1]), self._owned[slot]))
        pages = self.page_table[slot, pos // self.page_size]
        offsets = (pos % self.page_size).astype(np.int32)
        return pages.astype(np.int32), offsets

    def null_write_slots(self, n_tokens: int) -> Tuple[np.ndarray,
                                                       np.ndarray]:
        """Destinations for rows that must go NOWHERE (inactive decode
        slots, prompt padding): the null page, offset cycling through the
        page so scatter indices stay in range."""
        pos = np.arange(n_tokens)
        return (np.zeros(n_tokens, np.int32),
                (pos % self.page_size).astype(np.int32))

    def swap_pools(self, k_pool, v_pool) -> None:
        """Store the pools returned by a jitted step (functional update
        discipline; with donation the old buffers are already dead)."""
        self.k_pool = k_pool
        self.v_pool = v_pool

    def reset_pools(self) -> None:
        """Fresh zeroed pools (same shapes). The eviction path calls this
        after a failed step: with donation on, the old buffers may have
        been consumed by the failed execution, and every future sequence
        rewrites its pages through prefill before reading them anyway."""
        import jax.numpy as jnp

        shape, dtype = self.k_pool.shape, self.k_pool.dtype
        self.k_pool = jnp.zeros(shape, dtype)
        self.v_pool = jnp.zeros(shape, dtype)

    def stats(self) -> dict:
        return {
            "pages_in_use": self.pages_in_use,
            "pages_free": self.pages_free,
            "pages_capacity": self.num_pages - 1,
            "page_size": self.page_size,
            "max_pages_per_seq": self.max_pages,
        }
