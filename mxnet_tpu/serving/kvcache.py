"""Paged KV cache: static device pools + a host-side page allocator.

The HBM discipline of autoregressive decode. A contiguous per-sequence KV
buffer must be sized for the longest sequence it might ever hold, so a
batch of mixed lengths strands most of its HBM in padding; and growing a
buffer changes its shape, which retraces. Paging fixes both at once
(Ragged Paged Attention, PAPERS.md): KV lives in ONE statically-shaped
pool of fixed-size pages per layer, a sequence owns whatever pages it
needs right now through a page table, and the ragged attention kernel
(:func:`mxnet_tpu.ops.pallas_kernels.paged_attention`) reads through the
table — so allocation is a host-side free-list operation that never
touches a device shape. Nothing recompiles as sequences come, grow and
go.

Split of responsibilities:

* **host side (this class)** — the free list, per-page refcounts, the
  per-slot page tables and lengths (numpy, static shapes), admission
  accounting, the prefix index, and the ``mxnet_kvcache_*`` gauges;
* **device side (pure helpers)** — :func:`write_kv` scatters one step's
  new K/V rows into the pools at host-computed (page, offset) slots;
  traced inside the decode/prefill jit, static shapes throughout.

Page 0 is reserved as the *null page*: page-table padding and inactive
decode slots point at it (the BlockSpec index map must always name a
real page), and masked reads/garbage writes land there harmlessly. The
allocator never hands it out.

Prefix caching (``prefix_cache=True``; the engine knob is
``MXNET_DECODE_PREFIX_CACHE``): pages are REFCOUNTED — a page may be
mapped into several slots' tables at once, and it returns to the free
list only when its last reference drops AND it is not held by the prefix
index. The index keys each *full* page of a prompt by the rolling hash
of its whole token prefix (``key_i = sha1(key_{i-1} || tokens_i)``), so
a lookup that walks the chain and token-verifies every chunk can map a
shared system prompt's pages directly into a new slot — prefilled once
per fleet, not once per request. The first *divergent or partial* page
is shared **copy-on-write**: the matching page is copied into a fresh
page owned by the new sequence (the engine runs the device copy), and
only then written — sharers never observe each other's writes. Pages
whose last slot reference drops but that remain indexed move to a
**cached LRU**: they cost nothing (``pages_in_use`` excludes them), stay
warm for the next hit, and are reclaimed oldest-first the moment a
reservation needs them — eviction never touches a page a live sequence
references.

Knobs (``docs/env_var.md``): ``MXNET_KVCACHE_PAGE_SIZE`` (default 16
tokens/page), ``MXNET_KVCACHE_PAGES`` (0 = auto-size to the slot count x
max sequence length, + the null page).
"""
from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..base import MXNetError, get_env

__all__ = ["PagedKVCache", "OutOfPagesError", "PrefixMatch", "write_kv"]

_DEFAULT_PAGE_SIZE = 16

_T_PAGES = telemetry.gauge(
    "mxnet_kvcache_pages_in_use",
    "KV cache pages currently allocated to live sequences",
    labels=("cache",))
_T_CAPACITY = telemetry.gauge(
    "mxnet_kvcache_pages_capacity",
    "allocatable KV cache pages in the pool (excludes the null page)",
    labels=("cache",))
_T_SHARED = telemetry.gauge(
    "mxnet_kvcache_shared_pages",
    "KV pages currently mapped by more than one live sequence "
    "(refcount > 1: the prefix-sharing win, charged to no single tenant)",
    labels=("cache",))
_T_CACHED = telemetry.gauge(
    "mxnet_kvcache_cached_pages",
    "KV pages held only by the prefix index (refcount 0, reclaimable "
    "on demand — warm capacity, not live usage)",
    labels=("cache",))
_T_PREFIX_HITS = telemetry.counter(
    "mxnet_kvcache_prefix_hits_total",
    "admissions that mapped at least one cached prefix page/token",
    labels=("cache",))
_T_PREFIX_MISSES = telemetry.counter(
    "mxnet_kvcache_prefix_misses_total",
    "admissions that found no cached prefix",
    labels=("cache",))
_T_PRESSURE_SHEDS = telemetry.counter(
    "mxnet_kvcache_pressure_sheds_total",
    "cached-LRU (refcount-0) prefix pages proactively returned to the "
    "free list by the HBM pressure governor's yellow-tier ladder rung "
    "(shed_cached) — warm capacity traded for headroom",
    labels=("cache",))


class OutOfPagesError(MXNetError):
    """The free list (plus every reclaimable cached page) cannot cover
    the requested reservation; the caller (the decode engine's admission
    loop) defers the sequence instead of growing the pool — static
    shapes are the contract."""


def write_kv(k_pool, v_pool, layer: int, k_new, v_new, pages, offsets):
    """Scatter one batch of new K/V rows into the layer's pool pages.

    k_pool/v_pool: (L, P, page_size, KH, D) device pools (traced);
    k_new/v_new: (N, KH, D) rows; pages/offsets: (N,) int32 destinations
    (host-computed by :meth:`PagedKVCache.write_slots`). Returns the
    updated pools. Pure — trace it inside the step jit; every shape is
    static, so membership churn never recompiles. Rows whose destination
    is the null page (inactive slots, prompt padding) overwrite garbage
    with garbage by design.
    """
    k_pool = k_pool.at[layer, pages, offsets].set(k_new)
    v_pool = v_pool.at[layer, pages, offsets].set(v_new)
    return k_pool, v_pool


class _PrefixEntry:
    """One indexed page: the chain key it answers to, its parent key,
    the page id, and the VALID token run stored in it (``page_size``
    tokens for a full page, fewer for a partial — positions beyond
    ``len(tokens)`` hold other sequences' writes and are masked by
    ``seq_lens``, never trusted)."""

    __slots__ = ("key", "parent", "page", "tokens", "full")

    def __init__(self, key: Optional[bytes], parent: bytes, page: int,
                 tokens: np.ndarray, full: bool):
        self.key = key
        self.parent = parent
        self.page = int(page)
        self.tokens = tokens
        self.full = full


class PrefixMatch:
    """Result of :meth:`PagedKVCache.match_prefix`: the run of full
    pages whose whole token prefix matched, plus (optionally) the first
    divergent/partial page and how many of its leading tokens match —
    that page is shared copy-on-write."""

    __slots__ = ("full", "partial", "partial_len", "matched")

    def __init__(self, full: List[_PrefixEntry],
                 partial: Optional[_PrefixEntry], partial_len: int,
                 matched: int):
        self.full = full
        self.partial = partial
        self.partial_len = int(partial_len)
        self.matched = int(matched)


def _chain_key(parent: bytes, chunk: np.ndarray) -> bytes:
    return hashlib.sha1(parent + chunk.astype("<i4").tobytes()).digest()


def _common_prefix_len(a: np.ndarray, b: np.ndarray) -> int:
    n = min(a.size, b.size)
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


class PagedKVCache:
    """Fixed-size paged KV pools for ``num_slots`` concurrent sequences.

    Device state: ``k_pool``/``v_pool`` of shape ``(num_layers,
    num_pages, page_size, num_kv_heads, head_dim)`` — allocated once,
    shape-stable for the cache's lifetime. The decode engine threads the
    pools through its jitted step (functional update) and stores the
    returned arrays back via :meth:`swap_pools`.

    Host state per slot: a fixed-width page-table row (``max_pages``
    entries, unused entries = the null page 0) and a token count. The
    free list is LIFO — a page freed by one sequence is the next page
    another acquires, which the reuse regression test pins. With
    ``prefix_cache=True`` pages carry refcounts, slots may map shared
    read-only pages (charged to no single slot's *exclusive* count), and
    freed-but-indexed pages park in a reclaimable cached-LRU instead of
    the free list.
    """

    def __init__(self, num_slots: int, max_seq_len: int, num_layers: int,
                 num_kv_heads: int, head_dim: int, page_size: Optional[int]
                 = None, num_pages: Optional[int] = None, dtype="float32",
                 name: str = "decode", prefix_cache: bool = False):
        import jax.numpy as jnp

        from ..base import np_dtype

        if page_size is None:
            page_size = get_env("MXNET_KVCACHE_PAGE_SIZE",
                                _DEFAULT_PAGE_SIZE, int, cache=False)
        if num_pages is None:
            num_pages = get_env("MXNET_KVCACHE_PAGES", 0, int, cache=False)
        self.page_size = max(1, int(page_size))
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        self.max_pages = -(-self.max_seq_len // self.page_size)
        if not num_pages:
            # worst case: every slot holds a max-length sequence; +1 null
            num_pages = self.num_slots * self.max_pages + 1
        if num_pages < 2:
            raise MXNetError("kvcache needs >= 2 pages (null + 1), got %d"
                             % num_pages)
        self.num_pages = int(num_pages)
        self.name = name
        shape = (int(num_layers), self.num_pages, self.page_size,
                 int(num_kv_heads), int(head_dim))
        self.k_pool = jnp.zeros(shape, np_dtype(dtype))
        self.v_pool = jnp.zeros(shape, np_dtype(dtype))
        # LIFO free list over pages 1..P-1; page 0 is the null page
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self.page_table = np.zeros((self.num_slots, self.max_pages),
                                   np.int32)
        self.seq_lens = np.zeros((self.num_slots,), np.int32)
        self._owned = [0] * self.num_slots      # pages mapped per slot
        self._exclusive = [0] * self.num_slots  # un-shared pages per slot
        # per-page slot-mapping refcount; a page is live while > 0
        self._ref = np.zeros((self.num_pages,), np.int32)
        # prefix index: chain-key -> full-page entry, parent-key -> the
        # child entries hanging off it (full AND partial — the divergent-
        # page CoW candidates), page -> its entry, and the cached-LRU of
        # refcount-0 indexed pages (reclaim oldest-first)
        self.prefix_cache = bool(prefix_cache)
        self._index: Dict[bytes, _PrefixEntry] = {}
        self._children: Dict[bytes, List[_PrefixEntry]] = {}
        self._page_entry: Dict[int, _PrefixEntry] = {}
        self._cached: "collections.OrderedDict[int, _PrefixEntry]" = \
            collections.OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_matched = 0
        self.pressure_sheds = 0
        # bumped on every table mutation (reserve/free): the decode
        # engine keys its cached DEVICE copy of the page table on it, so
        # steady decode ticks skip the host->device put entirely
        self.version = 0
        # MXNET_KVCACHE_AUDIT=1: every mutation (and every engine tick)
        # re-proves the refcount invariant — the runtime twin of the
        # static resource-lifecycle pass
        self.audit = bool(get_env("MXNET_KVCACHE_AUDIT", 0, int,
                                  cache=False))
        _T_CAPACITY.set(self.num_pages - 1, cache=self.name)
        self._publish()

    # -- accounting --------------------------------------------------------
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_cached(self) -> int:
        """Refcount-0 pages parked in the prefix index — reclaimable."""
        return len(self._cached)

    @property
    def pages_available(self) -> int:
        """Pages a reservation can draw on right now: the free list plus
        every reclaimable cached page."""
        return len(self._free) + len(self._cached)

    @property
    def pages_in_use(self) -> int:
        """Pages mapped by at least one live sequence (cached-LRU pages
        are warm capacity, not usage — they reclaim on demand)."""
        return self.num_pages - 1 - len(self._free) - len(self._cached)

    @property
    def shared_pages(self) -> int:
        """Pages currently mapped by more than one live sequence — the
        refcount>1 set the ``shared`` pseudo-tenant answers for."""
        return int(np.count_nonzero(self._ref > 1))

    def pages_for(self, n_tokens: int) -> int:
        """Pages a sequence of ``n_tokens`` occupies."""
        return -(-int(n_tokens) // self.page_size)

    def pages_owned(self, slot: int) -> int:
        """Pages currently mapped by ``slot`` (0 after :meth:`free`),
        shared mappings included."""
        return self._owned[int(slot)]

    def exclusive_pages(self, slot: int) -> int:
        """Pages ``slot`` owns EXCLUSIVELY (fresh reservations + its CoW
        copies) — the count charged to the owning tenant's page budget;
        shared prefix pages belong to the ``shared`` pseudo-tenant and
        charge nobody twice."""
        return self._exclusive[int(slot)]

    def reserved_tokens(self, slot: int) -> int:
        """Token capacity of ``slot``'s reserved page run — the hard
        ceiling :meth:`write_slots` enforces. The speculative decode
        step clamps its per-tick draft depth so that all k+1 verify
        rows land below this bound: admission reserved the worst case
        (prompt + max_new) up front, so a speculating sequence can
        never grow pages mid-tick and never exceeds the tenant page
        budget it was charged at admission."""
        return self._owned[int(slot)] * self.page_size

    def can_admit(self, n_tokens: int) -> bool:
        """Whether a full reservation for ``n_tokens`` fits right now."""
        return self.pages_for(n_tokens) <= self.pages_available

    def can_admit_prefix(self, n_tokens: int,
                         match: Optional[PrefixMatch]) -> bool:
        """Whether ``n_tokens`` fits given a prefix ``match``: matched
        full pages are mapped (not allocated), the CoW page and the tail
        come from the free list / reclaimable cached pages — minus the
        match's own pages, which must not be reclaimed out from under
        the mapping."""
        need = self.pages_for(n_tokens)
        pinned = 0
        if match is not None:
            need -= len(match.full)
            for e in match.full:
                if e.page in self._cached:
                    pinned += 1
            if match.partial is not None and \
                    match.partial.page in self._cached:
                pinned += 1
        return need <= self.pages_available - pinned

    # -- allocation --------------------------------------------------------
    def _take_page(self, pin=()) -> int:
        """One page off the free list, or — when it's dry — reclaimed
        from the oldest cached (refcount-0, indexed) page not in
        ``pin``. Raises :class:`OutOfPagesError` when neither has one."""
        if self._free:
            return self._free.pop()
        for page in self._cached:
            if page in pin:
                continue
            entry = self._cached.pop(page)
            self._index_remove(entry)
            return page
        raise OutOfPagesError(
            "kvcache %r: pool exhausted (%d pages, 0 free, %d cached all "
            "pinned)" % (self.name, self.num_pages - 1, len(self._cached)))

    def reserve(self, slot: int, n_tokens: int, _pin=()) -> None:
        """Grow ``slot``'s page run to cover ``n_tokens`` total tokens.

        The decode engine reserves a sequence's WORST CASE (prompt +
        max_new_tokens) at admission, so a sequence admitted can always
        finish — no mid-flight eviction for lack of pages. That same
        admission-time worst case also bounds speculative decoding: a
        tick that writes up to k+1 tokens still lands every row at a
        position < prompt + max_new, i.e. inside this reservation (the
        engine clamps the draft depth by :meth:`reserved_tokens`), so
        pages-per-tick growth is ZERO after admission and a tenant's
        page budget can't be exceeded mid-tick. Shared pages
        already mapped by :meth:`admit_prefix` count toward the cover,
        so only the non-shared tail is allocated. Raises
        :class:`OutOfPagesError` (leaving the slot unchanged) when the
        free list plus reclaimable cached pages can't cover it.
        """
        if n_tokens > self.max_seq_len:
            raise MXNetError(
                "sequence of %d tokens exceeds max_seq_len %d"
                % (n_tokens, self.max_seq_len))
        need = self.pages_for(n_tokens) - self._owned[slot]
        if need <= 0:
            return
        usable = self.pages_available - sum(1 for p in _pin
                                            if p in self._cached)
        if need > usable:
            raise OutOfPagesError(
                "kvcache %r: need %d pages, %d free + %d cached (pool %d)"
                % (self.name, need, len(self._free), len(self._cached),
                   self.num_pages - 1))
        for _ in range(need):
            page = self._take_page(pin=_pin)
            self.page_table[slot, self._owned[slot]] = page
            self._owned[slot] += 1
            self._exclusive[slot] += 1
            self._ref[page] = 1
        self.version += 1
        self._publish()

    def free(self, slot: int) -> None:
        """Drop every page mapping ``slot`` holds and reset its table
        row to the null page. A page returns to the free list only when
        its LAST reference drops — other sequences sharing it are
        untouched; an indexed page with no references parks in the
        cached-LRU instead (warm for the next prefix hit, reclaimed on
        demand). Idempotent."""
        for i in range(self._owned[slot]):
            page = int(self.page_table[slot, i])
            if page == 0 or self._ref[page] <= 0:
                # double-free: this mapping's page already dropped its
                # last reference. Decref once only — decrementing past
                # zero used to clamp AND re-append the page, planting a
                # duplicate free-list entry that hands one page to two
                # slots (silent KV corruption). Audit mode makes the
                # re-entrant release loud instead of absorbing it.
                if self.audit:
                    raise MXNetError(
                        "kvcache %r audit: double-free of page %d via "
                        "slot %d (refcount already 0) — a release path "
                        "ran twice over one mapping" % (self.name, page,
                                                        slot))
                continue
            self._ref[page] -= 1
            if self._ref[page] == 0:
                entry = self._page_entry.get(page)
                if entry is not None:
                    self._cached[page] = entry
                else:
                    self._free.append(page)
        self.page_table[slot, :] = 0
        self.seq_lens[slot] = 0
        self._owned[slot] = 0
        self._exclusive[slot] = 0
        self.version += 1
        self._publish()

    # -- prefix index ------------------------------------------------------
    def match_prefix(self, prompt) -> Optional[PrefixMatch]:
        """Walk the index for ``prompt``: the longest run of full pages
        whose rolling-hash chain matches (every chunk token-verified, so
        a hit is exact by construction, not by hash luck), then the best
        divergent/partial child of the last matched key — the CoW page.
        Read-only; returns None when nothing matched (or the index is
        disabled)."""
        if not self.prefix_cache:
            return None
        prompt = np.asarray(prompt, np.int32).ravel()
        ps = self.page_size
        p = int(prompt.size)
        full: List[_PrefixEntry] = []
        parent = b""
        for i in range(p // ps):
            chunk = prompt[i * ps:(i + 1) * ps]
            key = _chain_key(parent, chunk)
            e = self._index.get(key)
            if e is None or not np.array_equal(e.tokens, chunk):
                break
            full.append(e)
            parent = key
        matched = len(full) * ps
        nxt = prompt[matched:matched + ps]
        best, best_n = None, 0
        if nxt.size:
            for e in self._children.get(parent, ()):
                n = _common_prefix_len(e.tokens, nxt)
                if n > best_n:
                    best, best_n = e, n
        matched += best_n
        if matched == 0:
            return None
        return PrefixMatch(full, best, best_n, matched)

    def admit_prefix(self, slot: int, total_tokens: int,
                     match: Optional[PrefixMatch]):
        """Admission in one atomic host step: map the match's full pages
        into ``slot`` (refcount++, read-only sharing), allocate a fresh
        page for the divergent/partial page (the engine device-copies
        the source into it — copy-on-write, charged to the writer), then
        :meth:`reserve` the remaining worst-case tail. Returns
        ``(matched_tokens, cow_src_page_or_None, cow_dst_page_or_None)``.
        Counts the hit/miss. Every failure raises BEFORE any mutation —
        :class:`OutOfPagesError` when the tail cannot be covered,
        :class:`~mxnet_tpu.base.MXNetError` past ``max_seq_len`` — so
        the slot is never left half-mapped."""
        if total_tokens > self.max_seq_len:
            raise MXNetError(
                "sequence of %d tokens exceeds max_seq_len %d"
                % (total_tokens, self.max_seq_len))
        if not self.can_admit_prefix(total_tokens, match):
            raise OutOfPagesError(
                "kvcache %r: prefix admission needs more pages than the "
                "%d free + %d cached available"
                % (self.name, len(self._free), len(self._cached)))
        if match is None or match.matched == 0:
            self.prefix_misses += 1
            _T_PREFIX_MISSES.inc(cache=self.name)
            self.reserve(slot, total_tokens)
            return 0, None, None
        pin = set()
        for e in match.full:
            self._cached.pop(e.page, None)  # adopted: no longer idle
            self.page_table[slot, self._owned[slot]] = e.page
            self._owned[slot] += 1
            self._ref[e.page] += 1
        cow_src = cow_dst = None
        if match.partial is not None:
            # the divergent/partial page is never mapped read-only: the
            # sequence WILL write into it (its remaining tail and/or its
            # first generated tokens), so it gets a private copy now —
            # pinned so the tail reservation can't reclaim the source
            # before the device copy runs
            cow_src = match.partial.page
            pin.add(cow_src)
            cow_dst = self._take_page(pin=pin)
            self.page_table[slot, self._owned[slot]] = cow_dst
            self._owned[slot] += 1
            self._exclusive[slot] += 1
            self._ref[cow_dst] = 1
        self.version += 1
        self.reserve(slot, total_tokens, _pin=pin)
        self.prefix_hits += 1
        self.prefix_tokens_matched += match.matched
        _T_PREFIX_HITS.inc(cache=self.name)
        self._publish()
        return match.matched, cow_src, cow_dst

    def insert_prefix(self, slot: int, prompt) -> None:
        """Index ``slot``'s freshly-prefilled prompt pages: one full
        entry per page-aligned chunk (chain-keyed), plus a partial entry
        for the tail — the future divergent-page CoW donor. Pages that
        are already indexed (mapped FROM the index, or a concurrent
        duplicate) are skipped; generated tokens are never indexed (the
        partial entry's ``tokens`` stop at the prompt)."""
        if not self.prefix_cache:
            return
        prompt = np.asarray(prompt, np.int32).ravel()
        ps = self.page_size
        p = int(prompt.size)
        parent = b""
        for i in range(p // ps):
            chunk = prompt[i * ps:(i + 1) * ps]
            key = _chain_key(parent, chunk)
            page = int(self.page_table[slot, i])
            # page 0 = the slot was freed under us (a close() racing the
            # last chunk): never index the null page
            if page and key not in self._index \
                    and page not in self._page_entry:
                e = _PrefixEntry(key, parent, page, chunk.copy(), True)
                self._index[key] = e
                self._children.setdefault(parent, []).append(e)
                self._page_entry[page] = e
            parent = key
        tail = prompt[(p // ps) * ps:]
        if tail.size:
            page = int(self.page_table[slot, p // ps])
            covered = any(
                e.tokens.size >= tail.size
                and np.array_equal(e.tokens[:tail.size], tail)
                for e in self._children.get(parent, ()))
            if page and page not in self._page_entry and not covered:
                e = _PrefixEntry(None, parent, page, tail.copy(), False)
                self._children.setdefault(parent, []).append(e)
                self._page_entry[page] = e

    def _index_remove(self, entry: _PrefixEntry) -> None:
        if entry.key is not None:
            self._index.pop(entry.key, None)
        kids = self._children.get(entry.parent)
        if kids is not None:
            try:
                kids.remove(entry)
            except ValueError:
                pass
            if not kids:
                del self._children[entry.parent]
        self._page_entry.pop(entry.page, None)

    def shed_cached(self, n: Optional[int] = None) -> int:
        """Proactively reclaim up to ``n`` (``None`` = all) cached-LRU
        refcount-0 pages to the free list, oldest-first — the governor's
        *yellow*-tier ladder rung. Distinct from demand reclaim inside
        ``_take_page`` (which takes cached pages only when a reservation
        needs them): shedding trades warm prefix capacity for free-list
        headroom *before* anything asks, so an admission under pressure
        never has to choose between deferring and evicting. Touches only
        pages no live sequence references — sequences in flight are
        unaffected. Returns the number of pages shed and counts them in
        ``mxnet_kvcache_pressure_sheds_total{cache=}``."""
        shed = 0
        while self._cached and (n is None or shed < n):
            page, entry = self._cached.popitem(last=False)
            self._index_remove(entry)
            self._free.append(page)
            shed += 1
        if shed:
            self.pressure_sheds += shed
            _T_PRESSURE_SHEDS.inc(shed, cache=self.name)
            self._publish()
        return shed

    def clear_prefix_index(self) -> None:
        """Drop EVERY index entry and return cached (refcount-0) pages
        to the free list. Called when pool *content* stops being
        trustworthy — a weight swap (KV computed under old params must
        not match new-params prompts) or a pool re-zero after eviction.
        Pages still mapped by live slots keep their refcounts and free
        normally later."""
        for page in self._cached:
            self._free.append(page)
        self._cached.clear()
        self._index.clear()
        self._children.clear()
        self._page_entry.clear()
        self._publish()

    # -- write-slot computation (host) -------------------------------------
    def write_slots(self, slot: int, start: int,
                    n_tokens: int) -> Tuple[np.ndarray, np.ndarray]:
        """(pages, offsets) int32 arrays addressing token positions
        ``start .. start+n_tokens`` of ``slot`` — the destinations
        :func:`write_kv` scatters into. Positions must be covered by a
        prior :meth:`reserve`."""
        pos = np.arange(start, start + n_tokens)
        if n_tokens and pos[-1] >= self._owned[slot] * self.page_size:
            raise MXNetError(
                "write past slot %d's reservation (pos %d, %d pages)"
                % (slot, int(pos[-1]), self._owned[slot]))
        pages = self.page_table[slot, pos // self.page_size]
        offsets = (pos % self.page_size).astype(np.int32)
        return pages.astype(np.int32), offsets

    def null_write_slots(self, n_tokens: int) -> Tuple[np.ndarray,
                                                       np.ndarray]:
        """Destinations for rows that must go NOWHERE (inactive decode
        slots, prompt padding, already-cached positions a chunk only
        recomputes): the null page, offset cycling through the page so
        scatter indices stay in range."""
        pos = np.arange(n_tokens)
        return (np.zeros(n_tokens, np.int32),
                (pos % self.page_size).astype(np.int32))

    def swap_pools(self, k_pool, v_pool) -> None:
        """Store the pools returned by a jitted step (functional update
        discipline; with donation the old buffers are already dead)."""
        self.k_pool = k_pool
        self.v_pool = v_pool

    def reset_pools(self) -> None:
        """Fresh zeroed pools (same shapes). The eviction path calls this
        after a failed step: with donation on, the old buffers may have
        been consumed by the failed execution, and every future sequence
        rewrites its pages through prefill before reading them anyway.
        The prefix index dies with the content it described."""
        import jax.numpy as jnp

        shape, dtype = self.k_pool.shape, self.k_pool.dtype
        self.k_pool = jnp.zeros(shape, dtype)
        self.v_pool = jnp.zeros(shape, dtype)
        self.clear_prefix_index()

    def _publish(self) -> None:
        _T_PAGES.set(self.pages_in_use, cache=self.name)
        _T_CACHED.set(self.pages_cached, cache=self.name)
        _T_SHARED.set(self.shared_pages, cache=self.name)
        if self.audit:
            self.audit_check()

    def audit_check(self) -> None:
        """``MXNET_KVCACHE_AUDIT=1``: re-prove the refcount invariant —
        the runtime counterpart of tpulint's ``resource-lifecycle`` pass.
        Runs after every mutation (via :meth:`_publish`) and once per
        decode tick from the engine. Raises :class:`MXNetError` on the
        first violated invariant:

        - ``pages_in_use`` equals the number of pages with a live ref;
        - ``sum(ref)`` equals the number of live page-table mappings
          (the first ``owned`` entries of every slot row);
        - the free list holds no duplicates, no null page, no referenced
          page, and is disjoint from the cached-LRU;
        - cached pages all carry refcount 0.
        """
        live_refs = int(np.count_nonzero(self._ref > 0))
        if self.pages_in_use != live_refs:
            raise MXNetError(
                "kvcache %r audit: pages_in_use %d != pages with live "
                "refs %d (free=%d cached=%d) — a release path leaked or "
                "double-counted" % (self.name, self.pages_in_use,
                                    live_refs, len(self._free),
                                    len(self._cached)))
        mappings = sum(self._owned)
        total_ref = int(self._ref.sum())
        if total_ref != mappings:
            raise MXNetError(
                "kvcache %r audit: sum of page refcounts %d != live "
                "page-table mappings %d — refcounts and table rows "
                "disagree" % (self.name, total_ref, mappings))
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            raise MXNetError(
                "kvcache %r audit: duplicate entries on the free list — "
                "one page would be handed to two slots"
                % (self.name,))
        if 0 in free_set:
            raise MXNetError(
                "kvcache %r audit: null page 0 on the free list"
                % (self.name,))
        if free_set & set(self._cached):
            raise MXNetError(
                "kvcache %r audit: page(s) %s on the free list AND in "
                "the cached-LRU" % (self.name,
                                    sorted(free_set & set(self._cached))))
        bad = [p for p in self._free if self._ref[p] > 0]
        if bad:
            raise MXNetError(
                "kvcache %r audit: referenced page(s) %s on the free "
                "list" % (self.name, bad))
        bad = [p for p in self._cached if self._ref[p] != 0]
        if bad:
            raise MXNetError(
                "kvcache %r audit: cached-LRU page(s) %s carry a live "
                "refcount" % (self.name, bad))
        for s in range(self.num_slots):
            if self._exclusive[s] > self._owned[s]:
                raise MXNetError(
                    "kvcache %r audit: slot %d exclusive count %d > "
                    "owned %d" % (self.name, s, self._exclusive[s],
                                  self._owned[s]))

    def stats(self) -> dict:
        out = {
            "pages_in_use": self.pages_in_use,
            "pages_free": self.pages_free,
            "pages_capacity": self.num_pages - 1,
            "page_size": self.page_size,
            "max_pages_per_seq": self.max_pages,
        }
        if self.prefix_cache:
            total = self.prefix_hits + self.prefix_misses
            out.update({
                "prefix_cache": True,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_hit_ratio": (self.prefix_hits / total
                                     if total else 0.0),
                "prefix_tokens_matched": self.prefix_tokens_matched,
                "pages_cached": self.pages_cached,
                "shared_pages": self.shared_pages,
                "index_entries": len(self._page_entry),
            })
        return out
