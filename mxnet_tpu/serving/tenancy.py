"""Multi-tenant serving control plane: who gets the engine, and when.

One decode engine (or batch server) fronts many clients. Without a
control plane the sharing is accidental: admission is FIFO, so a hot
client's backlog delays everyone behind it; the queue bound, the KV page
pool and the circuit breaker are all global, so one tenant's overload or
poisoned traffic sheds — or trips the breaker for — the whole fleet.
This module makes the sharing *deliberate*:

* **tenant registry** — every request carries a ``tenant_id``
  (``submit(..., tenant=)``; untagged callers ride the ``default``
  tenant). Tenants declare a **priority class** (``interactive`` /
  ``standard`` / ``batch`` — strict priority between classes), a
  **weight** (fair share within the class), a bounded **sub-queue**, a
  KV **page budget**, and a **token-rate** budget, either
  programmatically or through the ``MXNET_TENANTS`` spec;
* **weighted-fair queueing** — :class:`WeightedFairQueue` replaces the
  single FIFO: per-tenant bounded sub-queues (shed with
  ``QueueFullError`` *before* the global queue fills) drained by
  deficit-round-robin, so admission order is proportional to weight, not
  to arrival order. A tenant that cannot be admitted right now (page
  budget, rate budget, open breaker) is *deferred* — skipped without
  blocking the tenants behind it, which is exactly the head-of-line
  coupling the FIFO had. The HBM pressure governor
  (:mod:`mxnet_tpu.resilience.hbm`) adds one more deferral rung: under
  ``orange``/``red`` tiers the engine defers ``batch``-class tenants
  (``deferred_pressure`` in the stats snapshot) while ``interactive``
  traffic keeps flowing — degradation never inverts priority;
* **per-tenant circuit breakers** — :class:`TenantBreaker` counts a
  tenant's own request failures in a sliding window and sheds *that
  tenant alone* (:class:`TenantUnavailableError`) while the engine-level
  breaker stays reserved for engine-level faults. Visible as
  ``mxnet_tenant_breaker_state{server,tenant}``;
* **resource budgets** — KV page quotas and token-bucket rate limits
  enforced at decode admission: a tenant at its budget defers, everyone
  else keeps flowing.

The queue is NOT internally locked: the owning engine already serializes
submit/admission under its own condition variable, and a second lock
here would only add a deadlock surface. :class:`TenantRegistry` and
:class:`TenantBreaker` ARE thread-safe (submit() touches them before
taking the engine lock).

Spec DSL (``MXNET_TENANTS``, or the ``tenants=`` constructor argument)
— ``;``-separated tenants of ``,``-separated ``key=value`` pairs; a bare
first token is the tenant id::

    MXNET_TENANTS="gold,weight=4,priority=interactive,pages=64,rate=500;
                   bronze,weight=1,priority=batch,depth=32"

Keys: ``id``/bare token, ``weight``, ``priority`` (class name or int),
``depth`` (sub-queue bound), ``pages`` (KV page budget, 0 = unlimited),
``rate`` (tokens/s, 0 = unlimited), ``burst`` (token bucket size, 0 =
auto). Defaults come from the ``MXNET_TENANT_*`` knobs
(``docs/env_var.md``).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from .. import telemetry
from ..telemetry import flightrec as _flightrec
from ..telemetry import slo as _slo
from ..base import MXNetError, get_env
from ..resilience.breaker import STATE_VALUE
from .batcher import EngineUnavailableError
from .stats import TenantStats

__all__ = ["Tenant", "TenantRegistry", "TenantBreaker",
           "TenantUnavailableError", "WeightedFairQueue", "parse_tenants",
           "aggregate_snapshots", "PRIORITY_CLASSES", "DEFAULT_TENANT",
           "SHARED_TENANT"]

#: The tenant untagged ``submit()`` calls ride.
DEFAULT_TENANT = "default"

#: Reserved pseudo-tenant: prefix-cache pages shared by more than one
#: sequence (refcount > 1) are charged here, to NO real tenant's page
#: budget — a sharer pays only for its exclusive tail and CoW copies, so
#: shared system prompts are never double-charged. The id cannot be
#: registered or submitted against; it appears as a synthetic row in
#: ``stats()["tenants"]`` reporting the engine-wide shared-page count.
SHARED_TENANT = "shared"

#: Strict-priority admission classes: a lower value is admitted first,
#: weights apportion the share *within* a class only. ``batch`` traffic
#: therefore only runs when no ``interactive``/``standard`` request is
#: admissible — the documented starvation trade of strict priority.
PRIORITY_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}

_DEF_WEIGHT = 1.0
_DEF_DEPTH = 64
_DEF_BREAKER_THRESHOLD = 5
_DEF_BREAKER_WINDOW_S = 30.0
_DEF_BREAKER_RESET_S = 10.0

_T_BREAKER = telemetry.gauge(
    "mxnet_tenant_breaker_state",
    "per-tenant circuit breaker state (0 closed, 1 half-open, 2 open)",
    labels=("server", "tenant"))
_T_BREAKER_TRANS = telemetry.counter(
    "mxnet_tenant_breaker_transitions_total",
    "per-tenant circuit breaker state transitions",
    labels=("server", "tenant", "to"))


class TenantUnavailableError(EngineUnavailableError):
    """The *tenant's* breaker is open: this tenant's traffic is shed
    while every other tenant keeps being served (contrast
    :class:`~mxnet_tpu.serving.batcher.EngineUnavailableError`, the
    engine-wide shed)."""

    def __init__(self, tenant_id: str, state: str):
        super().__init__("tenant %r breaker is %s: request shed (other "
                         "tenants unaffected)" % (tenant_id, state))
        self.tenant_id = tenant_id


class TenantBreaker:
    """Sliding-window circuit breaker for one tenant's traffic.

    Differs from the engine :class:`~mxnet_tpu.resilience.CircuitBreaker`
    deliberately: that one counts *consecutive* failures (an engine that
    answers anything is healthy), while a misbehaving tenant's failures
    are *interleaved* with other tenants' successes — so here a success
    does NOT reset the count; the breaker opens when
    ``failure_threshold`` of the tenant's own requests failed within the
    trailing ``window_s`` seconds. ``reset_timeout_s`` later one
    half-open probe request is admitted; its success closes the breaker,
    its failure re-opens it. Thread-safe.
    """

    def __init__(self, server: str, tenant_id: str,
                 failure_threshold: Optional[int] = None,
                 window_s: Optional[float] = None,
                 reset_timeout_s: Optional[float] = None,
                 half_open_max: int = 1):
        if failure_threshold is None:
            failure_threshold = get_env("MXNET_TENANT_BREAKER_THRESHOLD",
                                        _DEF_BREAKER_THRESHOLD, int,
                                        cache=False)
        if window_s is None:
            window_s = get_env("MXNET_TENANT_BREAKER_WINDOW_S",
                               _DEF_BREAKER_WINDOW_S, float, cache=False)
        if reset_timeout_s is None:
            reset_timeout_s = get_env("MXNET_TENANT_BREAKER_RESET_S",
                                      _DEF_BREAKER_RESET_S, float,
                                      cache=False)
        self.server = server
        self.tenant_id = tenant_id
        self.failure_threshold = max(1, int(failure_threshold))
        self.window_s = max(0.001, float(window_s))
        self.reset_timeout_s = max(0.0, float(reset_timeout_s))
        self.half_open_max = max(1, int(half_open_max))
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures: Deque[float] = collections.deque()
        self._opened_at = 0.0
        self._probes = 0
        self._probe_at = 0.0
        _T_BREAKER.set(STATE_VALUE["closed"], server=server,
                       tenant=tenant_id)

    def _transition(self, to: str) -> None:
        # caller holds self._lock
        self._state = to
        _T_BREAKER.set(STATE_VALUE[to], server=self.server,
                       tenant=self.tenant_id)
        _T_BREAKER_TRANS.inc(server=self.server, tenant=self.tenant_id,
                             to=to)
        # black box: "which tenant's breaker tripped right before the
        # death" is the first question a post-mortem asks
        _flightrec.record("tenant_breaker", server=self.server,
                          tenant=self.tenant_id, to=to)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._failures and self._failures[0] < horizon:
            self._failures.popleft()

    def _elapsed(self, now: float) -> bool:
        return now - self._opened_at >= self.reset_timeout_s

    @property
    def state(self) -> str:
        with self._lock:
            if self._state == "open" and self._elapsed(time.monotonic()):
                return "half_open"
            return self._state

    def allow(self) -> bool:
        """May one of this tenant's requests be admitted right now?
        Open->half-open promotion is time-based, here — like the engine
        breaker, a caller that only asks ``allow`` drives the machine."""
        with self._lock:
            if self._state == "closed":
                return True
            now = time.monotonic()
            if self._state == "open":
                if not self._elapsed(now):
                    return False
                self._transition("half_open")
                self._probes = 1
                self._probe_at = now
                return True
            if self._probes < self.half_open_max:
                self._probes += 1
                self._probe_at = now
                return True
            if now - self._probe_at >= self.reset_timeout_s:
                # probe lease expired: an admitted probe whose request
                # never reported (deferred after allow(), expired at
                # assembly) must not wedge the breaker half-open forever
                self._probe_at = now
                return True
            return False

    def on_success(self) -> None:
        with self._lock:
            if self._state != "closed":
                self._transition("closed")
                self._failures.clear()
                self._probes = 0

    def on_failure(self) -> None:
        with self._lock:
            now = time.monotonic()
            self._failures.append(now)
            self._prune(now)
            if self._state == "half_open":
                self._transition("open")
                self._opened_at = now
                self._probes = 0
            elif self._state == "closed" and \
                    len(self._failures) >= self.failure_threshold:
                self._transition("open")
                self._opened_at = now

    def __repr__(self) -> str:
        return "TenantBreaker(%r/%r, state=%s, failures=%d/%d in %.0fs)" % (
            self.server, self.tenant_id, self.state, len(self._failures),
            self.failure_threshold, self.window_s)


class _TokenBucket:
    """Continuous-refill token bucket; ``rate <= 0`` disables (always
    admits). Guarded by the owning Tenant's lock."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: float):
        self.rate = max(0.0, float(rate))
        self.burst = float(burst)
        self.tokens = self.burst
        self._last = time.monotonic()

    def try_take(self, cost: float) -> bool:
        if self.rate <= 0.0:
            return True
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


class Tenant:
    """One tenant's configuration + runtime state inside one engine.

    Created through :class:`TenantRegistry`; the engine's admission loop
    is the only writer of the queue/deficit fields (under the engine
    lock), while page/rate accounting takes the tenant's own lock so the
    close() path can release concurrently with the worker.
    """

    def __init__(self, registry: "TenantRegistry", tenant_id: str,
                 weight: float, priority: int, queue_depth: int,
                 page_budget: Optional[int], rate: float, burst: float,
                 breaker: TenantBreaker, stats: TenantStats,
                 spec_k: Optional[int] = None):
        self.tenant_id = tenant_id
        self.weight = max(0.01, float(weight))
        self.priority = int(priority)
        self.queue_depth = max(1, int(queue_depth))
        self.page_budget = page_budget if page_budget else None
        # speculative draft-depth CAP for this tenant's slots: None =
        # inherit the engine's MXNET_DECODE_SPEC_K. Can only LOWER the
        # engine k (the verify width K+1 is a compile-time shape; a
        # tenant asking for more would recompile the step) — the lever
        # that stops one slow-accepting tenant burning a replica's tick
        # budget on rejected verify rows. Mutable at runtime (the fleet's
        # configure_speculation writes it); plain int read each tick.
        self.spec_k = None if spec_k is None else max(0, int(spec_k))
        # the SLO engine divides the tenant burn/violation alerts by
        # these (instance key mirrors the registry's sorted-label key:
        # server/tenant)
        inst = "%s/%s" % (registry.server, tenant_id)
        _slo.note_bound("tenant_queue_depth", inst, self.queue_depth)
        if self.page_budget is not None:
            _slo.note_bound("tenant_pages", inst, self.page_budget)
        self.rate = max(0.0, float(rate))
        self.breaker = breaker
        self.stats = stats
        # maxlen is a belt-and-braces backstop: the engine sheds with
        # QueueFullError BEFORE append ever reaches the bound, so maxlen
        # can never silently drop — it just makes "bounded" structural
        self.queue: Deque = collections.deque(maxlen=self.queue_depth)
        self.deficit = 0.0
        self._lock = threading.Lock()
        self._pages_in_use = 0
        if self.rate > 0.0:
            if burst <= 0.0:
                # auto burst: one second of budget, but never so small a
                # single admissible request could not pass
                burst = max(self.rate, float(registry.max_cost))
            self._bucket: Optional[_TokenBucket] = _TokenBucket(self.rate,
                                                                burst)
            self.burst = self._bucket.burst
        else:
            self._bucket = None
            self.burst = 0.0

    # -- budgets -----------------------------------------------------------
    @property
    def pages_in_use(self) -> int:
        return self._pages_in_use

    def within_page_budget(self, need: int) -> bool:
        if self.page_budget is None:
            return True
        with self._lock:
            return self._pages_in_use + int(need) <= self.page_budget

    def charge_pages(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._pages_in_use += int(n)
            pages = self._pages_in_use
        self.stats.set_pages(pages)

    def release_pages(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._pages_in_use = max(0, self._pages_in_use - int(n))
            pages = self._pages_in_use
        self.stats.set_pages(pages)

    def take_tokens(self, cost: float) -> bool:
        if self._bucket is None:
            return True
        with self._lock:
            return self._bucket.try_take(float(cost))

    def refund_tokens(self, cost: float) -> None:
        """Return a charge whose admission was vetoed AFTER the bucket
        was debited (e.g. by the breaker) — without the refund a
        deferred tenant's retried admissions would drain its whole
        burst for work that never ran."""
        if self._bucket is None:
            return
        with self._lock:
            self._bucket.tokens = min(self._bucket.burst,
                                      self._bucket.tokens + float(cost))

    # -- failure attribution ----------------------------------------------
    def on_request_failure(self) -> None:
        """One of this tenant's requests failed (poisoned prompt, fault
        injected against this tenant, prefill error): per-request
        failures feed the TENANT breaker — the engine breaker is
        reserved for tick-level engine faults."""
        self.breaker.on_failure()
        self.stats.on_error()

    def snapshot(self) -> Dict:
        out = self.stats.snapshot()
        out.update({
            "weight": self.weight,
            "priority": self.priority,
            "queue_depth_bound": self.queue_depth,
            "queued": len(self.queue),
            "page_budget": self.page_budget,
            "pages_in_use": self.pages_in_use,
            "rate_tokens_s": self.rate,
            "spec_k": self.spec_k,
            "breaker": self.breaker.state,
        })
        return out


def parse_tenants(spec: str) -> List[Dict]:
    """Parse the ``MXNET_TENANTS`` DSL into register() kwargs dicts;
    malformed input raises (a typo'd tenant spec silently dropping a
    quota would be an isolation hole, not a default)."""
    out: List[Dict] = []
    for chunk in str(spec).split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        cfg: Dict = {}
        for i, tok in enumerate(chunk.split(",")):
            tok = tok.strip()
            if not tok:
                continue
            key, sep, val = tok.partition("=")
            key, val = key.strip(), val.strip()
            if not sep:
                if i == 0:
                    cfg["tenant_id"] = key
                    continue
                raise MXNetError("tenant spec: %r is not key=value" % tok)
            if not val:
                raise MXNetError("tenant spec: empty value in %r" % tok)
            try:
                if key == "id":
                    cfg["tenant_id"] = val
                elif key == "weight":
                    cfg["weight"] = float(val)
                elif key == "priority":
                    cfg["priority"] = (PRIORITY_CLASSES[val]
                                       if val in PRIORITY_CLASSES
                                       else int(val))
                elif key == "depth":
                    cfg["queue_depth"] = int(val)
                elif key == "pages":
                    cfg["page_budget"] = int(val)
                elif key == "rate":
                    cfg["rate"] = float(val)
                elif key == "burst":
                    cfg["burst"] = float(val)
                elif key == "spec_k":
                    cfg["spec_k"] = int(val)
                else:
                    raise MXNetError("tenant spec: unknown key %r in %r"
                                     % (key, tok))
            except (TypeError, ValueError):
                raise MXNetError("tenant spec: bad value in %r" % tok)
        if "tenant_id" not in cfg:
            raise MXNetError("tenant spec: chunk %r names no tenant id"
                             % chunk)
        out.append(cfg)
    return out


class TenantRegistry:
    """Per-engine tenant table: registration-ordered, thread-safe,
    auto-registering (a fleet sees new tenant ids without a deploy —
    unknown ids get the default configuration).

    ``max_cost`` is the largest admission cost a single request can
    carry (the decode plane passes ``max_seq_len`` tokens; the batch
    plane 1) — it sizes the DRR quantum and the auto token-bucket burst.
    """

    def __init__(self, server: str = "serving", spec: Optional[str] = None,
                 max_cost: float = 1.0,
                 default_queue_depth: Optional[int] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_window_s: Optional[float] = None,
                 breaker_reset_s: Optional[float] = None):
        self.server = server
        self.max_cost = max(1.0, float(max_cost))
        self._breaker_kw = dict(failure_threshold=breaker_threshold,
                                window_s=breaker_window_s,
                                reset_timeout_s=breaker_reset_s)
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}
        self._order: List[str] = []
        self._def_weight = get_env("MXNET_TENANT_WEIGHT", _DEF_WEIGHT,
                                   float, cache=False)
        # 0 = inherit: an unconfigured tenant's sub-queue is as deep as
        # the engine's global bound (single-tenant traffic then sheds
        # exactly where the pre-tenancy FIFO did); the knob or a spec
        # `depth=` tightens it per tenant
        self._def_depth = get_env("MXNET_TENANT_QUEUE_DEPTH", 0, int,
                                  cache=False)
        if self._def_depth <= 0:
            self._def_depth = (int(default_queue_depth)
                               if default_queue_depth else _DEF_DEPTH)
        self._def_pages = get_env("MXNET_TENANT_PAGE_BUDGET", 0, int,
                                  cache=False)
        self._def_rate = get_env("MXNET_TENANT_RATE", 0.0, float,
                                 cache=False)
        self._def_burst = get_env("MXNET_TENANT_BURST", 0.0, float,
                                  cache=False)
        if spec is None:
            spec = get_env("MXNET_TENANTS", "", str, cache=False)
        for cfg in parse_tenants(spec):
            self.register(**cfg)

    def register(self, tenant_id: str, weight: Optional[float] = None,
                 priority: int = PRIORITY_CLASSES["standard"],
                 queue_depth: Optional[int] = None,
                 page_budget: Optional[int] = None,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_window_s: Optional[float] = None,
                 breaker_reset_s: Optional[float] = None,
                 spec_k: Optional[int] = None) -> Tenant:
        """Create (or return the existing) tenant. Like the telemetry
        get-or-create contract, kwargs only apply on first creation."""
        tenant_id = str(tenant_id)
        if tenant_id == SHARED_TENANT:
            raise MXNetError(
                "tenant id %r is reserved for the prefix-cache shared-"
                "page pseudo-tenant (refcount>1 pages charged to no real "
                "tenant); pick another id" % SHARED_TENANT)
        with self._lock:
            t = self._tenants.get(tenant_id)
            if t is not None:
                return t
            bkw = {
                "failure_threshold": (breaker_threshold
                                      if breaker_threshold is not None
                                      else self._breaker_kw[
                                          "failure_threshold"]),
                "window_s": (breaker_window_s
                             if breaker_window_s is not None
                             else self._breaker_kw["window_s"]),
                "reset_timeout_s": (breaker_reset_s
                                    if breaker_reset_s is not None
                                    else self._breaker_kw[
                                        "reset_timeout_s"]),
            }
            t = Tenant(
                self, tenant_id,
                weight=self._def_weight if weight is None else weight,
                priority=priority,
                queue_depth=(self._def_depth if queue_depth is None
                             else queue_depth),
                page_budget=(self._def_pages if page_budget is None
                             else page_budget),
                rate=self._def_rate if rate is None else rate,
                burst=self._def_burst if burst is None else burst,
                breaker=TenantBreaker(self.server, tenant_id, **bkw),
                stats=TenantStats(self.server, tenant_id),
                spec_k=spec_k)
            self._tenants[tenant_id] = t
            self._order.append(tenant_id)
            return t

    def resolve(self, tenant_id: Optional[str]) -> Tenant:
        """The tenant for a submit(): ``None`` -> the default tenant;
        unknown ids auto-register with default config."""
        return self.register(DEFAULT_TENANT if tenant_id is None
                             else str(tenant_id))

    def get(self, tenant_id: str) -> Optional[Tenant]:
        with self._lock:
            return self._tenants.get(str(tenant_id))

    def tenants(self) -> List[Tenant]:
        """Snapshot list in registration order (safe to iterate while
        other threads register)."""
        with self._lock:
            return [self._tenants[tid] for tid in self._order]

    def __iter__(self):
        return iter(self.tenants())

    def __len__(self) -> int:
        with self._lock:
            return len(self._tenants)

    def snapshot(self) -> Dict[str, Dict]:
        return {t.tenant_id: t.snapshot() for t in self.tenants()}


# counter-like per-tenant snapshot fields that sum across replicas; gauges
# (queued, pages_in_use, slots_active, ...) also sum — each replica holds
# its own share of the tenant's fleet-wide footprint
_ADDITIVE_SNAPSHOT_FIELDS = (
    "submitted", "completed", "shed", "shed_breaker", "timeouts", "errors",
    "deferred_pages", "deferred_rate", "queued", "queue_depth",
    "slots_active", "pages_in_use", "pages_in_use_now", "pages_in_use_max",
    "pages_cached", "spec_proposed_tokens", "spec_accepted_tokens")


def aggregate_snapshots(snapshots: List[Dict[str, Dict]]) -> Dict[str, Dict]:
    """Merge per-replica :meth:`TenantRegistry.snapshot` dicts into one
    fleet-wide per-tenant view (``FleetRouter.stats()["tenants"]``).

    Counters and footprint gauges sum across replicas; latency
    percentiles take the worst replica's value (a fleet p99 cannot be
    recomputed from per-replica percentiles, and for an SLO read the
    conservative bound is the honest one — the ``*_count`` fields say
    how much traffic stands behind each); config fields (weight,
    priority, budgets) and breaker state come from the first replica
    that carries the tenant — every replica is built from the same spec.
    """
    out: Dict[str, Dict] = {}
    for snap in snapshots:
        for tenant_id, row in (snap or {}).items():
            agg = out.get(tenant_id)
            if agg is None:
                out[tenant_id] = dict(row)
                continue
            for key, val in row.items():
                if key in _ADDITIVE_SNAPSHOT_FIELDS \
                        or key.endswith("_count"):
                    agg[key] = agg.get(key, 0) + val
                elif key.endswith("_ms") and isinstance(val, (int, float)):
                    agg[key] = max(agg.get(key, 0.0), val)
                elif key == "breaker":
                    # surface the worst replica-local verdict: one open
                    # breaker anywhere is fleet-visible
                    order = {"closed": 0, "half_open": 1, "open": 2}
                    if order.get(val, 0) > order.get(agg.get(key), 0):
                        agg[key] = val
    return out


class WeightedFairQueue:
    """Deficit-round-robin admission over per-tenant sub-queues.

    Strict priority between classes, weighted fairness within one: each
    pop scans priority levels ascending; within a level the *turn*
    rotates over tenants with queued work, a tenant receives one quantum
    (``weight * registry.max_cost``) when its turn begins and admits
    requests while its deficit covers their cost — so over time each
    tenant's admitted cost share converges to its weight share, and a
    burst is bounded by one quantum.

    ``guard(tenant, head_request)`` is the admission veto (page budget,
    rate budget, breaker): a vetoed tenant is **deferred** — its turn
    passes without burning deficit or blocking the level, the anti-
    head-of-line property the whole design exists for. Deficit
    accumulation of a long-deferred tenant is capped at one quantum +
    one max-cost request so it cannot bank unbounded catch-up burst.

    NOT self-locking: the owning engine calls every method under its own
    condition variable (both planes already serialized submit/admission
    there).
    """

    def __init__(self, registry: TenantRegistry,
                 cost_fn: Optional[Callable] = None):
        self._reg = registry
        self._cost = cost_fn or (lambda req: 1.0)
        self._turn: Dict[int, str] = {}
        self._last: Dict[int, str] = {}
        self._n_queued = 0

    # -- intake ------------------------------------------------------------
    def push(self, tenant: Tenant, req) -> int:
        """Append to the tenant's sub-queue (the caller has already
        enforced the bound and shed); returns the tenant's new depth."""
        tenant.queue.append(req)
        self._n_queued += 1
        return len(tenant.queue)

    def total_queued(self) -> int:
        return self._n_queued

    def queued(self, tenant: Tenant) -> int:
        return len(tenant.queue)

    def oldest_submit(self) -> Optional[float]:
        """Earliest ``t_submit`` among the sub-queue heads (the batch
        window anchor). None when empty."""
        heads = [t.queue[0].t_submit for t in self._reg if t.queue]
        return min(heads) if heads else None

    # -- the DRR pick ------------------------------------------------------
    def pop(self, guard: Optional[Callable] = None):
        """The next admissible ``(tenant, request)`` by priority + DRR,
        or None when nothing is admissible right now."""
        levels = sorted({t.priority for t in self._reg if t.queue})
        for level in levels:
            got = self._pop_level(level, guard)
            if got is not None:
                self._n_queued -= 1
                return got
        return None

    def _grant(self, tenant: Tenant) -> None:
        quantum = tenant.weight * self._reg.max_cost
        tenant.deficit = min(tenant.deficit + quantum,
                             quantum + self._reg.max_cost)

    def _succ(self, ids: List[str], last: Optional[str]) -> str:
        if last in ids:
            return ids[(ids.index(last) + 1) % len(ids)]
        return ids[0]

    def _advance(self, level: int, ids: List[str],
                 by_id: Dict[str, Tenant]) -> None:
        self._last[level] = self._turn[level]
        nxt = self._succ(ids, self._last[level])
        self._turn[level] = nxt
        self._grant(by_id[nxt])

    def _pop_level(self, level: int, guard):
        row = [t for t in self._reg if t.priority == level and t.queue]
        if not row:
            return None
        ids = [t.tenant_id for t in row]
        by_id = {t.tenant_id: t for t in row}
        if self._turn.get(level) not in by_id:
            # turn-holder drained or brand new level: the turn passes to
            # the next active tenant after the last holder, with a grant
            self._turn[level] = self._succ(ids, self._last.get(level))
            self._grant(by_id[self._turn[level]])
        for _ in range(len(ids) + 1):
            t = by_id[self._turn[level]]
            req = t.queue[0]
            cost = self._cost(req)
            if t.deficit >= cost and (guard is None or guard(t, req)):
                t.queue.popleft()
                t.deficit -= cost
                if not t.queue:
                    t.deficit = 0.0  # classic DRR: drained queue banks nothing
                    self._advance(level, ids, by_id)
                return t, req
            self._advance(level, ids, by_id)
        return None

    # -- removal -----------------------------------------------------------
    def expire(self, now: float) -> List[Tuple[Tenant, object]]:
        """Remove and return every queued request whose deadline passed."""
        out: List[Tuple[Tenant, object]] = []
        for t in self._reg:
            if not t.queue:
                continue
            keep: Deque = collections.deque(maxlen=t.queue.maxlen)
            for req in t.queue:
                if req.deadline is not None and now > req.deadline:
                    out.append((t, req))
                else:
                    keep.append(req)
            t.queue = keep
        self._n_queued -= len(out)
        return out

    def drain(self, tenant: Optional[Tenant] = None
              ) -> List[Tuple[Tenant, object]]:
        """Remove and return everything queued (one tenant, or all)."""
        out: List[Tuple[Tenant, object]] = []
        for t in ([tenant] if tenant is not None else list(self._reg)):
            while t.queue:
                out.append((t, t.queue.popleft()))
        self._n_queued -= len(out)
        return out
