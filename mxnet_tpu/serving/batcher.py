"""Server: bounded queue + deadline-driven micro-batcher + robustness policy.

The coalescing loop TPU serving lives on: concurrent ``submit()`` calls
land requests in a bounded queue; a single batcher thread anchors a
micro-batch window (``MXNET_SERVING_MAX_DELAY_MS``) at the oldest queued
request, collects until the top bucket fills or the window closes, pads to
the smallest bucket that fits (:mod:`~mxnet_tpu.serving.buckets`) and hands
one fixed-shape batch to the :class:`~mxnet_tpu.serving.engine.Engine`.
Every request resolves through its own ``concurrent.futures.Future``.

Robustness policy, in the order a request meets it:

* **validation** — shape/dtype are checked in ``submit`` on the caller's
  thread; malformed input never reaches the batch;
* **load shedding** — a full queue (``MXNET_SERVING_QUEUE_DEPTH``) rejects
  at ``submit`` with :class:`QueueFullError`: under overload the server
  degrades by answering fewer requests fast, not all requests late;
* **per-request timeout** — requests whose queue wait exceeds their
  deadline (``MXNET_SERVING_TIMEOUT_MS``) fail with
  :class:`RequestTimeoutError` at batch-assembly time instead of wasting
  a bucket slot on an answer nobody is waiting for;
* **engine retry** — each engine run is the ``serving.engine`` chaos site
  and executes under the resilience retry policy: a transient fault (real
  or injected) re-runs the same padded batch against a warm jit cache
  instead of failing user requests;
* **breaker + fallback** — every engine carries a
  :class:`~mxnet_tpu.resilience.CircuitBreaker`
  (site ``serving.<name>.<role>``, role ``primary``/``fallback``); when
  the primary exhausts its retries
  the batch falls to the next engine in the chain (``fallback_engine`` —
  canonically a :class:`BlockEngine` behind a
  :class:`StableHLOEngine`), and an open breaker skips its engine
  entirely until the reset timeout admits a half-open probe;
* **engine load-shed** — with every breaker open the batch fails fast
  with :class:`EngineUnavailableError` (an explicit answer, not a hang),
  counted in ``stats()['unavailable']``;
* **error isolation** — if a non-transient error poisons a batch, the
  batcher re-runs each member alone: only the poisoned request(s) receive
  the exception, innocent bystanders still get answers. Request-caused
  failures do count toward the engine's breaker (the engine layer cannot
  tell a poisoned input from a sick engine), but any successful serve
  resets the consecutive-failure count — so isolated poison fails only
  itself, while an unbroken FLOOD of poison (``breaker_threshold``
  consecutive failures, no success in between) deliberately trips the
  breaker and sheds: at that point the traffic is the fault;
* **graceful drain** — ``close()`` stops intake, serves everything queued,
  then joins the batcher thread; ``close(drain=False)`` fails queued
  requests with :class:`ServerClosedError` immediately.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from .. import resilience, telemetry
from ..base import MXNetError, get_env, np_dtype
from ..resilience import CircuitBreaker, chaos
from ..telemetry import flightrec as _flightrec
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from .buckets import bucket_ladder, pad_to_bucket, select_bucket
from .engine import Engine
from .stats import ServingStats

__all__ = ["Server", "ServingError", "QueueFullError", "RequestTimeoutError",
           "ServerClosedError", "EngineUnavailableError"]

_DEFAULT_MAX_DELAY_MS = 2.0
_DEFAULT_QUEUE_DEPTH = 256
_DEFAULT_TIMEOUT_MS = 1000.0


class ServingError(MXNetError):
    """Base class of serving-policy failures."""


class QueueFullError(ServingError):
    """Load shed: the bounded submit queue is at capacity."""


class RequestTimeoutError(ServingError):
    """The request's deadline expired while it waited in the queue."""


class ServerClosedError(ServingError):
    """Submitted to (or still queued in) a closed server."""


class EngineUnavailableError(ServingError):
    """Every engine's circuit breaker is open: the request is shed at the
    engine layer (explicit fast failure instead of queueing work no engine
    will run)."""


class _Request:
    __slots__ = ("data", "future", "t_submit", "deadline", "tenant",
                 "trace")

    def __init__(self, data, deadline, tenant=None, trace=None):
        self.data = data
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        self.tenant = tenant
        self.trace = trace


def _tenancy():
    # deferred: tenancy imports this module for the error hierarchy, so
    # the batcher reaches back lazily (first Server construction)
    from . import tenancy
    return tenancy


class _EngineSlot:
    """One engine in the serve chain: the engine, its circuit breaker and
    the name both report under."""

    __slots__ = ("name", "engine", "breaker")

    def __init__(self, name: str, engine: Engine, breaker: CircuitBreaker):
        self.name = name
        self.engine = engine
        self.breaker = breaker


class Server:
    """Thread-safe dynamic-batching inference service over one Engine.

    Parameters mirror the ``MXNET_SERVING_*`` knobs and win over them when
    given explicitly; ``sample_shape`` is the per-request shape without the
    batch axis. Results delivered through futures are views into the
    batched output array (zero-copy); copy before mutating.

    ``name`` must be unique among live servers in the process: serving
    stats series and the per-engine breaker gauge
    (``serving.<name>.<role>``) key on it, and a second server reusing a
    name writes over the first one's series.

    ``fallback_engine`` extends the serve chain for degraded mode (the
    canonical pairing: a StableHLO artifact primary with the live
    BlockEngine behind it); each engine gets its own circuit breaker
    (``breaker_threshold`` consecutive batch failures open it,
    ``breaker_reset_s`` later a half-open probe may close it — defaults
    from ``MXNET_RESILIENCE_BREAKER_*``). ``retry_policy`` overrides the
    shared resilience policy for engine runs.
    """

    def __init__(self, engine: Engine, sample_shape: Sequence[int],
                 dtype="float32", buckets: Optional[Sequence[int]] = None,
                 max_delay_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 timeout_ms: Optional[float] = None, name: str = "serving",
                 fallback_engine: Optional[Engine] = None,
                 retry_policy: Optional["resilience.RetryPolicy"] = None,
                 breaker_threshold: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 tenants=None):
        self._engine = engine
        self._sample_shape = tuple(int(d) for d in sample_shape)
        self._dtype = np.dtype(np_dtype(dtype))
        self._ladder = bucket_ladder(buckets)
        if max_delay_ms is None:
            max_delay_ms = get_env("MXNET_SERVING_MAX_DELAY_MS",
                                   _DEFAULT_MAX_DELAY_MS, float, cache=False)
        if queue_depth is None:
            queue_depth = get_env("MXNET_SERVING_QUEUE_DEPTH",
                                  _DEFAULT_QUEUE_DEPTH, int, cache=False)
        if timeout_ms is None:
            timeout_ms = get_env("MXNET_SERVING_TIMEOUT_MS",
                                 _DEFAULT_TIMEOUT_MS, float, cache=False)
        self._max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self._queue_depth = max(1, int(queue_depth))
        self._timeout_s = float(timeout_ms) / 1e3
        self._stats = ServingStats(name)
        self._name = name
        self._retry = retry_policy
        engines = [("primary", engine)]
        if fallback_engine is not None:
            engines.append(("fallback", fallback_engine))
        self._slots = [
            _EngineSlot(role, eng, CircuitBreaker(
                "serving.%s.%s" % (name, role),
                failure_threshold=breaker_threshold,
                reset_timeout_s=breaker_reset_s))
            for role, eng in engines]
        # multi-tenant control plane (docs/serving.md §tenancy): the
        # same weighted-fair sub-queue machinery as the decode engine,
        # costed per REQUEST (batch rows are fungible — no page budgets
        # here, weights apportion batch-slot share)
        ten = _tenancy()
        if isinstance(tenants, ten.TenantRegistry):
            self._tenants = tenants
        else:
            self._tenants = ten.TenantRegistry(
                server=name, spec=tenants, max_cost=1.0,
                default_queue_depth=self._queue_depth)
        self._wfq = ten.WeightedFairQueue(self._tenants)
        # burn-ratio denominator for the SLO engine's QueueDepthBurn
        _slo.note_bound("queue_depth", name, self._queue_depth)
        self._warm_compiles: Optional[int] = None
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="mxnet-serving-" + name)
        self._thread.start()

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------
    def submit(self, x, timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one request; returns its Future. Thread-safe.

        ``timeout_ms`` overrides the server default for this request;
        ``<= 0`` disables the deadline. ``tenant`` names the submitting
        tenant (:mod:`~mxnet_tpu.serving.tenancy`; untagged callers ride
        ``default``). Raises :class:`ServerClosedError` /
        :class:`QueueFullError` / :class:`TenantUnavailableError`
        synchronously — shed work costs the caller one host array copy,
        never a device cycle.
        """
        arr = np.asarray(x, dtype=self._dtype)
        if arr.shape != self._sample_shape:
            raise MXNetError(
                "serving request shape %s != sample_shape %s"
                % (arr.shape, self._sample_shape))
        tobj = self._tenants.resolve(tenant)
        # trace minted at submit() (MXNET_TRACE_SAMPLE-gated) — the
        # batch plane's hops: enqueue, batch, complete/timeout/shed
        trace = _tracing.start_trace("batch", self._name, tobj.tenant_id)
        _tracing.event(trace, "submit")
        state = tobj.breaker.state
        if state == "open":
            # per-tenant shed: this tenant's poisoned/failing traffic is
            # refused at the door while every other tenant keeps serving
            tobj.stats.on_shed(breaker=True)
            _tracing.finish(trace, "shed", reason="tenant_breaker")
            raise _tenancy().TenantUnavailableError(tobj.tenant_id, state)
        timeout_s = (self._timeout_s if timeout_ms is None
                     else float(timeout_ms) / 1e3)
        deadline = (None if timeout_s <= 0
                    else time.perf_counter() + timeout_s)
        req = _Request(arr, deadline, tobj, trace)
        shed = None
        depth = 0
        with self._cv:
            if self._closed:
                raise ServerClosedError("submit() on a closed Server")
            if len(tobj.queue) >= tobj.queue_depth:
                shed = "tenant %r queue full (depth %d): request shed " \
                       "before the global queue" \
                       % (tobj.tenant_id, tobj.queue_depth)
            elif self._wfq.total_queued() >= self._queue_depth:
                shed = "serving queue full (depth %d): request shed" \
                       % self._queue_depth
            else:
                depth = self._wfq.push(tobj, req)
                gdepth = self._wfq.total_queued()
                self._cv.notify_all()
        if shed:
            self._stats.on_shed()
            tobj.stats.on_shed()
            _tracing.finish(trace, "shed", reason="queue_full")
            raise QueueFullError(shed)
        _tracing.event(trace, "enqueue", tenant_depth=depth,
                       queue_depth=gdepth)
        self._stats.on_submit(gdepth)
        tobj.stats.on_submit(depth)
        return req.future

    def refresh_params(self) -> int:
        """Live weight swap for the batch plane: re-snapshot the current
        parameter values of every engine in the chain that supports it
        (:meth:`BlockEngine.refresh_params`). The swap lands between
        batch executions — in-flight batches finish on the old weights,
        queued requests serve on the new ones, nothing is dropped.
        Returns the number of engines refreshed."""
        n = 0
        for slot in self._slots:
            fn = getattr(slot.engine, "refresh_params", None)
            if fn is not None:
                fn()
                n += 1
        return n

    def warmup(self) -> int:
        """Run one dummy batch per bucket so every rung's executable is
        compiled before traffic arrives — on EVERY engine in the chain, so
        a breaker trip degrades onto a warm fallback instead of paying its
        compiles under duress; returns the primary engine compile count.
        After warmup, a steady-state serve performs zero compiles."""
        for slot in self._slots:
            for b in self._ladder:
                slot.engine.run(np.zeros((b,) + self._sample_shape,
                                         self._dtype))
        count = self._engine.compile_count
        # anchor for the steady-state-recompile gauge: any compile the
        # engine does past this point violates the compile-once promise.
        # No gauge when the engine can't count compiles (-1) — a constant
        # 0 that was never measured would defeat the alert it feeds.
        self._warm_compiles = count if count >= 0 else None
        if self._warm_compiles is not None:
            telemetry.set_steady_state_recompiles("serving." + self._name, 0)
        return count

    def stats(self) -> dict:
        """Snapshot of serving metrics (see ``ServingStats.snapshot``),
        plus the engine's ``compile_count``, the bucket ladder, and — once
        :meth:`warmup` has run — ``steady_state_recompiles`` (compiles
        since warmup; the bucket ladder exists so this stays 0, and the
        ``mxnet_steady_state_recompiles`` gauge lets a scraper alert on
        it)."""
        out = self._stats.snapshot()
        count = self._engine.compile_count
        out["compile_count"] = count
        out["buckets"] = list(self._ladder)
        out["breakers"] = {slot.name: slot.breaker.state
                           for slot in self._slots}
        out["tenants"] = self._tenants.snapshot()
        out["alerts"] = _slo.evaluate()
        if self._warm_compiles is not None and count >= 0:
            steady = count - self._warm_compiles
            out["steady_state_recompiles"] = steady
            telemetry.set_steady_state_recompiles(
                "serving." + self._name, steady)
        return out

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop intake; by default serve everything already queued, then
        stop the batcher thread. ``drain=False`` fails queued requests
        with :class:`ServerClosedError` instead. ``timeout`` bounds the
        thread join (seconds; ``None`` waits for the full drain) — the
        batcher is a daemon thread, so a bounded close abandons a wedged
        in-flight batch rather than hanging the caller. Idempotent."""
        with self._cv:
            self._closed = True
            dropped: List[_Request] = []
            if not drain:
                dropped = [req for _t, req in self._wfq.drain()]
            self._cv.notify_all()
        for req in dropped:
            self._fail(req, ServerClosedError("server closed before serve"))
        if self._thread is not threading.current_thread():
            self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def tenants(self):
        """The server's tenant registry
        (:class:`~mxnet_tpu.serving.tenancy.TenantRegistry`)."""
        return self._tenants

    # ------------------------------------------------------------------
    # batcher thread
    # ------------------------------------------------------------------
    def _tenant_guard(self, tenant, req) -> bool:
        """Admission veto for the weighted-fair pick: a tenant whose
        breaker refuses is deferred (its queued work sheds in
        :meth:`_shed_tenant_breakers`), everyone else fills the batch.
        The non-consuming state check runs first; ``allow()`` (which may
        consume the half-open probe) only when the pop will happen."""
        if tenant.breaker.state == "open":
            _tracing.event(req.trace, "defer", reason="breaker")
            return False
        if not tenant.breaker.allow():
            _tracing.event(req.trace, "defer", reason="breaker")
            return False
        return True

    def _shed_tenant_breakers(self):
        """Queued work of tenants whose breaker is OPEN is answered now
        with :class:`TenantUnavailableError` — that tenant alone."""
        dropped = []
        for tenant in self._tenants:
            if tenant.queue and tenant.breaker.state == "open":
                with self._cv:
                    dropped.extend(self._wfq.drain(tenant))
        exc_cls = _tenancy().TenantUnavailableError
        for tenant, req in dropped:
            tenant.stats.on_shed(breaker=True)
            _tracing.finish(req.trace, "shed", reason="tenant_breaker")
            self._fail(req, exc_cls(tenant.tenant_id, "open"))

    def _worker(self):
        top = self._ladder[-1]
        while True:
            self._shed_tenant_breakers()
            batch: List[_Request] = []
            expired: List[_Request] = []
            with self._cv:
                while not self._wfq.total_queued() and not self._closed:
                    self._cv.wait()
                if not self._wfq.total_queued():  # closed and drained
                    return
                # window anchored at the oldest queued request: no
                # request waits on coalescing longer than max_delay,
                # regardless of how traffic trickles in behind it
                oldest = self._wfq.oldest_submit()
                window_end = oldest + self._max_delay_s
                while self._wfq.total_queued() < top and not self._closed:
                    remaining = window_end - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                now = time.perf_counter()
                # weighted-fair batch fill: rows picked by priority class
                # + deficit round robin, not arrival order — a hot
                # tenant's backlog cannot monopolize the bucket
                while len(batch) < top:
                    picked = self._wfq.pop(self._tenant_guard)
                    if picked is None:
                        break
                    tenant, req = picked
                    tenant.stats.set_depth(len(tenant.queue))
                    if req.deadline is not None and now > req.deadline:
                        expired.append(req)
                    else:
                        batch.append(req)
                depth = self._wfq.total_queued()
            for req in expired:
                self._stats.on_timeout()
                if req.tenant is not None:
                    req.tenant.stats.on_timeout()
                _tracing.finish(req.trace, "timeout", where="queued")
                self._fail(req, RequestTimeoutError(
                    "request spent > its deadline queued"))
            if not batch:
                if not expired:
                    # queued work exists but every tenant is deferred
                    # (half-open probes in flight): yield, don't spin
                    time.sleep(0.001)
                continue
            try:
                bucket = select_bucket(len(batch), self._ladder)
                padded = pad_to_bucket([r.data for r in batch], bucket,
                                       self._dtype)
                for req in batch:
                    _tracing.event(req.trace, "batch", bucket=bucket,
                                   real_rows=len(batch),
                                   queue_wait_ms=round(
                                       (now - req.t_submit) * 1e3, 3))
                self._stats.on_batch(len(batch), bucket, depth)
                self._run_batch(batch, padded)
            except Exception as exc:  # noqa: BLE001 - batcher must survive
                # e.g. a custom engine returning malformed output: fail the
                # batch's futures instead of killing the batcher thread and
                # hanging every later request
                self._stats.on_error()
                for req in batch:
                    self._fail(req, exc)

    def _engine_run(self, padded: np.ndarray):
        """One padded batch through the engine chain.

        Each admitted engine runs under the retry policy at chaos site
        ``serving.engine``; an engine that still fails reports to its
        breaker and the batch degrades to the next slot. With no slot
        admitted (every breaker open) the batch is shed with
        :class:`EngineUnavailableError` — serving answers *something* for
        every request, it never wedges on a dead engine.
        """
        # explicit retry_policy wins; otherwise look the shared default up
        # per batch so reset_default_policy()/changed knobs reach a live
        # server (default_policy() is a cached read — no per-batch cost)
        policy = self._retry or resilience.default_policy()
        last_exc: Optional[BaseException] = None
        for slot in self._slots:
            if not slot.breaker.allow():
                continue

            def attempt(engine=slot.engine):
                chaos.maybe_fail("serving.engine")
                return engine.run(padded)

            try:
                out = policy.call(attempt, site="serving.engine")
            except Exception as exc:  # noqa: BLE001 - degrade, don't die
                slot.breaker.on_failure()
                self._stats.on_engine_failure(slot.name)
                _flightrec.record("serving.engine_failure",
                                  server=self._name, engine=slot.name,
                                  error=repr(exc))
                last_exc = exc
                continue
            slot.breaker.on_success()
            if slot is not self._slots[0]:
                # a fallback serve is a fleet-health event (degraded
                # mode), not just a counter — the black box keeps it
                self._stats.on_fallback(slot.name)
                _flightrec.record("serving.fallback", server=self._name,
                                  engine=slot.name)
            return out
        if last_exc is not None:
            raise last_exc
        raise EngineUnavailableError(
            "every engine breaker is open (%s): request shed"
            % {s.name: s.breaker.state for s in self._slots})

    def _run_batch(self, reqs: List[_Request], padded: np.ndarray):
        try:
            out = self._engine_run(padded)
        except EngineUnavailableError as exc:
            # engine-layer load shed: per-request reruns would ask the same
            # open breakers again — answer every future explicitly now
            self._stats.on_unavailable(len(reqs))
            for req in reqs:
                if req.tenant is not None:
                    req.tenant.stats.on_shed()
                _tracing.finish(req.trace, "shed",
                                reason="engine_unavailable")
                self._fail(req, exc)
            return
        except Exception as exc:  # noqa: BLE001 - isolation boundary
            if len(reqs) == 1:
                self._stats.on_error()
                req = reqs[0]
                if req.tenant is not None:
                    # a solo failure is THIS request's fault (the
                    # isolation rerun already exonerated the batch):
                    # feed the tenant's breaker, so a flood of one
                    # tenant's poison sheds that tenant alone
                    req.tenant.on_request_failure()
                self._fail(req, exc)
                return
            # error isolation: the batch is poisoned by (at least) one
            # member — rerun each alone in the bottom bucket so only the
            # guilty request(s) observe the failure
            self._stats.on_isolation_retry()
            bottom = self._ladder[0]
            for req in reqs:
                # each rerun is a real device execution: record it so
                # batches/bucket_counts/batch_fill track what actually ran
                self._stats.on_batch(1, bottom, None)
                self._run_batch([req], pad_to_bucket([req.data], bottom,
                                                     self._dtype))
            return
        self._deliver(reqs, out)

    def _deliver(self, reqs: List[_Request], out):
        multi = isinstance(out, tuple)
        done = time.perf_counter()
        for i, req in enumerate(reqs):
            result = tuple(o[i] for o in out) if multi else out[i]
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(result)
                lat = (done - req.t_submit) * 1e3
                _tracing.finish(req.trace, "complete",
                                latency_ms=round(lat, 3))
                self._stats.on_complete(lat)
                if req.tenant is not None:
                    req.tenant.stats.on_complete(lat)
                    req.tenant.breaker.on_success()

    @staticmethod
    def _fail(req: _Request, exc: BaseException):
        # generic terminal fallback (specific verdicts finished earlier)
        _tracing.finish(req.trace, "error", error=type(exc).__name__)
        if req.future.done():  # already resolved (only the batcher resolves)
            return
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)
