"""Engine: what executes one padded batch. The batcher doesn't care.

An :class:`Engine` maps a host ``(bucket, *sample_shape)`` numpy batch to
host numpy outputs — the whole device round-trip (transfer in, XLA run,
one batched device->host copy out) lives behind ``run``. Two production
backends ship here:

* :class:`BlockEngine` — a live initialized Gluon block. The engine owns
  its own ``jax.jit`` wrapper (parameters close over as constants), so the
  jit cache is private and countable: ``compile_count`` is the number of
  distinct batch shapes compiled, the metric the compile-once guarantee is
  asserted against.
* :class:`StableHLOEngine` — a loaded ``aot.export_model`` artifact
  (``model.stablehlo``). Artifacts exported with ``poly_batch=True`` carry
  a symbolic batch dimension and serve the whole bucket ladder from one
  serialization; the jit wrapper re-specializes (once) per bucket.

Tests implement throwaway subclasses (slow/poisoned engines) to drive the
batcher's failure paths — anything with ``run`` + ``compile_count`` serves.
"""
from __future__ import annotations

from typing import Tuple, Union

import numpy as np

__all__ = ["Engine", "BlockEngine", "StableHLOEngine"]

BatchOut = Union[np.ndarray, Tuple[np.ndarray, ...]]


class Engine:
    """Interface: run one fixed-shape batch, report compile activity."""

    #: Short identifier used in breaker sites and engine-event telemetry
    #: (``mxnet_breaker_state{site="serving.<server>.<kind>"}``); concrete
    #: engines override it so a tripped breaker names what tripped.
    kind = "engine"

    def run(self, batch: np.ndarray) -> BatchOut:
        """Execute one padded batch; return host output(s) whose leading
        axis aligns with the input batch axis."""
        raise NotImplementedError

    @property
    def compile_count(self) -> int:
        """Distinct shapes compiled so far; -1 when the backend can't tell.
        A steady-state serve must leave this unchanged."""
        return -1


def _host(out) -> BatchOut:
    """One batched device->host copy for however many outputs there are."""
    from ..base import fetch_host

    if isinstance(out, (list, tuple)):
        return tuple(fetch_host(list(out)))
    return fetch_host([out])[0]


def _cache_size(jitted) -> int:
    from ..telemetry import jit_cache_size

    return jit_cache_size(jitted)


def _donate_batch_argnums(argnum: int):
    """Donate the padded input batch where safe: the engine materializes a
    fresh device array per request batch (``jnp.asarray`` of host data), so
    the buffer is dead after the serve — donation lets XLA reuse it as
    scratch. Parameters are NEVER donated (one shared device copy serves
    every bucket rung). Only on backends whose PJRT implements donation."""
    from .. import fastpath

    if fastpath.donation_argnums_ok():
        return (argnum,)
    return ()


class BlockEngine(Engine):
    """Serve a live (initialized, materialized) Gluon block.

    For a :class:`HybridBlock` the forward is the block's functional form
    (``_base_fn``): the parameter pytree enters every rung's executable as
    a *traced operand*, so all buckets share ONE set of device parameter
    buffers instead of each executable baking its own constant copy of the
    weights (a 4-rung ladder over a 45 MB net would otherwise hold 4
    copies in HBM). Plain Blocks have no functional form; their forward
    closes over the parameters, which bake in as XLA constants per rung.

    Either way the values are snapshot at construction — frozen-weights
    deployment semantics, matching ``aot.export_model``; call
    :meth:`refresh_params` after retraining to re-snapshot.
    """

    kind = "block"

    def __init__(self, block, dtype="float32"):
        import jax
        import jax.numpy as jnp

        from .. import _global
        from ..base import np_dtype
        from ..ndarray.ndarray import NDArray

        self._block = block
        self._dtype = np_dtype(dtype)
        self._jnp = jnp
        self._global = _global
        self._functional = hasattr(block, "_base_fn")
        if self._functional:
            base_fn = block._base_fn([0], train=False)

            def fwd(pvals, x, rng):
                outs, _aux = base_fn(pvals, rng, x)  # aux (BN stats)
                return outs                          # dropped: inference

            self._fwd = fwd
        else:
            def fwd_const(x):
                out = block(NDArray(x, None))
                if isinstance(out, (list, tuple)):
                    return tuple(o._data for o in out)
                return out._data

            self._fwd = fwd_const
        self._donate_argnum = 1 if self._functional else 0
        self._jits = {}
        self._active_fn()
        self._pvals = None
        self.refresh_params()

    def refresh_params(self):
        """Re-snapshot the block's current parameter values (the block
        must be initialized with materialized shapes). On the functional
        path compiled executables are kept — only the buffers swap; the
        constant-closure path re-jits (warm shapes recompile once)."""
        if self._functional:
            params = self._block.collect_params()
            self._pvals = {n: p.data()._data for n, p in params.items()}
        else:
            self._jits = {}
            self._active_fn()

    def _active_fn(self):
        """The jit variant for the CURRENT donation mode.
        ``MXNET_FASTPATH_DONATE`` is a live knob (docs/env_var.md), but
        ``donate_argnums`` bakes into a jit — so the mode is re-read per
        run and each mode's executable is built once on demand. Flipping
        the knob on a live server costs at most one recompile per shape."""
        import jax

        donate = _donate_batch_argnums(self._donate_argnum)
        key = bool(donate)
        fn = self._jits.get(key)
        if fn is None:
            fn = jax.jit(self._fwd, donate_argnums=donate)
            self._jits[key] = fn
        self._fn = fn  # compile_count tracks the active variant
        return fn, key

    def run(self, batch: np.ndarray) -> BatchOut:
        from .. import telemetry

        fn, donating = self._active_fn()
        x = self._jnp.asarray(batch, self._dtype)
        if donating and x is batch:
            # asarray was a no-copy alias (caller passed a device array of
            # the engine dtype): donating it would consume CALLER-owned
            # memory — donate a private copy instead
            x = self._jnp.array(x, copy=True)
        if self._functional:
            return _host(telemetry.jit_call("serving.block_engine", fn,
                                            self._pvals, x,
                                            self._global.next_key()))
        return _host(telemetry.jit_call("serving.block_engine", fn, x))

    @property
    def compile_count(self) -> int:
        # sum over ALL donation-mode variants: flipping the live
        # MXNET_FASTPATH_DONATE knob builds a fresh jit, and a count that
        # reset with it would drive Server's steady-state-recompile gauge
        # negative and hide real recompiles below zero
        counts = [_cache_size(fn) for fn in self._jits.values()]
        if not counts or any(c < 0 for c in counts):
            return -1
        return sum(counts)


class StableHLOEngine(Engine):
    """Serve a deserialized ``model.stablehlo`` artifact (``aot`` format).

    ``exported.call`` re-traces on every invocation; wrapping it in
    ``jax.jit`` here makes each concrete batch shape lower exactly once,
    so bucketed traffic against a ``poly_batch`` export is compile-once
    with the same countable cache as :class:`BlockEngine`.
    """

    kind = "stablehlo"

    def __init__(self, out_dir: str):
        import jax

        from .. import aot

        self._exported = aot.load_stablehlo(out_dir)
        self._fn = jax.jit(self._exported.call)

    def run(self, batch: np.ndarray) -> BatchOut:
        from .. import telemetry

        return _host(telemetry.jit_call("serving.stablehlo_engine",
                                        self._fn, batch))

    @property
    def compile_count(self) -> int:
        return _cache_size(self._fn)
