"""mxnet_tpu.serving — dynamic-batching in-process inference service.

The layer between "a jitted forward" and "traffic" (ROADMAP north star:
serve heavy traffic from millions of users). TPU serving economics invert
the eager story: throughput comes from coalescing many small concurrent
requests into a few fixed-shape batched XLA executions, so every serve
hits a warm jit cache entry and the steady state never recompiles.

Pieces
------
* :mod:`~mxnet_tpu.serving.buckets`  — the fixed batch-size ladder
  (default ``1/4/16/32``) and zero-padding up to the next bucket;
* :mod:`~mxnet_tpu.serving.engine`   — the ``Engine`` interface hiding
  *what* executes a batch: a live Gluon block (:class:`BlockEngine`) or a
  loaded ``aot`` StableHLO artifact (:class:`StableHLOEngine`);
* :mod:`~mxnet_tpu.serving.batcher`  — :class:`Server`: bounded submit
  queue, deadline-driven micro-batcher, load shedding, per-request
  timeout, error isolation, graceful drain — plus engine-level
  resilience (retry under the ``mxnet_tpu.resilience`` policy, a circuit
  breaker per engine, AOT→Block fallback, engine load-shed);
* :mod:`~mxnet_tpu.serving.stats`    — counters + latency reservoir
  behind ``Server.stats()``, bridged to ``profiler`` Counters/Markers;
* :mod:`~mxnet_tpu.serving.kvcache`  — paged KV cache for autoregressive
  decode: static device pools, host free-list allocator, per-sequence
  page tables;
* :mod:`~mxnet_tpu.serving.decode`   — :class:`DecodeEngine`: token-level
  continuous batching over fixed decode slots, one jitted step per tick,
  prefill through a bucket ladder, ragged paged-attention reads
  (:mod:`mxnet_tpu.ops.pallas_kernels`) — the LLM serving plane;
* :mod:`~mxnet_tpu.serving.fleet`    — :class:`FleetRouter`: N decode
  replicas behind the single-engine surface — prefix-affinity placement,
  tenant-aware spillover, replica lifecycle (rolling swap, drain),
  failure containment with exactly-once re-routing, and SLO-driven
  autoscaling;
* :mod:`~mxnet_tpu.serving.speculative` — draft proposers for
  speculative decoding: the model-free prompt-lookup (n-gram) draft and
  a pluggable registry (``MXNET_DECODE_SPEC_DRAFT``); the engine
  verifies k+1 positions per slot in ONE widened ragged tick, greedy
  rejection keeps output bit-exact, and the static K+1 width keeps the
  steady state recompile-free;
* :mod:`~mxnet_tpu.serving.tenancy`  — the multi-tenant control plane
  both servers thread through: tenant registry (``MXNET_TENANTS``),
  weighted-fair queueing with priority classes, per-tenant circuit
  breakers / KV page quotas / token-rate budgets, and the live weight
  swap (:meth:`DecodeEngine.swap_params` /
  :meth:`Server.refresh_params`).

Typical use::

    from mxnet_tpu import serving
    srv = serving.serve_block(net, sample_shape=(3, 224, 224))
    srv.warmup()                      # compile every bucket up front
    fut = srv.submit(image)           # thread-safe, from any thread
    probs = fut.result(timeout=1.0)
    print(srv.stats())                # p50/p99, batch fill, shed, ...
    srv.close()                       # graceful drain

Every ``MXNET_SERVING_*`` knob flows through ``base.get_env``
(``cache=False`` — servers are constructed long after import); the
registry lives in ``docs/env_var.md`` and ``docs/serving.md``.
"""
from __future__ import annotations

from .batcher import (EngineUnavailableError, QueueFullError,
                      RequestTimeoutError, Server, ServerClosedError,
                      ServingError)
from .buckets import bucket_ladder, pad_to_bucket, select_bucket
from .decode import DecodeEngine, PagedDecodeModel, TinyDecoder
from .engine import BlockEngine, Engine, StableHLOEngine
from .fleet import FleetRouter
from .kvcache import OutOfPagesError, PagedKVCache, PrefixMatch
from .speculative import (DraftProposer, ModelDraft, PromptLookupDraft,
                          available_drafts, make_draft, register_draft)
from .stats import ServingStats, TenantStats
from .tenancy import (PRIORITY_CLASSES, Tenant, TenantBreaker,
                      TenantRegistry, TenantUnavailableError,
                      WeightedFairQueue)

__all__ = [
    "Engine", "BlockEngine", "StableHLOEngine",
    "Server", "ServingError", "QueueFullError", "RequestTimeoutError",
    "ServerClosedError", "EngineUnavailableError",
    "ServingStats", "TenantStats",
    "bucket_ladder", "select_bucket", "pad_to_bucket",
    "serve_block", "serve_stablehlo",
    "DecodeEngine", "PagedDecodeModel", "TinyDecoder", "FleetRouter",
    "PagedKVCache", "OutOfPagesError", "PrefixMatch",
    "DraftProposer", "PromptLookupDraft", "ModelDraft",
    "register_draft", "make_draft", "available_drafts",
    "Tenant", "TenantRegistry", "TenantBreaker",
    "TenantUnavailableError", "WeightedFairQueue", "PRIORITY_CLASSES",
]


def serve_block(block, sample_shape, dtype="float32", **kwargs) -> Server:
    """Serve a live (initialized) Gluon block.

    ``sample_shape`` is the per-request shape *without* the batch axis —
    the server stacks requests along a new leading axis before running
    the block, so a block exported for ``(batch, *sample_shape)`` inputs
    serves unchanged.
    """
    return Server(BlockEngine(block, dtype=dtype), sample_shape,
                  dtype=dtype, **kwargs)


def serve_stablehlo(out_dir: str, fallback_block=None, **kwargs) -> Server:
    """Serve a loaded ``aot.export_model`` artifact.

    Reads ``manifest.json`` for the sample shape/dtype. Artifacts exported
    with ``poly_batch=True`` serve every bucket from one serialization;
    fixed-shape artifacts serve only the bucket equal to their exported
    batch size (pass ``buckets=[that_size]``).

    ``fallback_block`` (a live initialized Gluon block) arms degraded
    mode: if the artifact engine's circuit breaker trips, traffic falls
    to a :class:`BlockEngine` over that block — the AOT→Block fallback
    chain — before the server load-sheds.
    """
    import json
    import os

    with open(os.path.join(out_dir, "manifest.json")) as f:
        manifest = json.load(f)
    sample_shape = tuple(manifest["input_shape"][1:])
    dtype = manifest.get("input_dtype", "float32")
    if not manifest.get("poly_batch") and kwargs.get("buckets") is None:
        # a fixed-shape artifact runs exactly one batch size: serve it as
        # the single bucket instead of failing every other rung
        kwargs["buckets"] = [int(manifest["input_shape"][0])]
    if fallback_block is not None and kwargs.get("fallback_engine") is None:
        kwargs["fallback_engine"] = BlockEngine(fallback_block, dtype=dtype)
    return Server(StableHLOEngine(out_dir), sample_shape, dtype=dtype,
                  **kwargs)
