"""Speculative decoding drafts: propose k tokens, verify in one tick.

The decode plane's per-token cost is one ragged-attention dispatch; a
draft that guesses the next k tokens lets the engine verify all k+1
positions in ONE widened tick (:mod:`~mxnet_tpu.serving.decode`), so a
correct guess turns k+1 dispatch-bound tokens into one. Greedy rejection
keeps the output *bit-exact* against the no-cache oracle: the engine
accepts the longest draft prefix whose tokens equal the model's own
argmax at each position, plus the one "free" token the verify pass
computed anyway — by construction the committed tokens are exactly what
sequential greedy decode would have produced, whatever the draft said.

Drafts are **proposers**, not samplers: a :class:`DraftProposer` sees a
sequence's token history (prompt + generated so far) and returns up to
``k`` guessed continuation token ids. Registered by name
(:func:`register_draft` / :func:`make_draft`) so the engine knob
``MXNET_DECODE_SPEC_DRAFT`` picks one without code:

* ``prompt_lookup`` (default) — model-free n-gram lookup over the
  sequence's OWN history: find the most recent earlier occurrence of the
  current suffix and propose the tokens that followed it. Zero extra
  weights, zero extra dispatches — the draft is pure host work — and it
  wins exactly where decode output repeats its context (code edits, RAG
  quoting, templated answers, short-cycle chatter).
* ``model`` — the served model itself run greedily (dense, no cache) as
  its own draft: acceptance is ~100% by construction, which makes it the
  accept-all schedule of the test/bench plane rather than a production
  speed win (it re-pays the model per drafted token on the host). A real
  deployment would register a *smaller* decoder here; the interface —
  history in, tokens out — is the same.

A draft can be WRONG with no correctness cost (rejected rows' KV is
rolled back by simply not advancing ``seq_lens`` — masked, then
overwritten) and no shape cost (the widened step is a static ``K+1``
query block per slot; non-speculating rows pad with null positions, so
speculation changes data, never shapes).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError, get_env

__all__ = ["DraftProposer", "PromptLookupDraft", "ModelDraft",
           "register_draft", "make_draft", "available_drafts"]

_EMPTY = np.zeros((0,), np.int32)

_DEFAULT_NGRAM_MAX = 3
_DEFAULT_NGRAM_MIN = 1


class DraftProposer:
    """Contract a draft serves speculation through.

    ``propose(history, k)`` returns up to ``k`` guessed continuation
    token ids (np.int32, possibly empty) for a sequence whose tokens so
    far — prompt AND generated — are ``history``. Called on the engine
    worker thread once per speculating slot per tick: keep it host-cheap
    (the prompt-lookup draft is pure numpy). Proposals are *hints*: a
    wrong token costs one wasted verify row, never correctness.
    """

    name = "draft"

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        raise NotImplementedError


class PromptLookupDraft(DraftProposer):
    """Model-free prompt-lookup (n-gram) draft.

    Match the history's current suffix of ``ngram_max`` (falling back to
    shorter n-grams down to ``ngram_min``) against every earlier window
    of the history; on the MOST RECENT earlier occurrence, propose the
    tokens that followed it. Repetitive-suffix workloads — code, RAG
    quoting, a greedy model that has entered a cycle — resolve almost
    every tick this way; a history with no recurrence proposes nothing
    and the tick degrades to the ordinary single-token step.
    """

    name = "prompt_lookup"

    def __init__(self, ngram_max: Optional[int] = None,
                 ngram_min: Optional[int] = None):
        if ngram_max is None:
            ngram_max = get_env("MXNET_DECODE_SPEC_NGRAM",
                                _DEFAULT_NGRAM_MAX, int, cache=False)
        self.ngram_max = max(1, int(ngram_max))
        self.ngram_min = max(1, min(int(ngram_min or _DEFAULT_NGRAM_MIN),
                                    self.ngram_max))

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        h = np.asarray(history, np.int64).ravel()
        n = int(h.size)
        k = int(k)
        if k <= 0 or n < self.ngram_min + 1:
            return _EMPTY
        for g in range(min(self.ngram_max, n - 1), self.ngram_min - 1, -1):
            tail = h[n - g:]
            # windows h[i:i+g] for i in 0..n-g-1 (the window at n-g IS
            # the tail itself — excluded); one vectorized compare, then
            # the LAST match = the most recent earlier occurrence
            windows = np.lib.stride_tricks.sliding_window_view(h, g)[:-1]
            hits = np.flatnonzero((windows == tail).all(axis=1))
            if hits.size:
                i = int(hits[-1])
                cont = h[i + g:i + g + k]
                if cont.size:
                    return cont.astype(np.int32)
        return _EMPTY


class ModelDraft(DraftProposer):
    """The served model as its own draft: greedy dense decode of the
    next ``k`` tokens on the host. Acceptance is ~100% by construction
    (the verify pass computes the same argmax), so this is the
    accept-all schedule for tests/benches and the template for plugging
    a genuinely smaller draft decoder behind the same interface — NOT a
    production win with the full-size model (it re-pays the model per
    drafted token)."""

    name = "model"

    def __init__(self, model, params):
        if model is None or not hasattr(model, "reference_generate"):
            raise MXNetError(
                "ModelDraft needs a model with reference_generate() "
                "(the no-cache greedy oracle); got %r" % (model,))
        self._model = model
        self._params = params

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        if k <= 0:
            return _EMPTY
        return np.asarray(
            self._model.reference_generate(self._params, history, int(k)),
            np.int32)


# -- the registry -----------------------------------------------------------
#: name -> factory(model, params) -> DraftProposer. Model-free drafts
#: ignore the arguments; model-backed ones capture them.
_DRAFTS: Dict[str, Callable] = {
    "prompt_lookup": lambda model, params: PromptLookupDraft(),
    "model": lambda model, params: ModelDraft(model, params),
}


def register_draft(name: str, factory: Callable) -> None:
    """Register a draft variant: ``factory(model, params)`` must return
    a :class:`DraftProposer`. Re-registering a name replaces it (tests
    swap in schedule-shaped drafts this way)."""
    _DRAFTS[str(name)] = factory


def available_drafts() -> List[str]:
    return sorted(_DRAFTS)


def make_draft(name: str, model=None, params=None) -> DraftProposer:
    """Instantiate the draft registered as ``name`` (the
    ``MXNET_DECODE_SPEC_DRAFT`` values) for one engine."""
    factory = _DRAFTS.get(str(name))
    if factory is None:
        raise MXNetError(
            "unknown speculative draft %r (registered: %s)"
            % (name, ", ".join(available_drafts())))
    draft = factory(model, params)
    if not isinstance(draft, DraftProposer):
        raise MXNetError(
            "draft factory %r returned %r, not a DraftProposer"
            % (name, type(draft).__name__))
    return draft


def sanitize(proposed, k: int, vocab_size: int) -> np.ndarray:
    """Clamp a draft's proposal to the engine's contract: at most ``k``
    tokens, all valid ids — the proposal is truncated at the first
    out-of-vocab token rather than letting a buggy draft index the
    embedding out of range. Wrongness is fine; invalidity is not."""
    arr = np.asarray(proposed, np.int64).ravel()[:max(0, int(k))]
    if arr.size == 0:
        return _EMPTY
    bad = np.flatnonzero((arr < 0) | (arr >= int(vocab_size)))
    if bad.size:
        arr = arr[:int(bad[0])]
    return arr.astype(np.int32)
