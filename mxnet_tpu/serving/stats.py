"""Serving observability: counters + latency reservoir + two bridges.

Three consumers, one collector:

* ``Server.stats()`` — an O(window) synchronous snapshot (queue depth,
  batch-fill ratio, p50/p99 latency, shed/timeout/error counts) for
  benches, autoscalers and tests;
* the framework profiler — every update also feeds ``profiler.py``
  Counters (queue depth, batch fill) and Markers (shed, timeout), which
  no-op unless a profiling session is running, so a serve under
  ``profiler.set_state('run')`` drops its pressure signals straight into
  the chrome://tracing timeline next to the op/executor lanes;
* the telemetry registry — the same updates publish Prometheus-scrapable
  series (``mxnet_serving_requests_total{server=,event=}``, the
  ``mxnet_serving_latency_ms`` p50/p99 summary, queue-depth/batch-fill
  gauges, per-bucket batch counts), so a fleet dashboard reads serving
  pressure without calling into the process.

Latency is held in a bounded ring (``MXNET_SERVING_LATENCY_WINDOW``,
default 2048 most-recent requests) — percentiles over recent traffic,
O(1) memory under unbounded load.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, Optional

import numpy as np

from .. import profiler, telemetry
from ..base import get_env

__all__ = ["ServingStats", "TenantStats"]

_DEFAULT_WINDOW = 2048

# registry handles shared by every ServingStats; the `server` label keeps
# concurrent servers in one process apart
_T_REQS = telemetry.counter(
    "mxnet_serving_requests_total",
    "serving request lifecycle events",
    labels=("server", "event"))
_T_LATENCY = telemetry.histogram(
    "mxnet_serving_latency_ms",
    "end-to-end request latency (submit to result) in milliseconds",
    labels=("server",))
_T_DEPTH = telemetry.gauge(
    "mxnet_serving_queue_depth",
    "requests waiting in the submit queue",
    labels=("server",))
_T_FILL = telemetry.gauge(
    "mxnet_serving_batch_fill_pct",
    "real rows over bucket size of the most recent batch, percent",
    labels=("server",))
_T_BATCHES = telemetry.counter(
    "mxnet_serving_batches_total",
    "device batch executions per bucket rung",
    labels=("server", "bucket"))
_T_ENGINE = telemetry.counter(
    "mxnet_serving_engine_events_total",
    "engine-level resilience events (failure after retries, fallback "
    "serve, load-shed with every breaker open)",
    labels=("server", "engine", "event"))
_T_TTFT = telemetry.histogram(
    "mxnet_serving_ttft_ms",
    "time to first token: submit to the first generated token "
    "(decode plane) in milliseconds",
    labels=("server",))
_T_TPOT = telemetry.histogram(
    "mxnet_serving_tpot_ms",
    "time per output token: inter-token interval during decode in "
    "milliseconds",
    labels=("server",))
_T_CHUNKS = telemetry.counter(
    "mxnet_decode_prefill_chunks_total",
    "prefill chunks executed by the decode plane (chunked prefill "
    "interleaves these with decode ticks so TTFT stops tracking the "
    "longest prompt in the queue)",
    labels=("server",))
_T_DRAIN = telemetry.counter(
    "mxnet_serving_drain_completed_total",
    "requests finished during a graceful close(drain=True) — the number "
    "a zero-drop drain/rolling-upgrade asserts against",
    labels=("server",))
_T_SPEC_PROPOSED = telemetry.counter(
    "mxnet_spec_proposed_tokens_total",
    "draft tokens proposed by the speculative decode plane (the verify "
    "rows beyond each slot's committed token)",
    labels=("server",))
_T_SPEC_ACCEPTED = telemetry.counter(
    "mxnet_spec_accepted_tokens_total",
    "draft tokens accepted by greedy verification (committed to the "
    "sequence; proposed - accepted = wasted verify rows)",
    labels=("server",))
_T_SPEC_RATE = telemetry.gauge(
    "mxnet_spec_acceptance_rate",
    "cumulative accepted/proposed draft-token ratio; tenant='_engine' "
    "is the engine-wide row, other rows are per tenant — the signal a "
    "per-tenant spec_k knob is tuned against",
    labels=("server", "tenant"))


def _percentile_rows(out: Dict, pairs) -> None:
    """Emit ``{key}_p50_ms``/``{key}_p99_ms``/``{key}_count`` for each
    ``(key, samples)`` reservoir — the one place the percentile set and
    empty-reservoir convention live, shared by the global and per-tenant
    snapshots so the two can never diverge."""
    for key, arr in pairs:
        if arr.size:
            p50, p99 = np.percentile(arr, [50.0, 99.0])
            out[key + "_p50_ms"] = float(p50)
            out[key + "_p99_ms"] = float(p99)
        else:
            out[key + "_p50_ms"] = out[key + "_p99_ms"] = 0.0
        out[key + "_count"] = int(arr.size)


class ServingStats:
    """Thread-safe serving metrics collector for one :class:`Server`."""

    def __init__(self, name: str = "serving", window: Optional[int] = None):
        if window is None:
            window = get_env("MXNET_SERVING_LATENCY_WINDOW", _DEFAULT_WINDOW,
                             int, cache=False)
        self._lock = threading.Lock()
        self._lat_ms = collections.deque(maxlen=max(1, int(window)))
        # decode-plane reservoirs: first-token latency (TTFT) and the
        # inter-token interval (TPOT) — the two numbers an LLM serving
        # SLO is written in. Empty (and snapshot-zero) for batch servers.
        self._ttft_ms = collections.deque(maxlen=max(1, int(window)))
        self._tpot_ms = collections.deque(maxlen=max(1, int(window)))
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.timeouts = 0
        self.errors = 0
        self.batches = 0
        self.prefill_chunks = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.padded_rows = 0
        self.served_rows = 0
        self.isolation_retries = 0
        self.fallbacks = 0
        self.unavailable = 0
        self.engine_failures: Dict[str, int] = {}
        self.bucket_counts: Dict[int, int] = {}
        self._queue_depth = 0
        self.name = name
        # profiler bridge: zero-cost unless a profiling session is live
        dom = profiler.Domain(name)
        self._c_depth = dom.new_counter("queue_depth")
        self._c_fill = dom.new_counter("batch_fill_pct")
        self._m_shed = dom.new_marker("shed")
        self._m_timeout = dom.new_marker("timeout")

    # -- producers (called by Server / batcher thread) ---------------------
    def on_submit(self, depth: int):
        with self._lock:
            self.submitted += 1
            self._queue_depth = depth
        self._c_depth.set_value(depth)
        _T_REQS.inc(server=self.name, event="submitted")
        _T_DEPTH.set(depth, server=self.name)

    def on_shed(self):
        with self._lock:
            self.shed += 1
        self._m_shed.mark()
        _T_REQS.inc(server=self.name, event="shed")

    def on_timeout(self):
        with self._lock:
            self.timeouts += 1
        self._m_timeout.mark()
        _T_REQS.inc(server=self.name, event="timeout")

    def on_batch(self, real: int, bucket: int, depth: Optional[int]):
        """Record one device execution; ``depth=None`` (isolation reruns)
        leaves the queue-depth gauge untouched."""
        with self._lock:
            self.batches += 1
            self.served_rows += real
            self.padded_rows += bucket - real
            self.bucket_counts[bucket] = self.bucket_counts.get(bucket, 0) + 1
            if depth is not None:
                self._queue_depth = depth
        if depth is not None:
            self._c_depth.set_value(depth)
            _T_DEPTH.set(depth, server=self.name)
        self._c_fill.set_value(100.0 * real / bucket)
        _T_FILL.set(100.0 * real / bucket, server=self.name)
        _T_BATCHES.inc(server=self.name, bucket=bucket)

    def on_complete(self, latency_ms: float):
        with self._lock:
            self.completed += 1
            self._lat_ms.append(latency_ms)
        _T_REQS.inc(server=self.name, event="completed")
        _T_LATENCY.observe(latency_ms, server=self.name)

    def on_first_token(self, ttft_ms: float):
        """First generated token of a sequence delivered (decode plane):
        submit-to-first-token latency."""
        with self._lock:
            self._ttft_ms.append(ttft_ms)
        _T_TTFT.observe(ttft_ms, server=self.name)

    def on_output_token(self, tpot_ms: float):
        """One subsequent output token (decode plane): interval since the
        sequence's previous token."""
        with self._lock:
            self._tpot_ms.append(tpot_ms)
        _T_TPOT.observe(tpot_ms, server=self.name)

    def on_output_tokens(self, tpot_ms_batch):
        """One decode tick's worth of output tokens (one TPOT sample per
        active slot): single lock acquisition per tick, not per token —
        this sits on the per-token hot path of the decode plane."""
        with self._lock:
            self._tpot_ms.extend(tpot_ms_batch)
        _T_TPOT.observe_many(tpot_ms_batch, server=self.name)

    def on_prefill_chunk(self):
        """One prefill chunk executed (decode plane, chunked prefill or
        a prefix-cache tail completion). Chunk rate, not token rate —
        off the per-token hot path."""
        with self._lock:
            self.prefill_chunks += 1
        _T_CHUNKS.inc(server=self.name)

    def on_spec(self, proposed: int, accepted: int):
        """One decode tick's speculative outcome, batched across slots:
        ``proposed`` draft tokens went into the verify rows, ``accepted``
        of them were committed. One lock acquisition per tick (this sits
        on the decode hot path next to :meth:`on_output_tokens`)."""
        if proposed <= 0 and accepted <= 0:
            return
        with self._lock:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            rate = (self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else 0.0)
        if proposed > 0:
            _T_SPEC_PROPOSED.inc(proposed, server=self.name)
        if accepted > 0:
            _T_SPEC_ACCEPTED.inc(accepted, server=self.name)
        _T_SPEC_RATE.set(rate, server=self.name, tenant="_engine")

    def on_error(self):
        with self._lock:
            self.errors += 1
        _T_REQS.inc(server=self.name, event="error")

    def on_drain(self, n: int):
        """``n`` requests completed between ``close(drain=True)`` and the
        worker's exit — drain_replica()/rolling upgrades assert zero
        drops against this instead of inferring them from traces."""
        if n > 0:
            _T_DRAIN.inc(n, server=self.name)

    def on_isolation_retry(self):
        with self._lock:
            self.isolation_retries += 1
        _T_REQS.inc(server=self.name, event="isolation_retry")

    def on_engine_failure(self, engine: str):
        """One engine exhausted its retries on a batch (the breaker for it
        has already been told); the batch may still be served by the next
        engine in the chain."""
        with self._lock:
            self.engine_failures[engine] = \
                self.engine_failures.get(engine, 0) + 1
        _T_ENGINE.inc(server=self.name, engine=engine, event="failure")

    def on_fallback(self, engine: str):
        """A batch was served by a non-primary engine (degraded mode)."""
        with self._lock:
            self.fallbacks += 1
        _T_ENGINE.inc(server=self.name, engine=engine, event="fallback")

    def on_unavailable(self, n_requests: int):
        """Load shed at the engine layer: every breaker open, ``n``
        requests answered with :class:`EngineUnavailableError`."""
        with self._lock:
            self.unavailable += n_requests
        _T_ENGINE.inc(n_requests, server=self.name, engine="all",
                      event="unavailable")

    # -- consumer ----------------------------------------------------------
    def snapshot(self) -> Dict:
        """Point-in-time dict of every serving metric (``Server.stats()``)."""
        with self._lock:
            lat = np.asarray(self._lat_ms)  # host floats; no device dtype
            ttft = np.asarray(self._ttft_ms)
            tpot = np.asarray(self._tpot_ms)
            out = {
                "queue_depth": self._queue_depth,
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "batches": self.batches,
                "prefill_chunks": self.prefill_chunks,
                "spec_proposed_tokens": self.spec_proposed,
                "spec_accepted_tokens": self.spec_accepted,
                "spec_acceptance_rate": (self.spec_accepted /
                                         self.spec_proposed
                                         if self.spec_proposed else 0.0),
                "isolation_retries": self.isolation_retries,
                "fallbacks": self.fallbacks,
                "unavailable": self.unavailable,
                "engine_failures": dict(self.engine_failures),
                "bucket_counts": dict(self.bucket_counts),
                "batch_fill": (self.served_rows /
                               (self.served_rows + self.padded_rows)
                               if self.served_rows else 0.0),
            }
        if lat.size:
            p50, p99 = np.percentile(lat, [50.0, 99.0])
            out["p50_ms"] = float(p50)
            out["p99_ms"] = float(p99)
            out["latency_window"] = int(lat.size)
        else:
            out["p50_ms"] = out["p99_ms"] = 0.0
            out["latency_window"] = 0
        _percentile_rows(out, (("ttft", ttft), ("tpot", tpot)))
        return out


# ---------------------------------------------------------------------------
# per-tenant rows: the multi-tenant control plane's view of the same SLOs
# ---------------------------------------------------------------------------

# the tenant-labeled variants of the ServingStats families: one row per
# (server, tenant) so a dashboard slices queue pressure, budget use and
# latency SLOs per client instead of per fleet (docs/observability.md
# defines the burn alerts over these)
_T_TEN_REQS = telemetry.counter(
    "mxnet_tenant_requests_total",
    "per-tenant request lifecycle events (submitted, completed, shed, "
    "shed_breaker, timeout, error, deferred_pages, deferred_rate)",
    labels=("server", "tenant", "event"))
_T_TEN_DEPTH = telemetry.gauge(
    "mxnet_tenant_queue_depth",
    "requests waiting in one tenant's sub-queue",
    labels=("server", "tenant"))
_T_TEN_SLOTS = telemetry.gauge(
    "mxnet_tenant_slots_active",
    "decode slots currently held by one tenant's sequences",
    labels=("server", "tenant"))
_T_TEN_PAGES = telemetry.gauge(
    "mxnet_tenant_pages_in_use",
    "KV cache pages currently reserved by one tenant's sequences",
    labels=("server", "tenant"))
_T_TEN_TTFT = telemetry.histogram(
    "mxnet_tenant_ttft_ms",
    "per-tenant time to first token in milliseconds",
    labels=("server", "tenant"))
_T_TEN_TPOT = telemetry.histogram(
    "mxnet_tenant_tpot_ms",
    "per-tenant time per output token in milliseconds",
    labels=("server", "tenant"))
_T_TEN_LATENCY = telemetry.histogram(
    "mxnet_tenant_latency_ms",
    "per-tenant end-to-end request latency in milliseconds",
    labels=("server", "tenant"))


class TenantStats:
    """Thread-safe per-tenant metrics collector (one per tenant per
    engine, owned by :class:`~mxnet_tpu.serving.tenancy.Tenant`). Same
    shape as :class:`ServingStats` but scoped to one tenant's traffic
    and published under the ``mxnet_tenant_*`` families."""

    def __init__(self, server: str, tenant: str,
                 window: Optional[int] = None):
        if window is None:
            window = get_env("MXNET_SERVING_LATENCY_WINDOW",
                             _DEFAULT_WINDOW, int, cache=False)
        self.server = server
        self.tenant = tenant
        self._lock = threading.Lock()
        self._lat_ms = collections.deque(maxlen=max(1, int(window)))
        self._ttft_ms = collections.deque(maxlen=max(1, int(window)))
        self._tpot_ms = collections.deque(maxlen=max(1, int(window)))
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.shed_breaker = 0
        self.timeouts = 0
        self.errors = 0
        self.deferred_pages = 0
        self.deferred_rate = 0
        self.deferred_pressure = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._queue_depth = 0
        self._slots = 0
        self._pages = 0
        self._max_pages = 0

    def _labels(self) -> Dict[str, str]:
        return {"server": self.server, "tenant": self.tenant}

    # -- producers ---------------------------------------------------------
    def on_submit(self, depth: int):
        with self._lock:
            self.submitted += 1
            self._queue_depth = depth
        _T_TEN_REQS.inc(event="submitted", **self._labels())
        _T_TEN_DEPTH.set(depth, **self._labels())

    def set_depth(self, depth: int):
        with self._lock:
            self._queue_depth = depth
        _T_TEN_DEPTH.set(depth, **self._labels())

    def on_shed(self, breaker: bool = False):
        with self._lock:
            if breaker:
                self.shed_breaker += 1
            else:
                self.shed += 1
        _T_TEN_REQS.inc(event="shed_breaker" if breaker else "shed",
                        **self._labels())

    def on_timeout(self):
        with self._lock:
            self.timeouts += 1
        _T_TEN_REQS.inc(event="timeout", **self._labels())

    def on_error(self):
        with self._lock:
            self.errors += 1
        _T_TEN_REQS.inc(event="error", **self._labels())

    def on_defer(self, kind: str):
        """One admission-guard deferral (``pages``, ``rate`` or
        ``pressure`` — the last is the HBM governor's orange-tier
        batch-class rung). Counts *defer events* — the admission loop
        may defer the same head request many times before it finally
        fits."""
        with self._lock:
            if kind == "pages":
                self.deferred_pages += 1
            elif kind == "pressure":
                self.deferred_pressure += 1
            else:
                self.deferred_rate += 1
        _T_TEN_REQS.inc(event="deferred_" + kind, **self._labels())

    def on_first_token(self, ttft_ms: float):
        with self._lock:
            self._ttft_ms.append(ttft_ms)
        _T_TEN_TTFT.observe(ttft_ms, **self._labels())

    def on_output_tokens(self, tpot_ms_batch):
        if not tpot_ms_batch:
            return
        with self._lock:
            self._tpot_ms.extend(tpot_ms_batch)
        _T_TEN_TPOT.observe_many(tpot_ms_batch, **self._labels())

    def on_complete(self, latency_ms: float):
        with self._lock:
            self.completed += 1
            self._lat_ms.append(latency_ms)
        _T_TEN_REQS.inc(event="completed", **self._labels())
        _T_TEN_LATENCY.observe(latency_ms, **self._labels())

    def on_spec(self, proposed: int, accepted: int):
        """This tenant's share of one tick's speculative outcome; keeps
        the per-tenant ``mxnet_spec_acceptance_rate`` row fresh so one
        slow-accepting tenant is visible (and tunable via its ``spec_k``)
        without dividing fleet-level counters."""
        if proposed <= 0 and accepted <= 0:
            return
        with self._lock:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            rate = (self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else 0.0)
        _T_SPEC_RATE.set(rate, **self._labels())

    def set_slots(self, n: int):
        with self._lock:
            self._slots = n
        _T_TEN_SLOTS.set(n, **self._labels())

    def set_pages(self, n: int):
        with self._lock:
            self._pages = n
            if n > self._max_pages:
                self._max_pages = n
        _T_TEN_PAGES.set(n, **self._labels())

    # -- consumer ----------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            lat = np.asarray(self._lat_ms)
            ttft = np.asarray(self._ttft_ms)
            tpot = np.asarray(self._tpot_ms)
            out = {
                "queue_depth": self._queue_depth,
                "slots_active": self._slots,
                "pages_in_use_now": self._pages,
                "pages_in_use_max": self._max_pages,
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "shed_breaker": self.shed_breaker,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "deferred_pages": self.deferred_pages,
                "deferred_rate": self.deferred_rate,
                "deferred_pressure": self.deferred_pressure,
                "spec_proposed_tokens": self.spec_proposed,
                "spec_accepted_tokens": self.spec_accepted,
                "spec_acceptance_rate": (self.spec_accepted /
                                         self.spec_proposed
                                         if self.spec_proposed else 0.0),
            }
        _percentile_rows(out, (("latency", lat), ("ttft", ttft),
                               ("tpot", tpot)))
        return out
