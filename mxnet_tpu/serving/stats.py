"""Serving observability: counters + latency reservoir + profiler bridge.

Two consumers, one collector:

* ``Server.stats()`` — an O(window) synchronous snapshot (queue depth,
  batch-fill ratio, p50/p99 latency, shed/timeout/error counts) for
  benches, autoscalers and tests;
* the framework profiler — every update also feeds ``profiler.py``
  Counters (queue depth, batch fill) and Markers (shed, timeout), which
  no-op unless a profiling session is running, so a serve under
  ``profiler.set_state('run')`` drops its pressure signals straight into
  the chrome://tracing timeline next to the op/executor lanes.

Latency is held in a bounded ring (``MXNET_SERVING_LATENCY_WINDOW``,
default 2048 most-recent requests) — percentiles over recent traffic,
O(1) memory under unbounded load.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, Optional

import numpy as np

from .. import profiler
from ..base import get_env

__all__ = ["ServingStats"]

_DEFAULT_WINDOW = 2048


class ServingStats:
    """Thread-safe serving metrics collector for one :class:`Server`."""

    def __init__(self, name: str = "serving", window: Optional[int] = None):
        if window is None:
            window = get_env("MXNET_SERVING_LATENCY_WINDOW", _DEFAULT_WINDOW,
                             int, cache=False)
        self._lock = threading.Lock()
        self._lat_ms = collections.deque(maxlen=max(1, int(window)))
        self.submitted = 0
        self.completed = 0
        self.shed = 0
        self.timeouts = 0
        self.errors = 0
        self.batches = 0
        self.padded_rows = 0
        self.served_rows = 0
        self.isolation_retries = 0
        self.bucket_counts: Dict[int, int] = {}
        self._queue_depth = 0
        # profiler bridge: zero-cost unless a profiling session is live
        dom = profiler.Domain(name)
        self._c_depth = dom.new_counter("queue_depth")
        self._c_fill = dom.new_counter("batch_fill_pct")
        self._m_shed = dom.new_marker("shed")
        self._m_timeout = dom.new_marker("timeout")

    # -- producers (called by Server / batcher thread) ---------------------
    def on_submit(self, depth: int):
        with self._lock:
            self.submitted += 1
            self._queue_depth = depth
        self._c_depth.set_value(depth)

    def on_shed(self):
        with self._lock:
            self.shed += 1
        self._m_shed.mark()

    def on_timeout(self):
        with self._lock:
            self.timeouts += 1
        self._m_timeout.mark()

    def on_batch(self, real: int, bucket: int, depth: Optional[int]):
        """Record one device execution; ``depth=None`` (isolation reruns)
        leaves the queue-depth gauge untouched."""
        with self._lock:
            self.batches += 1
            self.served_rows += real
            self.padded_rows += bucket - real
            self.bucket_counts[bucket] = self.bucket_counts.get(bucket, 0) + 1
            if depth is not None:
                self._queue_depth = depth
        if depth is not None:
            self._c_depth.set_value(depth)
        self._c_fill.set_value(100.0 * real / bucket)

    def on_complete(self, latency_ms: float):
        with self._lock:
            self.completed += 1
            self._lat_ms.append(latency_ms)

    def on_error(self):
        with self._lock:
            self.errors += 1

    def on_isolation_retry(self):
        with self._lock:
            self.isolation_retries += 1

    # -- consumer ----------------------------------------------------------
    def snapshot(self) -> Dict:
        """Point-in-time dict of every serving metric (``Server.stats()``)."""
        with self._lock:
            lat = np.asarray(self._lat_ms)  # host floats; no device dtype
            out = {
                "queue_depth": self._queue_depth,
                "submitted": self.submitted,
                "completed": self.completed,
                "shed": self.shed,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "batches": self.batches,
                "isolation_retries": self.isolation_retries,
                "bucket_counts": dict(self.bucket_counts),
                "batch_fill": (self.served_rows /
                               (self.served_rows + self.padded_rows)
                               if self.served_rows else 0.0),
            }
        if lat.size:
            p50, p99 = np.percentile(lat, [50.0, 99.0])
            out["p50_ms"] = float(p50)
            out["p99_ms"] = float(p99)
            out["latency_window"] = int(lat.size)
        else:
            out["p50_ms"] = out["p99_ms"] = 0.0
            out["latency_window"] = 0
        return out
