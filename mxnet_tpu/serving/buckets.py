"""Fixed batch-size buckets: the shape discipline of TPU serving.

XLA compiles one executable per input shape. A server that runs whatever
batch happens to be in the queue (3 requests, then 7, then 5, ...) compiles
a fresh HloModule for every new size — seconds of latency each, forever,
because traffic produces new sizes forever. The fix is a small ladder of
fixed batch sizes (default ``1/4/16/32``): every micro-batch is zero-padded
up to the next rung, so after one warmup pass per rung the jit cache is
complete and the steady state never compiles again.

The padded rows are real compute thrown away — the ladder is the knob that
trades that waste (worst just under 4x at the 4->16 step) against jit-cache
size. ``docs/serving.md`` has tuning guidance.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..base import MXNetError, get_env

__all__ = ["bucket_ladder", "select_bucket", "pad_to_bucket"]

_DEFAULT_BUCKETS = "1,4,16,32"


def bucket_ladder(buckets=None) -> Tuple[int, ...]:
    """Resolve and validate the batch-size ladder.

    ``buckets`` may be an explicit sequence of ints or ``None`` to read the
    ``MXNET_SERVING_BUCKETS`` knob (comma-separated, default ``1,4,16,32``).
    The ladder is returned sorted ascending; it must be non-empty, positive
    and strictly increasing after sorting.
    """
    if buckets is None:
        raw = get_env("MXNET_SERVING_BUCKETS", _DEFAULT_BUCKETS, str,
                      cache=False)
        try:
            buckets = [int(tok) for tok in str(raw).split(",") if tok.strip()]
        except ValueError:
            raise MXNetError("MXNET_SERVING_BUCKETS must be comma-separated "
                             "ints, got %r" % (raw,))
    ladder = tuple(sorted(int(b) for b in buckets))
    if not ladder or ladder[0] < 1:
        raise MXNetError("serving buckets must be positive ints, got %r"
                         % (buckets,))
    if len(set(ladder)) != len(ladder):
        raise MXNetError("serving buckets contain duplicates: %r" % (buckets,))
    return ladder


def select_bucket(n: int, ladder: Sequence[int]) -> int:
    """Smallest rung >= ``n``; the top rung when ``n`` overflows the ladder
    (the batcher then serves the top rung and leaves the rest queued)."""
    if n < 1:
        raise MXNetError("bucket selection needs n >= 1, got %d" % n)
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


def pad_to_bucket(rows: List[np.ndarray], bucket: int,
                  dtype=np.float32) -> np.ndarray:
    """Stack per-request arrays and zero-pad the batch axis up to ``bucket``.

    All rows must share one shape (the server validates at ``submit``).
    Returns a ``(bucket, *sample_shape)`` array; rows ``[len(rows):]`` are
    zeros and their outputs are dropped after the batched execution.
    """
    n = len(rows)
    if n == 0 or n > bucket:
        raise MXNetError("pad_to_bucket: %d rows into bucket %d" % (n, bucket))
    out = np.zeros((bucket,) + tuple(rows[0].shape), dtype=dtype)
    for i, row in enumerate(rows):
        out[i] = row
    return out
