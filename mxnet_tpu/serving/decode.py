"""Token-level continuous batching: the LLM decode plane.

The PR-2 :class:`~mxnet_tpu.serving.batcher.Server` batches at *request*
granularity — right for CNNs, structurally wrong for autoregressive
decode, where a finished sequence strands its batch slot until the whole
batch drains and padded KV wastes HBM. This module batches at *token*
granularity instead: a fixed number of decode **slots** each hold one live
sequence, every engine tick runs ONE jitted decode step over all slots
(one new token per active slot), and the moment a sequence finishes its
slot is re-admitted from the queue — in the same tick, without retracing,
because every array in the step is statically shaped in
``(num_slots, max_pages, page_size)`` (:mod:`~mxnet_tpu.serving.kvcache`,
Ragged Paged Attention per PAPERS.md).

Anatomy of a request:

1. **submit** — prompt validated on the caller's thread; bounded queue
   (shed with :class:`~mxnet_tpu.serving.batcher.QueueFullError`) and
   per-request deadline, exactly the PR-2 policy surface;
2. **admission** — a free slot + a full worst-case page reservation
   (prompt + ``max_new_tokens``; an admitted sequence can always finish);
3. **prefill** — the prompt runs once through a fixed ladder of padded
   lengths (:func:`~mxnet_tpu.serving.buckets.select_bucket` over
   ``MXNET_DECODE_PREFILL_BUCKETS``), writes its KV into the reserved
   pages, and produces the first output token (the TTFT mark). Prompts of
   ``MXNET_DECODE_RING_PREFILL_LEN`` tokens or more route their attention
   through :func:`mxnet_tpu.sequence_parallel.ring_attention` — the
   long-context path, sequence axis sharded over the local mesh;
4. **decode ticks** — one jitted step per tick regardless of membership
   churn: paged-attention over the page table, in-graph greedy sampling,
   and exactly TWO host<->device crossings per tick — one packed (5, S)
   operand put (tokens/positions/lengths/write slots travel together;
   the page table rides a version-keyed device cache re-put only when
   admission or completion mutates it) and ONE fetch of the sampled
   tokens (the per-token sync the ``decode-host-sync`` tpulint pass
   audits);
5. **completion** — EOS or the token budget frees the pages (LIFO reuse)
   and the freed slot admits the next queued sequence on the same tick.

Resilience (PR-4 wiring, chaos sites ``serving.decode`` /
``serving.decode.prefill`` / ``serving.decode.tenant.<id>``): prefill
runs per sequence under the retry policy, so a poisoned/unlucky prompt
fails ONLY its own future; the decode step retries transients, and a
step that still fails evicts exactly the sequences in flight (fresh
pools, slots reset) while the engine keeps answering later traffic —
all under one circuit breaker whose open state sheds with
:class:`EngineUnavailableError` instead of hanging.

Prefix caching (``MXNET_DECODE_PREFIX_CACHE``, default on): admission
walks the cache's rolling-hash prefix index and maps a matching system
prompt's pages straight into the new slot's page table — refcounted,
read-only, prefilled once per fleet instead of once per request; the
first divergent/partial page is shared copy-on-write (a jitted device
copy into a page charged to the writer), and only the non-shared tail is
reserved against the tenant's budget (shared pages belong to the
``shared`` pseudo-tenant). The tail — or, on a full hit, a one-token
recompute of the last prompt position — runs through a *chunk* jit that
attends over the sequence's pages, so a hit's prefill cost is the tail,
not the prompt. Outputs stay exactly equal to the no-cache oracle: hits
are token-verified against the stored runs, the index is flushed on
weight swaps and pool re-zeros, and CoW means no sequence ever observes
another's writes.

Chunked prefill (``MXNET_DECODE_PREFILL_CHUNK`` = chunk size, default
off): prefill splits into fixed-size chunks interleaved with decode
ticks inside the same one-jitted-step regime — one statically-shaped
chunk rung pre-compiled at :meth:`DecodeEngine.warmup`, each chunk
carrying the KV written so far through the page table — so a long
prompt stops monopolizing the tick loop and TTFT p99 stops tracking the
longest prompt in the queue.

Observability (:mod:`~mxnet_tpu.telemetry`): a sampled request
(``MXNET_TRACE_SAMPLE``) carries a trace minted at :meth:`submit`
through every hop — enqueue, admission-guard deferral verdicts,
admission, prefill chunks, prefix hits/CoW, every decode tick, the
terminal — queryable by ``trace_id``; the flight recorder keeps each
tick's in-flight request set plus evictions/swaps/faults so a mid-tick
death leaves a readable black box (the worker catch-all dumps it), and
``stats()["alerts"]`` carries the live SLO engine's verdicts.

Multi-tenancy (:mod:`~mxnet_tpu.serving.tenancy`): every request
belongs to a tenant (``submit(..., tenant=)``; untagged = ``default``).
The single FIFO is replaced by per-tenant bounded sub-queues drained by
weighted-fair deficit-round-robin, KV **page quotas** and token-rate
budgets are enforced at admission (a tenant at budget *defers* without
blocking other tenants — the FIFO's head-of-line coupling is gone), a
request-level failure feeds that tenant's own sliding-window breaker
(``mxnet_tenant_breaker_state``) so a misbehaving tenant is shed alone
while the engine breaker stays reserved for tick-level engine faults,
per-request deadlines now also cover generation (an expired sequence is
evicted at the next tick boundary, its pages freed), and
:meth:`DecodeEngine.swap_params` hot-swaps the model weights between
ticks — an A/B rollout or fleet upgrade drops zero in-flight requests
and recompiles nothing (same pytree signature = same jit signature).
"""
from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..telemetry import devprof as _devprof
from ..telemetry import flightrec as _flightrec
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from ..base import MXNetError, fetch_host, get_env
from ..resilience import CircuitBreaker, chaos
from ..resilience import hbm as _hbm
from .batcher import (EngineUnavailableError, QueueFullError,
                      RequestTimeoutError, ServerClosedError)
from .buckets import select_bucket
from .kvcache import OutOfPagesError, PagedKVCache, PrefixMatch, write_kv
from .stats import ServingStats
from .tenancy import (PRIORITY_CLASSES, SHARED_TENANT, Tenant,
                      TenantRegistry, TenantUnavailableError,
                      WeightedFairQueue)

__all__ = ["DecodeEngine", "PagedDecodeModel", "TinyDecoder"]

_DEFAULT_SLOTS = 8
_DEFAULT_MAX_SEQ_LEN = 256
_DEFAULT_PREFILL_BUCKETS = "16,64"
_DEFAULT_TIMEOUT_MS = 10000.0
_DEFAULT_QUEUE_DEPTH = 256
_DEFAULT_PREFIX_CACHE = 1  # sharing is exact by construction: default on
_DEFAULT_PREFILL_CHUNK = 0  # 0 = monolithic prefill (one rung per prompt)

_T_TOKENS = telemetry.counter(
    "mxnet_decode_tokens_total",
    "output tokens generated by the decode plane",
    labels=("server",))
_T_OCCUPANCY = telemetry.gauge(
    "mxnet_decode_slot_occupancy",
    "active decode slots over total slots, most recent tick",
    labels=("server",))
_T_EVENTS = telemetry.counter(
    "mxnet_decode_events_total",
    "decode engine lifecycle events (prefill, admitted, completed, "
    "evicted, shed_open_breaker, shed_tenant_breaker, deadline_evicted, "
    "weight_swap, cow_copy)",
    labels=("server", "event"))


def _tree_sig(tree):
    """(shape, dtype) signature of a param pytree: two pytrees with equal
    signatures produce identical jit avals, so swapping one for the other
    between ticks can never recompile the decode step."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: (tuple(getattr(x, "shape", ())),
                   str(getattr(x, "dtype", type(x).__name__))), tree)


class PagedDecodeModel:
    """Contract a model serves decode through. Pure functions over the
    paged cache — the engine jits them once and the shapes never move.

    Attributes the engine sizes the cache from: ``num_layers``,
    ``num_heads``, ``num_kv_heads``, ``head_dim``, ``vocab_size``.
    """

    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    vocab_size: int

    def decode(self, params, tokens, positions, k_pool, v_pool,
               page_tables, seq_lens, write_pages, write_offsets):
        """One query ROW per table row: ``tokens``/``positions``/
        ``write_*``/``seq_lens`` are ``(S*W,)`` where ``page_tables`` is
        ``(S, max_pages)`` — W is a static per-slot query width the model
        derives at trace time (``tokens.shape[0] // page_tables.shape[0]``).
        The classic decode tick is W=1: one token per slot. The
        speculative verify tick is W=K+1: slot s's rows sit at
        ``s*W .. s*W+W-1`` in position order (committed token, then
        draft tokens), sharing the slot's page-table row. ``seq_lens``
        is per ROW and INCLUDES the row's own token (it attends to
        itself and every position below — which covers the earlier draft
        rows, written before attention reads). Inactive/padded rows
        carry ``seq_len 0`` and the null write page; their logits are
        garbage the engine ignores. Returns
        ``(logits (S*W, vocab), k_pool, v_pool)``."""
        raise NotImplementedError

    def prefill(self, params, tokens, length, k_pool, v_pool,
                write_pages, write_offsets, attn=None):
        """Whole prompt in one pass: ``tokens`` ``(T,)`` padded to a
        ladder rung, ``length`` the real token count (traced — one
        compile per rung, not per length), ``write_*`` ``(T,)`` (padding
        rows target the null page). ``attn`` overrides the in-graph
        causal attention (the ring-attention long-context path). Returns
        ``(last_token_logits (vocab,), k_pool, v_pool)``."""
        raise NotImplementedError

    def prefill_chunk(self, params, tokens, start, length, k_pool, v_pool,
                      page_table_row, write_pages, write_offsets):
        """One prefill chunk of one sequence, attending THROUGH the page
        table: ``tokens`` ``(C,)`` padded to the chunk rung at absolute
        positions ``start .. start+C-1`` (``start``/``length`` traced
        int32 scalars — one compile per rung, not per prompt or chunk
        index), ``page_table_row`` ``(max_pages,)`` the slot's row.
        Writes the chunk's K/V at ``write_*`` ``(C,)`` (padding and
        already-cached positions target the null page), then attends
        each chunk query over the sequence's pages — the prefix written
        by earlier chunks or mapped from the prefix cache included.
        Returns ``(last_real_token_logits (vocab,), k_pool, v_pool)``.
        Both chunked prefill and the prefix-cache tail/recompute path
        run through this."""
        raise NotImplementedError


#: process-wide request ids for the flight recorder's per-tick in-flight
#: set — ALWAYS minted (unlike trace ids, which are sampled): the black
#: box must identify every sequence on the failing tick, not just the
#: sampled ones. itertools.count.__next__ is GIL-atomic — no lock.
_RID = itertools.count(1)


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "future", "t_submit",
                 "deadline", "tokens", "last_t", "slot", "tenant",
                 "match", "kv_cached", "filled", "prefilling", "seq",
                 "epoch", "rid", "trace")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 eos_id: Optional[int], deadline: Optional[float],
                 tenant: Tenant):
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        self.tokens: List[int] = []
        self.last_t = 0.0
        self.slot = -1
        self.tenant = tenant
        # prefix-cache / chunked-prefill state: the admission-time match
        # (stashed by the guard), how many prompt tokens' KV came from
        # shared pages, the next position the chunk scheduler processes,
        # whether prefill is still in flight, and the admission order
        # the chunk lane round-robins over
        self.match: Optional[PrefixMatch] = None
        self.kv_cached = 0
        self.filled = 0
        self.prefilling = False
        self.seq = 0
        self.epoch = 0  # weight-swap epoch at prefill start (stale guard)
        self.rid = next(_RID)
        # the sampled request trace (None = unsampled: every hop's
        # tracing.event() is then a single `is None` check)
        self.trace: Optional[_tracing.Trace] = None


class DecodeEngine:
    """Continuous-batching decode service over one :class:`PagedDecodeModel`.

    ``submit(prompt, max_new_tokens)`` from any thread returns a Future of
    the generated token ids (``np.int32``, EOS included when hit). One
    engine thread runs the admit/step/complete loop; sampling is greedy
    argmax in-graph.

    Construction compiles nothing — call :meth:`warmup` to pre-compile
    the decode step and every prefill rung before traffic, after which a
    steady-state serve performs zero compiles no matter how sequences
    churn (``stats()['steady_state_recompiles']``, gauge-gated like the
    PR-2 server). ``name`` keys the stats series, the breaker site and
    the kv-cache gauge; keep it unique among live engines.
    """

    def __init__(self, model: PagedDecodeModel, params,
                 num_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 queue_depth: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 ring_prefill_len: Optional[int] = None,
                 name: str = "decode", retry_policy=None,
                 breaker_threshold: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 dtype="float32", tenants=None,
                 prefix_cache: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 spec_k: Optional[int] = None,
                 spec_draft=None):
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        self._model = model
        self._params = params
        if num_slots is None:
            num_slots = get_env("MXNET_DECODE_SLOTS", _DEFAULT_SLOTS, int,
                                cache=False)
        if max_seq_len is None:
            max_seq_len = get_env("MXNET_DECODE_MAX_SEQ_LEN",
                                  _DEFAULT_MAX_SEQ_LEN, int, cache=False)
        if queue_depth is None:
            queue_depth = get_env("MXNET_SERVING_QUEUE_DEPTH",
                                  _DEFAULT_QUEUE_DEPTH, int, cache=False)
        if timeout_ms is None:
            timeout_ms = get_env("MXNET_SERVING_TIMEOUT_MS",
                                 _DEFAULT_TIMEOUT_MS, float, cache=False)
        if ring_prefill_len is None:
            ring_prefill_len = get_env("MXNET_DECODE_RING_PREFILL_LEN", 0,
                                       int, cache=False)
        if prefix_cache is None:
            prefix_cache = bool(get_env("MXNET_DECODE_PREFIX_CACHE",
                                        _DEFAULT_PREFIX_CACHE, int,
                                        cache=False))
        if prefill_chunk is None:
            prefill_chunk = get_env("MXNET_DECODE_PREFILL_CHUNK",
                                    _DEFAULT_PREFILL_CHUNK, int,
                                    cache=False)
        if spec_k is None:
            spec_k = get_env("MXNET_DECODE_SPEC_K", 0, int, cache=False)
        self.num_slots = max(1, int(num_slots))
        self.max_seq_len = int(max_seq_len)
        self._queue_depth = max(1, int(queue_depth))
        self._timeout_s = float(timeout_ms) / 1e3
        self._ring_len = max(0, int(ring_prefill_len))
        self._prefix_cache = bool(prefix_cache)
        self._chunk = max(0, min(int(prefill_chunk), self.max_seq_len))
        # speculative decoding: the step carries a STATIC width of
        # spec_k+1 query rows per slot (committed token + up to k draft
        # rows). k=0 keeps the classic 1-row tick bit-for-bit (the
        # packed operand is then (5, S) exactly as before). The width is
        # a compile-time constant — per-tick draft depth, acceptance and
        # per-tenant caps vary only the DATA inside it.
        self._spec_k = max(0, int(spec_k))
        self._spec_w = self._spec_k + 1
        if self._spec_k == 0:
            self._draft = None
        elif spec_draft is not None and not isinstance(spec_draft, str):
            self._draft = spec_draft   # a DraftProposer instance
        else:
            from .speculative import make_draft
            if spec_draft is None:
                spec_draft = get_env("MXNET_DECODE_SPEC_DRAFT",
                                     "prompt_lookup", str, cache=False)
            self._draft = make_draft(spec_draft, model, params)
        self._ladder = self._prefill_ladder(prefill_buckets)
        # the chunk jit's statically-shaped rungs: chunked prefill uses
        # ONE rung (the chunk size); with chunking off the prefix-cache
        # tail pads to the prefill ladder instead
        if self._chunk:
            self._chunk_rungs: tuple = (self._chunk,)
        elif self._prefix_cache:
            self._chunk_rungs = self._ladder
        else:
            self._chunk_rungs = ()
        self._cache = PagedKVCache(
            self.num_slots, self.max_seq_len, model.num_layers,
            model.num_kv_heads, model.head_dim, page_size=page_size,
            num_pages=num_pages, dtype=dtype, name=name,
            prefix_cache=self._prefix_cache)
        self._stats = ServingStats(name)
        self._name = name
        self._retry = retry_policy
        self._breaker = CircuitBreaker(
            "serving.%s.decode" % name, failure_threshold=breaker_threshold,
            reset_timeout_s=breaker_reset_s)
        # multi-tenant control plane: registry (tenants= is a
        # TenantRegistry, a MXNET_TENANTS-style spec string, or None =
        # the env spec), weighted-fair sub-queues costed in worst-case
        # tokens so weights apportion token throughput
        if isinstance(tenants, TenantRegistry):
            self._tenants = tenants
        else:
            self._tenants = TenantRegistry(
                server=name, spec=tenants,
                max_cost=float(self.max_seq_len),
                default_queue_depth=self._queue_depth)
        self._wfq = WeightedFairQueue(
            self._tenants,
            cost_fn=lambda r: float(int(r.prompt.size) + r.max_new))
        # the SLO engine's burn ratios divide by bounds the registry
        # cannot carry — register this engine's queue capacity
        _slo.note_bound("queue_depth", name, self._queue_depth)
        # HBM pressure governor: register this engine's worst-case byte
        # bounds and consult the degradation ladder at admission (see
        # _admit/_admit_guard). The KV pool is statically allocated, so
        # its bound is a constant; pending prefill is a callable bound —
        # every queued request may reserve up to max_seq_len of pages
        # (total_queued() reads one int, safe from any thread).
        self._governor = _hbm.governor()
        pool_bytes = int(self._cache.k_pool.nbytes
                         + self._cache.v_pool.nbytes)
        self._governor.register_bound("serving.%s.kv_pool" % name,
                                      pool_bytes)
        page_bytes = pool_bytes // max(1, self._cache.num_pages)
        worst_pages = self._cache.pages_for(self.max_seq_len)
        self._governor.register_bound(
            "serving.%s.pending_prefill" % name,
            lambda: self._wfq.total_queued() * worst_pages * page_bytes)
        #: post-OOM governed re-admission cap (admit FEWER sequences at
        #: the same static slot shapes); None = ungoverned. Worker-only.
        self._governed_limit: Optional[int] = None
        #: the tier _admit observed this pass; _admit_guard (same worker
        #: pass, under _cv) reads it for the orange batch-defer rung
        self._tick_tier = "green"
        self._params_sig = _tree_sig(params)
        self._pending_swaps: List[tuple] = []
        self._variants = {}
        self._active_variant: Optional[str] = None
        self._swaps = 0
        self._deadline_evictions = 0

        donate = self._donate_argnums()

        # the tick's five (S*W,) int32 operands (tokens, positions,
        # seq_lens, write pages, write offsets; W = spec_k+1 query rows
        # per slot, 1 when speculation is off) travel as ONE packed
        # (5, S*W) array — one host->device put per tick instead of five;
        # the page table rides a version-keyed device cache (below), so a
        # steady tick pays exactly one put + one fetch
        def _step_fn(params, packed, k_pool, v_pool, page_tables):
            tokens, positions, seq_lens, write_pages, write_offsets = packed
            logits, k_pool, v_pool = model.decode(
                params, tokens, positions, k_pool, v_pool, page_tables,
                seq_lens, write_pages, write_offsets)
            sampled = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return sampled, k_pool, v_pool

        # same packing for prefill: tokens + write pages + offsets share
        # the rung shape, so they travel as one (3, rung) array
        def _prefill_fn(params, packed, length, k_pool, v_pool):
            tokens, write_pages, write_offsets = packed
            last, k_pool, v_pool = model.prefill(
                params, tokens, length, k_pool, v_pool, write_pages,
                write_offsets)
            return jnp.argmax(last).astype(jnp.int32), k_pool, v_pool

        # one prefill CHUNK: same (3, rung) packing plus the absolute
        # start position and the slot's page-table row — the chunk
        # attends through the pages (earlier chunks' and shared prefix
        # KV included), so start/length are traced and one compile
        # serves every chunk of a rung
        def _chunk_fn(params, packed, start, length, page_row, k_pool,
                      v_pool):
            tokens, write_pages, write_offsets = packed
            last, k_pool, v_pool = model.prefill_chunk(
                params, tokens, start, length, k_pool, v_pool, page_row,
                write_pages, write_offsets)
            return jnp.argmax(last).astype(jnp.int32), k_pool, v_pool

        # the copy-on-write copy: duplicate one page's K/V (all layers)
        # into a fresh page so a sequence diverging inside a shared page
        # writes into its own copy; src/dst are traced scalars — ONE
        # compile, pre-warmed against the null page
        def _cow_fn(k_pool, v_pool, src, dst):
            k_pool = k_pool.at[:, dst].set(k_pool[:, src])
            v_pool = v_pool.at[:, dst].set(v_pool[:, src])
            return k_pool, v_pool

        # pools are donated through the jits (they are dead the moment
        # the step returns — swap_pools rebinds to the outputs), so the
        # cache costs ONE pool of HBM, not two per step
        self._step = jax.jit(_step_fn,
                             donate_argnums=(2, 3) if donate else ())
        self._prefill_jit = jax.jit(_prefill_fn, donate_argnums=donate)
        self._chunk_jit = jax.jit(
            _chunk_fn, donate_argnums=(5, 6) if donate else ())
        self._cow_jit = jax.jit(
            _cow_fn, donate_argnums=(0, 1) if donate else ())
        self._pt_dev = None  # version-keyed device page table
        self._pt_version = -1

        self._warm_compiles: Optional[int] = None
        self._slots: List[Optional[_DecodeRequest]] = \
            [None] * self.num_slots
        self._cv = threading.Condition()
        self._closed = False
        self._tokens_total = 0
        self._prefills = 0
        self._evictions = 0
        self._occ_sum = 0.0
        self._ticks = 0
        # speculation accounting (worker-confined): draft tokens
        # proposed/accepted, and the accepted-per-tick numerator/
        # denominator over SPECULATING slot-ticks only
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_new = 0         # tokens committed by speculating slots
        self._spec_slot_ticks = 0  # slot-ticks where a draft was in play
        self._cow_copies = 0   # written/read under _cv only
        self._admit_seq = 0    # admission order among prefilling slots
        self._rr_last = 0      # round-robin cursor over that order
        self._swap_epoch = 0   # worker-confined; bumps per applied swap
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="mxnet-decode-" + name)
        self._thread.start()

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    @staticmethod
    def _donate_argnums():
        from .. import fastpath

        if fastpath.donation_argnums_ok():
            return (3, 4)  # k_pool, v_pool in the prefill signature
        return ()

    def _pools_dead(self) -> bool:
        """Whether a failed jitted execution consumed the donated pools
        (TPU/GPU donation only; always False on CPU where donation is
        off). A retry must not re-pass dead buffers, and a prefill
        failure that killed the pools has destroyed EVERY live sequence's
        KV — the caller escalates to a full eviction + fresh pools."""
        dead = getattr(self._cache.k_pool, "is_deleted", None)
        return bool(dead and dead())

    def _device_page_table(self):
        """The page table's device copy, re-put only when the allocator
        mutated it (admission/free) — steady ticks with stable membership
        skip the transfer entirely."""
        ver = self._cache.version
        if self._pt_dev is None or self._pt_version != ver:
            self._pt_dev = self._jnp.asarray(self._cache.page_table)
            self._pt_version = ver
        return self._pt_dev

    def _prefill_ladder(self, buckets):
        if buckets is None:
            raw = get_env("MXNET_DECODE_PREFILL_BUCKETS",
                          _DEFAULT_PREFILL_BUCKETS, str, cache=False)
            try:
                buckets = [int(t) for t in str(raw).split(",") if t.strip()]
            except ValueError:
                raise MXNetError("MXNET_DECODE_PREFILL_BUCKETS must be "
                                 "comma-separated ints, got %r" % (raw,))
        ladder = sorted({int(b) for b in buckets if int(b) > 0})
        if not ladder:
            raise MXNetError("empty prefill bucket ladder")
        # the top rung must cover every admissible prompt: cap the ladder
        # with max_seq_len so select_bucket never under-sizes a pad
        ladder = [b for b in ladder if b < self.max_seq_len]
        ladder.append(self.max_seq_len)
        return tuple(ladder)

    # ------------------------------------------------------------------
    # client side
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue one sequence; returns a Future resolving to the
        generated ``np.int32`` token ids. Thread-safe. ``timeout_ms``
        bounds the WHOLE request — queue wait and generation: a sequence
        whose deadline expires mid-decode is evicted at the next tick
        boundary (pages freed, future fails with
        :class:`RequestTimeoutError`); ``<= 0`` disables. ``tenant``
        names the submitting tenant (:mod:`~mxnet_tpu.serving.tenancy`);
        untagged callers ride the ``default`` tenant."""
        arr = np.asarray(prompt, np.int32).ravel()
        if arr.size < 1:
            raise MXNetError("decode submit needs >= 1 prompt token")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if arr.size + max_new > self.max_seq_len:
            raise MXNetError(
                "prompt %d + max_new %d exceeds max_seq_len %d"
                % (arr.size, max_new, self.max_seq_len))
        # a worst-case reservation larger than the WHOLE pool — or than
        # the tenant's own page budget / rate burst — could never be
        # admitted: it would sit at its sub-queue head deferring forever,
        # so reject it at the door instead
        total = int(arr.size) + max_new
        need = self._cache.pages_for(total)
        capacity = self._cache.num_pages - 1
        tobj = self._tenants.resolve(tenant)
        # the trace is minted HERE — at submit(), the contract — so
        # EVERY door-reject (pool capacity, budgets, breaker) and shed
        # leaves a queryable chain too
        trace = _tracing.start_trace("decode", self._name, tobj.tenant_id)
        _tracing.event(trace, "submit", prompt_tokens=int(arr.size),
                       max_new=max_new)
        if need > capacity:
            _tracing.finish(trace, "rejected", reason="pool_capacity")
            raise MXNetError(
                "prompt %d + max_new %d needs %d KV pages but the pool "
                "only has %d: raise MXNET_KVCACHE_PAGES or shrink the "
                "request" % (arr.size, max_new, need, capacity))
        if tobj.page_budget is not None and need > tobj.page_budget:
            _tracing.finish(trace, "rejected", reason="page_budget")
            raise MXNetError(
                "request needs %d KV pages but tenant %r's page budget "
                "is %d: it could never be admitted"
                % (need, tobj.tenant_id, tobj.page_budget))
        if tobj.rate > 0.0 and total > tobj.burst:
            _tracing.finish(trace, "rejected", reason="burst_budget")
            raise MXNetError(
                "request costs %d tokens but tenant %r's burst budget "
                "is %.0f: it could never be admitted"
                % (total, tobj.tenant_id, tobj.burst))
        state = tobj.breaker.state
        if state == "open":
            # the tenant's own breaker is open: shed THIS tenant at the
            # door while every other tenant keeps flowing
            tobj.stats.on_shed(breaker=True)
            _T_EVENTS.inc(server=self._name, event="shed_tenant_breaker")
            _tracing.finish(trace, "shed", reason="tenant_breaker")
            raise TenantUnavailableError(tobj.tenant_id, state)
        timeout_s = (self._timeout_s if timeout_ms is None
                     else float(timeout_ms) / 1e3)
        deadline = (None if timeout_s <= 0
                    else time.perf_counter() + timeout_s)
        req = _DecodeRequest(arr, max_new, eos_id, deadline, tobj)
        req.trace = trace
        shed = None
        depth = 0
        with self._cv:
            if self._closed:
                raise ServerClosedError("submit() on a closed DecodeEngine")
            if len(tobj.queue) >= tobj.queue_depth:
                # per-tenant shed: one tenant's backlog fills ITS bound
                # before it can crowd the global queue
                shed = "tenant %r queue full (depth %d): request shed " \
                       "before the global queue" \
                       % (tobj.tenant_id, tobj.queue_depth)
            elif self._wfq.total_queued() >= self._queue_depth:
                shed = "decode queue full (depth %d): request shed" \
                       % self._queue_depth
            else:
                depth = self._wfq.push(tobj, req)
                gdepth = self._wfq.total_queued()
                self._cv.notify_all()
        if shed:
            self._stats.on_shed()
            tobj.stats.on_shed()
            _tracing.finish(trace, "shed", reason="queue_full")
            raise QueueFullError(shed)
        _tracing.event(trace, "enqueue", rid=req.rid, tenant_depth=depth,
                       queue_depth=gdepth)
        self._stats.on_submit(gdepth)
        tobj.stats.on_submit(depth)
        return req.future

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None) -> np.ndarray:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, max_new_tokens, eos_id=eos_id,
                           tenant=tenant).result(timeout)

    # ------------------------------------------------------------------
    # live weight swap
    # ------------------------------------------------------------------
    def swap_params(self, params, variant: Optional[str] = None,
                    wait: bool = True,
                    timeout: Optional[float] = None) -> Future:
        """Hot-swap the served weights between ticks — zero dropped
        requests, zero recompiles.

        The new pytree must carry the SAME (treedef, shape, dtype)
        signature as the current one: the params enter the decode/prefill
        jits as a traced operand, so an equal signature is structurally
        guaranteed not to retrace (the steady-state-recompile gauge stays
        at 0 across the swap — asserted by the live-swap test and the
        BENCH_TENANT soak). In-flight sequences keep their slots and KV
        pages and continue under the new weights from the next tick — the
        fleet-upgrade/A-B-rollout semantic: nothing is evicted, nothing
        re-prefills. Returns a Future resolving True once a tick boundary
        applied the swap (``wait=True`` blocks on it)."""
        sig = _tree_sig(params)
        if sig != self._params_sig:
            raise MXNetError(
                "swap_params: new param pytree signature differs from the "
                "served one (tree structure, leaf shape or dtype) — a "
                "mismatched swap would retrace every rung; export the "
                "variant with identical architecture")
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise ServerClosedError("swap_params() on a closed engine")
            self._pending_swaps.append((params, variant, fut))
            self._cv.notify_all()
        if wait:
            fut.result(timeout)
        return fut

    def register_variant(self, name: str, params) -> None:
        """Register a named fine-tuned variant (same architecture) for
        :meth:`use_variant` — N variants served from ONE engine, swapped
        between ticks."""
        sig = _tree_sig(params)
        if sig != self._params_sig:
            raise MXNetError(
                "variant %r: param signature differs from the served "
                "model" % name)
        self._variants[str(name)] = params

    def use_variant(self, name: str, wait: bool = True,
                    timeout: Optional[float] = None) -> Future:
        """Swap a registered variant live (see :meth:`swap_params`)."""
        if name not in self._variants:
            raise MXNetError("unknown variant %r (registered: %s)"
                             % (name, sorted(self._variants) or "none"))
        return self.swap_params(self._variants[name], variant=str(name),
                                wait=wait, timeout=timeout)

    @property
    def active_variant(self) -> Optional[str]:
        with self._cv:
            return self._active_variant

    def warmup(self) -> int:
        """Compile the decode step, every prefill rung, every chunk rung
        and the CoW copy jit before traffic (dummy passes writing only
        to the null page); anchors the steady-state-recompile gauge at 0
        — a cold first shared-prefix request compiles NOTHING. Returns
        the compile count."""
        jnp = self._jnp
        s = self.num_slots
        with self._cv:
            # snapshot: a live swap_params() may rebind between rungs
            params = self._params
        # the step's packed operand carries W = spec_k+1 rows per slot;
        # warming at that width anchors the widened tick too
        packed = np.zeros((5, s * self._spec_w), np.int32)
        packed[3], packed[4] = self._cache.null_write_slots(s * self._spec_w)
        sampled, kp, vp = self._step(
            params, jnp.asarray(packed), self._cache.k_pool,
            self._cache.v_pool, self._device_page_table())
        self._cache.swap_pools(kp, vp)
        if not self._chunk:
            # chunked mode never dispatches the monolithic rungs — every
            # prompt runs through the one chunk rung compiled below
            for rung in self._ladder:
                pre = np.zeros((3, rung), np.int32)
                pre[1], pre[2] = self._cache.null_write_slots(rung)
                _tok, kp, vp = self._prefill_jit(
                    params, jnp.asarray(pre),
                    jnp.asarray(1, jnp.int32), self._cache.k_pool,
                    self._cache.v_pool)
                self._cache.swap_pools(kp, vp)
        null_row = np.zeros((self._cache.max_pages,), np.int32)
        for rung in self._chunk_rungs:
            pre = np.zeros((3, rung), np.int32)
            pre[1], pre[2] = self._cache.null_write_slots(rung)
            _tok, kp, vp = self._chunk_jit(
                params, jnp.asarray(pre), jnp.asarray(0, jnp.int32),
                jnp.asarray(1, jnp.int32), jnp.asarray(null_row),
                self._cache.k_pool, self._cache.v_pool)
            self._cache.swap_pools(kp, vp)
        if self._prefix_cache:
            # null -> null: harmless, and the CoW copy is compiled
            kp, vp = self._cow_jit(
                self._cache.k_pool, self._cache.v_pool,
                jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
            self._cache.swap_pools(kp, vp)
        count = self.compile_count
        self._warm_compiles = count if count >= 0 else None
        if self._warm_compiles is not None:
            telemetry.set_steady_state_recompiles("serving." + self._name, 0)
        return count

    @property
    def compile_count(self) -> int:
        sizes = [telemetry.jit_cache_size(self._step),
                 telemetry.jit_cache_size(self._prefill_jit),
                 telemetry.jit_cache_size(self._chunk_jit),
                 telemetry.jit_cache_size(self._cow_jit)]
        if any(s < 0 for s in sizes):
            return -1
        return sum(sizes)

    def queue_depth(self) -> int:
        """Requests queued but not yet slotted — the cheap read behind
        the fleet's ``/debug/state`` view (``stats()`` evaluates SLOs;
        this doesn't)."""
        with self._cv:
            return self._wfq.total_queued()

    def kvcache_stats(self) -> dict:
        """The paged pool's counters alone (pages in use/free, prefix
        hit ratio) — the cheap subset of :meth:`stats`."""
        return self._cache.stats()

    def stats(self) -> dict:
        out = self._stats.snapshot()
        with self._cv:
            active = sum(1 for r in self._slots if r is not None)
            out.update({
                "slots": self.num_slots,
                "active_slots": active,
                "queued": self._wfq.total_queued(),
                "tokens_generated": self._tokens_total,
                "prefills": self._prefills,
                "evictions": self._evictions,
                "deadline_evictions": self._deadline_evictions,
                "slot_occupancy": (self._occ_sum / self._ticks
                                   if self._ticks else 0.0),
                "prefill_buckets": list(self._ladder),
                "prefill_chunk": self._chunk,
                "cow_copies": self._cow_copies,
                "breaker": self._breaker.state,
                "weight_swaps": self._swaps,
                "active_variant": self._active_variant,
                "speculative": {
                    "k": self._spec_k,
                    "draft": (getattr(self._draft, "name", None)
                              if self._draft is not None else None),
                    "proposed_tokens": self._spec_proposed,
                    "accepted_tokens": self._spec_accepted,
                    "acceptance_rate": (self._spec_accepted /
                                        self._spec_proposed
                                        if self._spec_proposed else 0.0),
                    # tokens committed per SPECULATING slot-tick — the
                    # >1.0 gate of the BENCH_DECODE soak (1.0 = drafts
                    # never helped; k+1 = every draft accepted)
                    "accepted_per_tick": (self._spec_new /
                                          self._spec_slot_ticks
                                          if self._spec_slot_ticks else 0.0),
                },
            })
            governed = self._governed_limit
        out["tenants"] = self._tenants.snapshot()
        out["kvcache"] = self._cache.stats()
        # the governor's verdict rides every stats snapshot (the fleet's
        # replica rows and /debug/state read it from here)
        hv = self._governor.healthz_view()
        hv["governed_limit"] = governed
        hv["pressure_sheds"] = self._cache.pressure_sheds
        out["hbm"] = hv
        out["prefix_cache_enabled"] = self._prefix_cache
        if self._prefix_cache:
            out["prefix_hit_ratio"] = out["kvcache"]["prefix_hit_ratio"]
            # refcount>1 pages belong to the `shared` pseudo-tenant: no
            # real tenant's budget is charged for them (a sharer pays
            # only its exclusive tail + CoW copies)
            out["tenants"][SHARED_TENANT] = {
                "pseudo": True,
                "pages_in_use_now": out["kvcache"]["shared_pages"],
                "pages_cached": out["kvcache"]["pages_cached"],
            }
        count = self.compile_count
        out["compile_count"] = count
        if self._warm_compiles is not None and count >= 0:
            steady = count - self._warm_compiles
            out["steady_state_recompiles"] = steady
            telemetry.set_steady_state_recompiles(
                "serving." + self._name, steady)
        # live SLO verdicts over the series this snapshot just refreshed
        out["alerts"] = _slo.evaluate()
        return out

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> int:
        """Stop intake; ``drain=True`` finishes every queued AND admitted
        sequence first, ``drain=False`` fails them with
        :class:`ServerClosedError` now. Idempotent.

        Returns the number of requests that *completed during the drain*
        (0 for ``drain=False`` and for repeat closes) — the number a
        zero-drop replica drain / rolling upgrade asserts against; also
        published as ``mxnet_serving_drain_completed_total{server=}``."""
        before = self._stats.completed
        with self._cv:
            self._closed = True
            dropped: List[_DecodeRequest] = []
            if not drain:
                dropped = [req for _t, req in self._wfq.drain()]
                for i, req in enumerate(self._slots):
                    if req is not None:
                        dropped.append(req)
                        self._slots[i] = None
                        self._release_slot(i, req)
            self._cv.notify_all()
        exc = ServerClosedError("engine closed before completion")
        for req in dropped:
            self._fail(req, exc)
        if self._thread is not threading.current_thread():
            self._thread.join(timeout)
        # the governor outlives the engine (process-global): replace the
        # live-state bounds with zeros so a closed engine neither skews
        # pressure nor stays pinned through the pending-prefill closure
        self._governor.register_bound(
            "serving.%s.kv_pool" % self._name, 0)
        self._governor.register_bound(
            "serving.%s.pending_prefill" % self._name, 0)
        if not drain:
            return 0
        drained = max(0, self._stats.completed - before)
        self._stats.on_drain(drained)
        return drained

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def name(self) -> str:
        """The engine's server name — keys its stats series, breaker
        site and kv-cache gauges (and the fleet router's replica map)."""
        return self._name

    @property
    def page_size(self) -> int:
        """Tokens per KV page — the chunk granularity of the prefix
        cache's rolling hash (the fleet router hashes prompts at the
        same granularity to route for affinity)."""
        return self._cache.page_size

    @property
    def tenants(self) -> TenantRegistry:
        """The engine's tenant registry — register tenants with explicit
        weights/quotas before (or while) traffic flows."""
        return self._tenants

    # ------------------------------------------------------------------
    # engine thread
    # ------------------------------------------------------------------
    def _any_active(self) -> bool:
        return any(r is not None for r in self._slots)

    def _worker(self):
        while True:
            with self._cv:
                while not self._wfq.total_queued() \
                        and not self._any_active() and not self._closed \
                        and not self._pending_swaps:
                    self._cv.wait()
                if self._closed and not self._wfq.total_queued() \
                        and not self._any_active():
                    swaps, self._pending_swaps = self._pending_swaps, []
                    break
            self._apply_pending_swaps()
            self._expire_queued()
            self._evict_expired()
            self._shed_tenant_breakers()
            has_work = False
            with self._cv:
                has_work = bool(self._wfq.total_queued()) \
                    or self._any_active()
            if not has_work:
                continue
            if not self._breaker.allow():
                # open ENGINE breaker: answer all queued work explicitly
                # (the PR-2 engine load-shed) instead of letting it age
                # out; the reset timeout admits a half-open probe later
                self._shed_open_breaker()
                time.sleep(0.005)
                continue
            try:
                # devprof tick scope: the sampling decision is drawn once
                # for the whole tick so a timed tick's prefill/step/host-
                # gap breakdown is coherent; one global read when off
                tick_t0 = time.perf_counter()
                tick_timed = _devprof.tick_begin()
                toks_before = self._tokens_total
                self._admit()
                prefilling = [(i, r) for i, r in enumerate(self._slots)
                              if r is not None and r.prefilling]
                decoding = [(i, r) for i, r in enumerate(self._slots)
                            if r is not None and not r.prefilling]
                if prefilling:
                    # ONE chunk per tick, ROUND-ROBIN over prefilling
                    # slots (admission order, wrapping), then the tick
                    # goes back to decoding. Round-robin — not oldest-
                    # first — is what decouples TTFT from the longest
                    # prompt: a 1-chunk prompt lands on its next turn
                    # instead of waiting out a 100-chunk neighbour.
                    cands = sorted(prefilling, key=lambda t: t[1].seq)
                    slot, req = next(
                        (t for t in cands if t[1].seq > self._rr_last),
                        cands[0])
                    self._rr_last = req.seq
                    self._advance_prefill(slot, req)
                if decoding:
                    self._step_once(decoding)
                elif not prefilling:
                    # every queued tenant deferred (pages/rate/breaker)
                    # with nothing in flight: yield instead of spinning
                    if tick_timed:
                        _devprof.tick_end()
                    time.sleep(0.001)
                    continue
                if tick_timed:
                    _devprof.note_decode_tick(
                        self._name,
                        (time.perf_counter() - tick_t0) * 1e3,
                        self._tokens_total - toks_before)
            except Exception as exc:  # noqa: BLE001 - engine must survive
                _devprof.tick_end()  # don't leak the tick scope into the
                # eviction/recovery path's dispatches
                # belt-and-braces (the PR-2 batcher discipline): NO
                # exception may kill the engine thread — that would hang
                # every in-flight and queued future forever. Evict
                # whatever was in flight and keep serving. This is also a
                # black-box moment: something unexpected reached the
                # catch-all, so commit the ring before state is torn down.
                _flightrec.record("decode.engine_exception",
                                  server=self._name, error=repr(exc))
                _flightrec.dump("decode engine catch-all: %r" % (exc,))
                self._breaker.on_failure()
                self._evict([(i, r) for i, r in enumerate(self._slots)
                             if r is not None], exc)
        # drained close: resolve any swap still pending so its waiter
        # does not hang on a dead worker
        exc = ServerClosedError("engine closed before the swap applied")
        for _params, _variant, fut in swaps:
            if fut.set_running_or_notify_cancel():
                fut.set_exception(exc)

    def _apply_pending_swaps(self):
        """Tick-boundary weight swap: rebind ``self._params`` between
        jitted executions. In-flight sequences continue on the new
        weights next tick; nothing is evicted and (same pytree
        signature) nothing retraces."""
        with self._cv:
            if not self._pending_swaps:
                return
            swaps, self._pending_swaps = self._pending_swaps, []
            for params, variant, _fut in swaps:
                self._params = params
                self._active_variant = variant
                self._swaps += 1
            self._swap_epoch += len(swaps)
            if swaps and self._prefix_cache:
                # cached KV was computed under the OLD weights: a prompt
                # prefilled under the new ones must not match it — flush
                # the index (in-flight sequences keep their pages and
                # continue, the documented rollout semantic)
                self._cache.clear_prefix_index()
        for _params, variant, fut in swaps:
            _T_EVENTS.inc(server=self._name, event="weight_swap")
            _flightrec.record("decode.weight_swap", server=self._name,
                              variant=variant)
            if fut.set_running_or_notify_cancel():
                fut.set_result(True)

    def _expire_queued(self):
        now = time.perf_counter()
        with self._cv:
            expired = self._wfq.expire(now)
        for tenant, req in expired:
            self._stats.on_timeout()
            tenant.stats.on_timeout()
            _tracing.finish(req.trace, "timeout", where="queued")
            self._fail(req, RequestTimeoutError(
                "request spent > its deadline queued"))

    def _evict_expired(self):
        """Deadline propagation into the tick loop: a sequence whose
        deadline passed mid-decode is evicted at the tick boundary —
        pages freed for waiting tenants, future failed — instead of
        holding its slot to the token budget."""
        now = time.perf_counter()
        victims: List[tuple] = []
        with self._cv:
            for i, req in enumerate(self._slots):
                if req is not None and req.deadline is not None \
                        and now > req.deadline:
                    victims.append((i, req))
                    self._slots[i] = None
                    self._release_slot(i, req)
        if victims:
            with self._cv:
                self._deadline_evictions += len(victims)
        for i, req in victims:
            self._stats.on_timeout()
            req.tenant.stats.on_timeout()
            _T_EVENTS.inc(server=self._name, event="deadline_evicted")
            _tracing.finish(req.trace, "timeout", where="mid_decode",
                            tokens=len(req.tokens))
            _flightrec.record("decode.deadline_evict", server=self._name,
                              rid=req.rid, tenant=req.tenant.tenant_id)
            self._fail(req, RequestTimeoutError(
                "deadline expired mid-decode after %d generated tokens: "
                "evicted at the tick boundary" % len(req.tokens)))

    def _shed_tenant_breakers(self):
        """A tenant whose breaker is open has its QUEUED work answered
        now with :class:`TenantUnavailableError` — that tenant alone;
        the engine keeps serving everyone else."""
        dropped: List[tuple] = []
        for tenant in self._tenants:
            if not tenant.queue:
                continue
            if tenant.breaker.state == "open":
                with self._cv:
                    dropped.extend(self._wfq.drain(tenant))
        for tenant, req in dropped:
            tenant.stats.on_shed(breaker=True)
            _T_EVENTS.inc(server=self._name, event="shed_tenant_breaker")
            _tracing.finish(req.trace, "shed", reason="tenant_breaker")
            self._fail(req, TenantUnavailableError(tenant.tenant_id,
                                                   "open"))

    def _shed_open_breaker(self):
        with self._cv:
            dropped = self._wfq.drain()
        if not dropped:
            return
        exc = EngineUnavailableError(
            "decode breaker is %s: request shed" % self._breaker.state)
        for tenant, req in dropped:
            self._stats.on_unavailable(1)
            tenant.stats.on_shed()
            _tracing.finish(req.trace, "shed", reason="engine_breaker")
            self._fail(req, exc)
            _T_EVENTS.inc(server=self._name, event="shed_open_breaker")

    # -- admission ------------------------------------------------------
    def _admit_guard(self, tenant: Tenant, req: "_DecodeRequest") -> bool:
        """Per-tenant admission veto, called by the weighted-fair pick
        under ``self._cv``. False = defer THIS tenant (its turn passes;
        other tenants' smaller/cheaper heads still admit this round —
        the anti-head-of-line property)."""
        # non-consuming open-state check FIRST, so a deferred tenant's
        # tokens are never charged for an admission its breaker would
        # refuse anyway (the worker's shed pass drains it shortly)
        if tenant.breaker.state == "open":
            _tracing.event(req.trace, "defer", reason="breaker")
            return False
        # orange-tier ladder rung: batch-class tenants defer while the
        # governor reports pressure — a deferral, not a shed (the
        # request stays queued and admits when the tier recedes), and
        # it NEVER touches interactive/standard heads: anti-head-of-line
        # means the batch head's turn simply passes to them
        if self._tick_tier in ("orange", "red") \
                and tenant.priority >= PRIORITY_CLASSES["batch"]:
            tenant.stats.on_defer("pressure")
            _tracing.event(req.trace, "defer", reason="pressure")
            return False
        total = int(req.prompt.size) + req.max_new
        # the admission walk: map-able shared prefix pages reduce both
        # the global reservation AND the tenant's charge — reserve()
        # only pays for the non-shared tail (+ the CoW copy). Stashed on
        # the request; _prefill consumes it on the same worker pass, so
        # the index cannot change in between.
        match = (self._cache.match_prefix(req.prompt)
                 if self._prefix_cache
                 and not (self._ring_len
                          and req.prompt.size >= self._ring_len)
                 else None)
        req.match = match
        need = self._cache.pages_for(total)
        if match is not None:
            need -= len(match.full)
        if not self._cache.can_admit_prefix(total, match):
            # global page pressure: this head defers, a cheaper tenant
            # behind it may still fit
            tenant.stats.on_defer("pages")
            _tracing.event(req.trace, "defer", reason="pages_global")
            return False
        if not tenant.within_page_budget(need):
            # the tenant is at ITS quota (shared pages charge the
            # `shared` pseudo-tenant, not this budget) — only its own
            # completions can unblock it, everyone else keeps flowing
            tenant.stats.on_defer("pages")
            _tracing.event(req.trace, "defer", reason="pages_budget")
            return False
        if not tenant.take_tokens(total):
            tenant.stats.on_defer("rate")
            _tracing.event(req.trace, "defer", reason="rate")
            return False
        # allow() LAST: it may consume the half-open probe, so it must
        # only run when the pop — and therefore the prefill that reports
        # the probe's outcome — really happens next. A veto here refunds
        # the tokens just taken: the request never ran.
        if not tenant.breaker.allow():
            tenant.refund_tokens(total)
            _tracing.event(req.trace, "defer", reason="breaker")
            return False
        _tracing.event(req.trace, "admission_verdict", pages_needed=need,
                       matched_pages=len(match.full) if match else 0)
        return True

    def _admit(self):
        # the governor's degradation ladder, consulted once per
        # admission pass (observe() is pure host arithmetic over the
        # bound registry — tick-rate cheap):
        #   yellow+  shed cached-LRU ref-0 prefix pages proactively
        #   orange   shrink the admission quantum to 1/pass and defer
        #            batch-class tenants (_admit_guard, never interactive)
        #   red      stop new admissions entirely; in-flight sequences
        #            keep decoding — completion is what drains pressure
        tier = self._governor.observe(source="decode.admit")
        with self._cv:
            # _cv guards both governor fields: _admit_guard reads
            # _tick_tier under the pop's lock, stats() reads
            # _governed_limit from caller threads
            # the only reader, _admit_guard, is a callback invoked through
            # _wfq.pop() inside this same worker's `with self._cv` block —
            # lock-guarded on both sides, just through an indirection the
            # analyzer cannot follow
            self._tick_tier = tier  # tpulint: disable=shared-state-race
            if self._governed_limit is not None and tier == "green" \
                    and not self._governor.latched:
                self._governed_limit = None
            governed = self._governed_limit
        if tier != "green":
            shed = self._cache.shed_cached()
            if shed:
                self._governor.note_shed(shed, self._cache.name)
                _T_EVENTS.inc(server=self._name, event="pressure_shed")
        if tier == "red":
            return
        limit = self.num_slots
        if governed is not None:
            # post-OOM governed re-admission: fewer sequences, same
            # static slot shapes, until the governor recovers green
            limit = min(limit, governed)
        quantum = 1 if tier == "orange" else self.num_slots
        admitted = 0
        while True:
            if sum(1 for r in self._slots if r is not None) >= limit:
                return
            slot = next((i for i, r in enumerate(self._slots)
                         if r is None), None)
            if slot is None:
                return
            with self._cv:
                picked = self._wfq.pop(self._admit_guard)
            if picked is None:
                return
            tenant, req = picked
            tenant.stats.set_depth(len(tenant.queue))
            try:
                self._prefill(req, slot)
            except Exception as exc:  # noqa: BLE001 - isolate to request
                # per-request isolation: a prefill failure (poisoned
                # prompt, tenant-scoped fault, exhausted retries) answers
                # ONLY this future — and feeds the TENANT breaker, not
                # the engine one (request-level vs tick-level faults)
                self._release_slot(slot, req)
                tenant.on_request_failure()
                self._stats.on_error()
                self._fail(req, exc)
                if self._on_oom("serving.decode.prefill", exc) \
                        or self._pools_dead():
                    # ...unless the failure classified as an OOM (an
                    # allocation died — every pool byte is suspect, and
                    # the governor just latched red) or the failed
                    # execution consumed the donated pools: every live
                    # sequence's KV died with them, so evict them all
                    # onto fresh pools (empty `active` still re-zeroes —
                    # reset_pools runs either way)
                    self._evict([(i, r) for i, r
                                 in enumerate(self._slots)
                                 if r is not None], exc)
                    return
            admitted += 1
            if admitted >= quantum:
                # orange's shrunk admission quantum: one admission per
                # pass keeps new prefill load trickling while pressure
                # is worked off
                return

    def _on_oom(self, plane: str, exc: BaseException) -> bool:
        """OOM classification at a failure site: False (untouched) for a
        non-OOM exception. A classified OOM — real ``RESOURCE_EXHAUSTED``
        out of XLA or the chaos harness's ``action=oom`` — runs the
        shared survival routine (``hbm.oom_survival``: diagnostic into
        the flight recorder, governor latched red, per-plane counter)
        and arms governed re-admission: after the caller's full
        eviction, ``_admit`` re-admits at half the sequence count that
        was in flight (``MXNET_HBM_RED_ADMIT`` overrides) until the
        governor recovers green. Slot shapes never change — fewer
        sequences, same jit signatures, zero recompiles."""
        if not _hbm.oom_survival(plane, exc, dump=False):
            return False
        active = sum(1 for r in self._slots if r is not None)
        with self._cv:
            self._governed_limit = self._governor.governed_admit(
                max(1, active))
        _T_EVENTS.inc(server=self._name, event="oom")
        return True

    def _prefill(self, req: _DecodeRequest, slot: int):
        # tenant-scoped chaos site, OUTSIDE the retry policy: a fault
        # scheduled against this tenant models the tenant's own traffic
        # being poisoned — it fails this request (feeding the tenant's
        # breaker via _admit's handler), it is not an engine transient
        # to be retried away. Site: serving.decode.tenant.<id>.
        chaos.maybe_fail("serving.decode.tenant.%s" % req.tenant.tenant_id)
        p = int(req.prompt.size)
        total = p + req.max_new
        req.epoch = self._swap_epoch  # worker-confined read
        ring = bool(self._ring_len and p >= self._ring_len)
        if self._prefix_cache and not ring:
            # the admission walk's match (stashed by the guard on this
            # same worker pass): shared full pages map refcounted into
            # the slot, the divergent/partial page gets a private CoW
            # copy, and reserve() pays only for the non-shared tail
            matched, cow_src, cow_dst = self._cache.admit_prefix(
                slot, total, req.match)
        else:
            self._cache.reserve(slot, total)
            matched, cow_src, cow_dst = 0, None, None
        # shared pages charge the `shared` pseudo-tenant (i.e. nobody):
        # the tenant's budget pays for its exclusive tail + CoW copies
        req.tenant.charge_pages(self._cache.exclusive_pages(slot))
        if cow_src is not None:
            self._run_cow(cow_src, cow_dst)
        req.kv_cached = matched
        _tracing.event(req.trace, "admit", slot=slot, ring=ring,
                       queue_wait_ms=round(
                           (time.perf_counter() - req.t_submit) * 1e3, 3))
        if matched:
            _tracing.event(req.trace, "prefix_hit", tokens_cached=matched)
        if cow_src is not None:
            _tracing.event(req.trace, "cow_copy", src_page=cow_src,
                           dst_page=cow_dst)
        # at least the LAST prompt position always runs through the
        # model: its logits are the first output token — a full-prompt
        # hit recomputes that one position (null writes) over the
        # shared/CoW pages instead of re-prefilling anything
        req.filled = min(matched, p - 1)
        if self._chunk and not ring:
            req.prefilling = True
            with self._cv:
                self._admit_seq += 1
                req.seq = self._admit_seq
            self._slots[slot] = req
            _T_EVENTS.inc(server=self._name, event="admitted")
            return
        if matched == 0:
            tok = self._run_full_prefill(req, slot, ring=ring)
        else:
            rung = select_bucket(p - req.filled, self._ladder)
            tok = self._run_chunk(slot, req, req.filled, p, rung)
        self._finish_prefill(req, slot, tok)

    def _run_full_prefill(self, req: _DecodeRequest, slot: int,
                          ring: bool = False):
        """The monolithic prefill: whole prompt padded to a ladder rung,
        attention in-graph (or routed through ring attention for
        long-context prompts). The cold-cache path — a prefix hit runs
        :meth:`_run_chunk` over the tail instead."""
        from .. import resilience

        jnp = self._jnp
        p = int(req.prompt.size)
        rung = select_bucket(p, self._ladder)
        _tracing.event(req.trace, "prefill", rung=rung, tokens=p,
                       ring=ring)
        pre = np.zeros((3, rung), np.int32)  # tokens, write pages, offsets
        pre[0, :p] = req.prompt
        wpg, woff = self._cache.write_slots(slot, 0, p)
        npg, noff = self._cache.null_write_slots(rung - p)
        pre[1] = np.concatenate([wpg, npg])
        pre[2] = np.concatenate([woff, noff])
        policy = self._retry or resilience.default_policy()

        def attempt():
            chaos.maybe_fail("serving.decode.prefill")
            if self._pools_dead():
                raise MXNetError(  # not transient: stop the retry loop
                    "KV pools consumed by a failed prefill (donation); "
                    "eviction required")
            if ring:
                return self._run_ring_prefill(pre[0], p, pre[1], pre[2])
            return telemetry.jit_call(
                "serving.decode_prefill", self._prefill_jit, self._params,
                jnp.asarray(pre), jnp.asarray(p, jnp.int32),
                self._cache.k_pool, self._cache.v_pool)

        tok, kp, vp = policy.call(attempt, site="serving.decode.prefill")
        self._cache.swap_pools(kp, vp)
        return tok

    def _run_chunk(self, slot: int, req: _DecodeRequest, start: int,
                   end: int, rung: int):
        """One jitted prefill chunk over prompt positions ``[start,
        end)`` of ``slot``, padded to ``rung``. Positions below
        ``req.kv_cached`` are only *recomputed* (their KV already sits
        in shared/CoW pages — writes redirect to the null page); the
        rest scatter into the slot's reserved pages. Attention runs over
        the slot's page row, so each chunk sees everything written
        before it. Returns the device argmax token of position
        ``end - 1``."""
        from .. import resilience

        jnp = self._jnp
        n = end - start
        _tracing.event(req.trace, "prefill_chunk", start=start, end=end,
                       rung=rung)
        pre = np.zeros((3, rung), np.int32)
        pre[0, :n] = req.prompt[start:end]
        cached_n = max(0, min(req.kv_cached, end) - start)
        pages, offs = [], []
        if cached_n:
            npg, noff = self._cache.null_write_slots(cached_n)
            pages.append(npg)
            offs.append(noff)
        if n - cached_n:
            wpg, woff = self._cache.write_slots(slot, start + cached_n,
                                                n - cached_n)
            pages.append(wpg)
            offs.append(woff)
        if rung - n:
            npg, noff = self._cache.null_write_slots(rung - n)
            pages.append(npg)
            offs.append(noff)
        pre[1] = np.concatenate(pages)
        pre[2] = np.concatenate(offs)
        row = np.ascontiguousarray(self._cache.page_table[slot])
        policy = self._retry or resilience.default_policy()

        def attempt():
            chaos.maybe_fail("serving.decode.prefill")
            if self._pools_dead():
                raise MXNetError(  # not transient: stop the retry loop
                    "KV pools consumed by a failed prefill (donation); "
                    "eviction required")
            return telemetry.jit_call(
                "serving.decode_prefill_chunk", self._chunk_jit,
                self._params, jnp.asarray(pre),
                jnp.asarray(start, jnp.int32), jnp.asarray(n, jnp.int32),
                jnp.asarray(row), self._cache.k_pool, self._cache.v_pool)

        tok, kp, vp = policy.call(attempt, site="serving.decode.prefill")
        self._cache.swap_pools(kp, vp)
        self._stats.on_prefill_chunk()
        return tok

    def _run_cow(self, src: int, dst: int):
        """The copy-on-write device copy (jitted, precompiled at
        warmup): the divergent/partial page's K/V duplicated into the
        writer's own page BEFORE any of its writes can land there —
        sharers never observe each other's tokens."""
        jnp = self._jnp
        kp, vp = telemetry.jit_call(
            "serving.decode_cow", self._cow_jit, self._cache.k_pool,
            self._cache.v_pool, jnp.asarray(src, jnp.int32),
            jnp.asarray(dst, jnp.int32))
        self._cache.swap_pools(kp, vp)
        with self._cv:
            self._cow_copies += 1
        _T_EVENTS.inc(server=self._name, event="cow_copy")

    def _advance_prefill(self, slot: int, req: _DecodeRequest):
        """Chunked prefill: ONE chunk for ``slot``, then the tick yields
        back to decoding. Completion delivers the first token (the TTFT
        mark). A chunk failure is request-level — exactly this future
        fails (feeding the TENANT breaker), the engine keeps ticking —
        unless donation consumed the pools, which escalates to the full
        eviction like any pool death."""
        p = int(req.prompt.size)
        end = min(req.filled + self._chunk, p)
        try:
            tok = self._run_chunk(slot, req, req.filled, end, self._chunk)
        except Exception as exc:  # noqa: BLE001 - isolate to request
            self._slots[slot] = None
            self._release_slot(slot, req)
            req.tenant.on_request_failure()
            self._stats.on_error()
            self._fail(req, exc)
            if self._on_oom("serving.decode.prefill", exc) \
                    or self._pools_dead():
                self._evict([(i, r) for i, r in enumerate(self._slots)
                             if r is not None], exc)
            return
        req.filled = end
        if end >= p:
            self._finish_prefill(req, slot, tok)

    def _finish_prefill(self, req: _DecodeRequest, slot: int, tok):
        """Prefill complete (monolithic, tail or final chunk): index the
        prompt's pages for future sharers, deliver the first token and
        hand the slot to the decode tick."""
        p = int(req.prompt.size)
        self._breaker.on_success()
        req.tenant.breaker.on_success()
        self._cache.seq_lens[slot] = p
        if not (self._ring_len and p >= self._ring_len) \
                and req.epoch == self._swap_epoch:
            # a swap that landed mid-prefill (between chunks) flushed
            # the index AND left this sequence's earlier pages holding
            # old-weight KV: serving the request is the documented
            # in-flight rollout semantic, but RE-INDEXING those pages
            # would hand stale KV to future prompts — skip the insert
            self._cache.insert_prefix(slot, req.prompt)
        self._prefills += 1
        _T_EVENTS.inc(server=self._name, event="prefill")
        # first token: ONE scalar fetch per admitted sequence (prefill
        # rate, not token rate — outside the decode-host-sync budget)
        first = int(fetch_host([tok])[0])
        now = time.perf_counter()
        ttft = (now - req.t_submit) * 1e3
        _tracing.event(req.trace, "first_token", ttft_ms=round(ttft, 3))
        self._stats.on_first_token(ttft)
        req.tenant.stats.on_first_token(ttft)
        req.tokens.append(first)
        req.last_t = now
        self._tokens_total += 1
        _T_TOKENS.inc(server=self._name)
        req.slot = slot
        req.prefilling = False
        if not self._chunk:
            _T_EVENTS.inc(server=self._name, event="admitted")
        if self._finished(req, first):
            self._slots[slot] = None
            self._complete(req, slot, now)
        else:
            self._slots[slot] = req

    def _run_ring_prefill(self, tokens, length, wpg, woff):
        """Long-context prefill: same model function, attention swapped
        for ring attention over the local device mesh. Runs eagerly (the
        collective path device_puts shardings jit can't trace), so it
        trades the compile-once guarantee for sequence-sharded memory —
        the documented long-context trade (docs/serving.md)."""
        import jax

        from .. import sequence_parallel

        jnp = self._jnp
        model = self._model
        n_dev = jax.local_device_count()
        groups = model.num_heads // model.num_kv_heads

        def ring_attn(q, k, v, scale):
            # (T, H, D) -> ring layout (1, H, T, D); GQA expands kv
            if groups > 1:
                k = jnp.repeat(k, groups, axis=1)
                v = jnp.repeat(v, groups, axis=1)
            out = sequence_parallel.ring_attention(
                q.transpose(1, 0, 2)[None], k.transpose(1, 0, 2)[None],
                v.transpose(1, 0, 2)[None], causal=True, scale=scale)
            return out[0].transpose(1, 0, 2)

        use_ring = n_dev > 1 and tokens.shape[0] % n_dev == 0
        last, kp, vp = model.prefill(
            self._params, jnp.asarray(tokens),
            jnp.asarray(length, jnp.int32), self._cache.k_pool,
            self._cache.v_pool, jnp.asarray(wpg), jnp.asarray(woff),
            attn=ring_attn if use_ring else None)
        return jnp.argmax(last).astype(jnp.int32), kp, vp

    # -- the decode tick ------------------------------------------------
    def _step_once(self, active):
        from .. import resilience

        jnp = self._jnp
        s = self.num_slots
        w = self._spec_w
        ps = self._cache.page_size
        # rows: tokens, positions, seq_lens, write pages, write offsets —
        # ONE packed put per tick, W = spec_k+1 query rows per slot (slot
        # s owns rows s*W .. s*W+W-1: row 0 the committed token, rows
        # 1..k its draft guesses at the next positions). W is static —
        # draft depth, acceptance and per-tenant caps vary only the data,
        # so speculation can never retrace the step. Inactive slots and
        # unused draft rows keep seq_len 0 and the null write page (row 3
        # stays 0); their offsets cycle the page so scatter indices stay
        # in range.
        packed = np.zeros((5, s * w), np.int32)
        packed[4] = np.arange(s * w) % ps
        drafts: dict = {}
        pages_before = self._cache.pages_in_use if self._cache.audit else 0
        for slot, req in active:
            pos = int(req.prompt.size) + len(req.tokens) - 1
            base = slot * w
            draft = (self._propose(req, slot, pos)
                     if self._draft is not None else ())
            drafts[slot] = draft
            row_toks = [req.tokens[-1]]
            row_toks.extend(int(t) for t in draft)
            for j, row_tok in enumerate(row_toks):
                # row j carries the token at absolute position pos+j and
                # attends up to itself (per-row seq_len) — rows below it
                # in the same tick write their KV before attention reads,
                # so draft rows see each other causally. Admission's
                # worst-case reserve() plus the _propose clamp guarantee
                # pos+j is covered, so index the page table directly.
                packed[0, base + j] = row_tok
                packed[1, base + j] = pos + j
                packed[2, base + j] = pos + j + 1
                packed[3, base + j] = \
                    self._cache.page_table[slot, (pos + j) // ps]
                packed[4, base + j] = (pos + j) % ps
        # black box: the in-flight set BEFORE the step executes, so a
        # mid-tick death's dump names the failing tick's sequences and
        # their tenants (the post-mortem acceptance contract). One event
        # per tick, one deque append — the enabled() guard keeps even
        # the reqs-list BUILD off the MXNET_TELEMETRY=0 hot path.
        if telemetry.enabled():
            _flightrec.record(
                "decode.tick", server=self._name, tick=self._ticks,
                reqs=[[req.rid, req.tenant.tenant_id,
                       "prefill" if req.prefilling else "decode"]
                      for req in self._slots if req is not None])
        policy = self._retry or resilience.default_policy()

        def attempt():
            chaos.maybe_fail("serving.decode")
            if self._pools_dead():
                raise MXNetError(  # not transient: stop the retry loop
                    "KV pools consumed by a failed step (donation); "
                    "eviction required")
            return telemetry.jit_call(
                "serving.decode_step", self._step, self._params,
                jnp.asarray(packed), self._cache.k_pool,
                self._cache.v_pool, self._device_page_table())

        try:
            sampled, kp, vp = policy.call(attempt, site="serving.decode")
            self._cache.swap_pools(kp, vp)
            # the one per-token device->host sync of the plane: the
            # sampled token ids must reach the host for EOS/stop checks
            # and feedback. Inside the try: a wedged transfer evicts the
            # tick like a failed step instead of killing the worker.
            toks = fetch_host([sampled])[0]
        except Exception as exc:  # noqa: BLE001 - evict, don't die
            # OOM first: a classified RESOURCE_EXHAUSTED (or injected
            # action=oom) additionally latches the governor red and arms
            # governed re-admission before the same full-eviction path
            # below reclaims every page
            self._on_oom("serving.decode", exc)
            self._breaker.on_failure()
            # the pool re-zero kills EVERY in-flight sequence's KV —
            # chunked-prefilling slots included, not just this tick's
            self._evict([(i, r) for i, r in enumerate(self._slots)
                         if r is not None], exc)
            return
        self._breaker.on_success()
        now = time.perf_counter()
        tpots = []
        tenant_tpots: dict = {}
        tenant_slots: dict = {}
        tenant_spec: dict = {}
        total_new = 0
        tick_proposed = 0
        tick_accepted = 0
        for slot, req in active:
            base = slot * w
            draft = drafts.get(slot, ())
            k_eff = len(draft)
            # greedy rejection: accept the longest draft prefix that
            # equals the model's own argmax chain — committed token j is
            # the model's prediction from row j, and draft[j] rode row
            # j+1, so draft[j] was a correct guess iff it equals
            # toks[base+j]. The committed tokens are ALWAYS the model's
            # outputs (never the draft's), so output == sequential
            # greedy decode bit-for-bit whatever the draft proposed.
            a = 0
            while a < k_eff and int(draft[a]) == int(toks[base + a]):
                a += 1
            n_new = 0
            for j in range(a + 1):
                tok = int(toks[base + j])
                req.tokens.append(tok)
                n_new += 1
                if self._finished(req, tok):
                    break
            # commit = advance seq_lens past the rows that verified;
            # rejected rows' KV (positions >= the new seq_len) is the
            # ROLLBACK: never committed, masked by the ragged attention
            # bound, and overwritten by the next tick's rows — no page
            # alloc/free happened mid-tick, so there is nothing else to
            # unwind and no bystander is touched.
            self._cache.seq_lens[slot] += n_new
            accepted = min(a, n_new)
            total_new += n_new
            ms = (now - req.last_t) * 1e3
            # every decode tick the sequence participates in is a hop of
            # its (sampled) trace — the None path is one pointer check.
            # A multi-token tick amortizes the wall interval over its
            # commits so TPOT keeps meaning time-per-OUTPUT-token.
            per_tok = ms / n_new
            _tracing.event(req.trace, "tick",
                           token_index=len(req.tokens),
                           tpot_ms=round(per_tok, 3),
                           **({"drafted": k_eff, "accepted": accepted}
                              if self._spec_k else {}))
            tpots.extend([per_tok] * n_new)
            tenant_tpots.setdefault(req.tenant, []).extend(
                [per_tok] * n_new)
            tenant_slots[req.tenant] = tenant_slots.get(req.tenant, 0) + 1
            if k_eff:
                self._spec_slot_ticks += 1
                self._spec_new += n_new
                tick_proposed += k_eff
                tick_accepted += accepted
                row = tenant_spec.setdefault(req.tenant, [0, 0])
                row[0] += k_eff
                row[1] += accepted
            req.last_t = now
            if self._finished(req, int(req.tokens[-1])):
                self._slots[slot] = None
                tenant_slots[req.tenant] -= 1
                self._complete(req, slot, now)
        # per-TICK accounting, not per token: one reservoir extend + one
        # counter bump per tick keeps host bookkeeping off the token path
        # (and one per tenant that was active this tick)
        self._stats.on_output_tokens(tpots)
        for tenant, ms_batch in tenant_tpots.items():
            tenant.stats.on_output_tokens(ms_batch)
            tenant.stats.set_slots(tenant_slots.get(tenant, 0))
        if tick_proposed or tick_accepted:
            self._spec_proposed += tick_proposed
            self._spec_accepted += tick_accepted
            self._stats.on_spec(tick_proposed, tick_accepted)
            for tenant, (p_cnt, a_cnt) in tenant_spec.items():
                tenant.stats.on_spec(p_cnt, a_cnt)
        self._tokens_total += total_new
        _T_TOKENS.inc(total_new, server=self._name)
        self._ticks += 1
        occ = len(active) / float(s)
        self._occ_sum += occ
        _T_OCCUPANCY.set(occ, server=self._name)
        # MXNET_KVCACHE_AUDIT: re-prove the page refcount invariant at
        # every tick boundary, not just on cache mutations — seq_lens
        # advances and slot completion both ran above without a page-map
        # change, and the audit contract is "per tick"
        if self._cache.audit:
            self._cache.audit_check()
            # the speculation-specific tick invariants: a verify tick
            # allocates NOTHING (completions above can only free), and
            # no speculating tenant stands over the page budget it was
            # admitted under — the gauge-proven form of "k+1 writes fit
            # the admission-time reservation".
            if self._cache.pages_in_use > pages_before:
                raise MXNetError(
                    "kvcache %r audit: decode tick grew pages_in_use "
                    "%d -> %d — a speculative write escaped its "
                    "admission-time reservation" %
                    (self._name, pages_before, self._cache.pages_in_use))
            if self._spec_k:
                for tenant in {req.tenant for _slot, req in active}:
                    if tenant.page_budget is not None and \
                            tenant.pages_in_use > tenant.page_budget:
                        raise MXNetError(
                            "tenant %r audit: pages_in_use %d exceeds "
                            "page_budget %d after a speculative tick"
                            % (tenant.tenant_id, tenant.pages_in_use,
                               tenant.page_budget))

    def _propose(self, req: _DecodeRequest, slot: int, pos: int):
        """Draft tokens for one slot's verify tick, clamped so the tick
        can NEVER outgrow what admission reserved:

        * the engine k (the static width bound — more would change the
          compiled shape);
        * the tenant's ``spec_k`` cap, if set (can only lower);
        * the request's remaining output budget (a tick commits at most
          k+1 tokens; committing past ``max_new`` would over-generate);
        * the slot's page reservation (every row writes KV at pos+j,
          and ``write_slots`` hard-faults past the reserved run — the
          PR-13 tenant page budget was charged for exactly that run at
          admission, so staying inside it keeps the budget invariant
          mid-tick with zero page traffic).
        """
        from .speculative import sanitize

        k = self._spec_k
        cap = req.tenant.spec_k
        if cap is not None:
            k = min(k, cap)
        k = min(k, req.max_new - len(req.tokens) - 1)
        k = min(k, self._cache.reserved_tokens(slot) - (pos + 1))
        if k <= 0:
            return ()
        history = np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])
        try:
            proposed = self._draft.propose(history, k)
        except Exception:  # noqa: BLE001 - a draft bug must not kill ticks
            # drafts are hints: a failing proposer degrades this slot to
            # the classic single-token tick instead of faulting the tick
            # (which would evict every in-flight sequence)
            return ()
        return sanitize(proposed, k, self._model.vocab_size)

    def set_tenant_spec_k(self, tenant_id: str, spec_k: Optional[int]):
        """Set (or clear, with ``None``) one tenant's speculative draft
        cap at runtime. Caps only LOWER the engine's ``spec_k`` — the
        verify width K+1 is a compile-time shape — so a slow-accepting
        tenant can be throttled to 0 without touching anyone's compiled
        step. The fleet router forwards this to every replica."""
        tenant = self._tenants.resolve(tenant_id)
        tenant.spec_k = None if spec_k is None else max(0, int(spec_k))

    @staticmethod
    def _finished(req: _DecodeRequest, tok: int) -> bool:
        return (len(req.tokens) >= req.max_new
                or (req.eos_id is not None and tok == req.eos_id))

    def _release_slot(self, slot: int, req: _DecodeRequest):
        """Free a slot's page mappings AND return its EXCLUSIVE pages to
        the owning tenant's budget — shared prefix pages were never
        charged to it (they belong to the ``shared`` pseudo-tenant) and
        live on for other sharers / the prefix index. Idempotent (a slot
        already freed owns 0 pages), so the close()/worker race can
        double-call it harmlessly."""
        freed = self._cache.exclusive_pages(slot)
        self._cache.free(slot)
        req.tenant.release_pages(freed)

    def _complete(self, req: _DecodeRequest, slot: int, now: float):
        self._release_slot(slot, req)
        _T_EVENTS.inc(server=self._name, event="completed")
        _tracing.finish(req.trace, "complete", tokens=len(req.tokens),
                        latency_ms=round((now - req.t_submit) * 1e3, 3))
        if req.future.done():
            # close(drain=False) raced the in-flight tick and already
            # failed this future; completing it now would raise
            return
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(np.asarray(req.tokens, np.int32))
            lat = (now - req.t_submit) * 1e3
            self._stats.on_complete(lat)
            req.tenant.stats.on_complete(lat)

    def _evict(self, active, exc: BaseException):
        """A decode step failed after retries: only the sequences in
        flight are affected — fail exactly their futures, reset their
        slots and re-zero the pools (donation may have consumed the old
        buffers mid-failure), and keep serving new traffic. This is a
        TICK-level fault: it feeds the engine breaker (the caller), not
        the tenants' — the victims were bystanders of an engine failure,
        not misbehaving traffic."""
        _flightrec.record(
            "decode.evict", server=self._name, error=repr(exc),
            reqs=[[req.rid, req.tenant.tenant_id]
                  for _slot, req in active])
        for slot, req in active:
            self._slots[slot] = None
            self._release_slot(slot, req)
            self._stats.on_error()
            req.tenant.stats.on_error()
            self._evictions += 1
            _T_EVENTS.inc(server=self._name, event="evicted")
            _tracing.finish(req.trace, "evict",
                            tokens=len(req.tokens), error=repr(exc))
            self._fail(req, exc)
        self._cache.reset_pools()

    @staticmethod
    def _fail(req: _DecodeRequest, exc: BaseException):
        # generic terminal fallback: paths with a more specific verdict
        # (evict/timeout/shed) finish the trace first and this no-ops
        _tracing.finish(req.trace, "error", error=type(exc).__name__)
        if req.future.done():
            return
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)


# ---------------------------------------------------------------------------
# Reference model: a tiny pre-norm transformer over the paged cache
# ---------------------------------------------------------------------------

class TinyDecoder(PagedDecodeModel):
    """Small causal transformer implementing the paged-decode contract.

    The reference workload of the decode plane (bench soak + tests) and
    the template for wiring a real model: per layer — RMSNorm, QKV
    projections, :func:`~mxnet_tpu.serving.kvcache.write_kv` of the new
    K/V rows, :func:`~mxnet_tpu.ops.pallas_kernels.paged_attention` over
    the page table, output projection, RMSNorm + ReLU MLP; weights ride
    a plain dict pytree. ``embed_dim == num_heads * head_dim``;
    positions are sinusoidal (no learned table to size).
    """

    def __init__(self, vocab_size=128, num_layers=2, num_heads=4,
                 head_dim=16, num_kv_heads=None, mlp_ratio=2):
        if num_kv_heads is None:
            num_kv_heads = num_heads
        if num_heads % num_kv_heads:
            raise MXNetError("num_heads %d %% num_kv_heads %d != 0"
                             % (num_heads, num_kv_heads))
        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.embed_dim = self.num_heads * self.head_dim
        self.mlp_dim = self.embed_dim * int(mlp_ratio)
        self.scale = 1.0 / float(self.head_dim) ** 0.5

    # -- params ---------------------------------------------------------
    def init_params(self, seed: int = 0):
        import jax.numpy as jnp

        rng = np.random.RandomState(seed)
        e, v, h, kh, d, m = (self.embed_dim, self.vocab_size,
                             self.num_heads, self.num_kv_heads,
                             self.head_dim, self.mlp_dim)

        def w(*shape):
            return jnp.asarray(rng.randn(*shape).astype(np.float32)
                               * (1.0 / np.sqrt(shape[0])))

        layers = []
        for _ in range(self.num_layers):
            layers.append({
                "ln1": jnp.ones((e,), jnp.float32),
                "wq": w(e, h * d), "wk": w(e, kh * d), "wv": w(e, kh * d),
                "wo": w(h * d, e),
                "ln2": jnp.ones((e,), jnp.float32),
                "w1": w(e, m), "w2": w(m, e),
            })
        return {"embed": w(v, e), "layers": layers,
                "lnf": jnp.ones((e,), jnp.float32), "unembed": w(e, v)}

    # -- shared pieces --------------------------------------------------
    @staticmethod
    def _norm(x, scale):
        import jax.numpy as jnp

        return x * scale / jnp.sqrt(
            jnp.mean(jnp.square(x), axis=-1, keepdims=True) + 1e-6)

    def _pe(self, positions):
        import jax.numpy as jnp

        e = self.embed_dim
        half = e // 2
        freq = 1.0 / (10000.0 ** (jnp.arange(half) / float(half)))
        ang = positions.astype(jnp.float32)[:, None] * freq[None, :]
        return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)

    def _dense_causal(self, q, k, v, scale):
        """(T, H, D) causal attention oracle: the prefill in-graph path
        and the no-cache reference."""
        import jax
        import jax.numpy as jnp

        groups = self.num_heads // self.num_kv_heads
        if groups > 1:
            k = jnp.repeat(k, groups, axis=1)
            v = jnp.repeat(v, groups, axis=1)
        scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
        t = q.shape[0]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("hqk,khd->qhd", p, v)

    def _mlp(self, x, layer):
        import jax

        return jax.nn.relu(x @ layer["w1"]) @ layer["w2"]

    # -- contract -------------------------------------------------------
    def prefill(self, params, tokens, length, k_pool, v_pool,
                write_pages, write_offsets, attn=None):
        import jax.numpy as jnp

        t = tokens.shape[0]
        h, kh, d = self.num_heads, self.num_kv_heads, self.head_dim
        positions = jnp.arange(t, dtype=jnp.int32)
        x = params["embed"][tokens] + self._pe(positions)
        for li, layer in enumerate(params["layers"]):
            hx = self._norm(x, layer["ln1"])
            q = (hx @ layer["wq"]).reshape(t, h, d)
            k = (hx @ layer["wk"]).reshape(t, kh, d)
            v = (hx @ layer["wv"]).reshape(t, kh, d)
            k_pool, v_pool = write_kv(k_pool, v_pool, li, k, v,
                                      write_pages, write_offsets)
            if attn is None:
                att = self._dense_causal(q, k, v, self.scale)
            else:
                att = attn(q, k, v, self.scale)
            x = x + att.reshape(t, h * d) @ layer["wo"]
            x = x + self._mlp(self._norm(x, layer["ln2"]), layer)
        logits = self._norm(x, params["lnf"]) @ params["unembed"]
        return logits[length - 1], k_pool, v_pool

    def prefill_chunk(self, params, tokens, start, length, k_pool, v_pool,
                      page_table_row, write_pages, write_offsets):
        import jax.numpy as jnp

        from ..ops import pallas_kernels

        c = tokens.shape[0]
        h, kh, d = self.num_heads, self.num_kv_heads, self.head_dim
        positions = start.astype(jnp.int32) + jnp.arange(c, dtype=jnp.int32)
        x = params["embed"][tokens] + self._pe(positions)
        for li, layer in enumerate(params["layers"]):
            hx = self._norm(x, layer["ln1"])
            q = (hx @ layer["wq"]).reshape(c, h, d)
            k = (hx @ layer["wk"]).reshape(c, kh, d)
            v = (hx @ layer["wv"]).reshape(c, kh, d)
            # scatter FIRST so in-chunk positions read their own K/V back
            # through the pages like every earlier chunk's (already-cached
            # positions write to the null page — their KV is in the
            # shared/CoW pages, this pass only recomputes activations)
            k_pool, v_pool = write_kv(k_pool, v_pool, li, k, v,
                                      write_pages, write_offsets)
            att = pallas_kernels.paged_prefill_attention(
                q, k_pool[li], v_pool[li], page_table_row, start, length,
                scale=self.scale)
            x = x + att.reshape(c, h * d) @ layer["wo"]
            x = x + self._mlp(self._norm(x, layer["ln2"]), layer)
        logits = self._norm(x, params["lnf"]) @ params["unembed"]
        return logits[length - 1], k_pool, v_pool

    def decode(self, params, tokens, positions, k_pool, v_pool,
               page_tables, seq_lens, write_pages, write_offsets):
        from ..ops import pallas_kernels

        s = tokens.shape[0]
        # the per-slot query width (1 = classic tick, K+1 = speculative
        # verify tick) falls out of trace-time shapes — the contract's
        # operands widen, the signature doesn't
        w = s // page_tables.shape[0]
        h, kh, d = self.num_heads, self.num_kv_heads, self.head_dim
        x = params["embed"][tokens] + self._pe(positions)
        for li, layer in enumerate(params["layers"]):
            hx = self._norm(x, layer["ln1"])
            q = (hx @ layer["wq"]).reshape(s, h, d)
            k = (hx @ layer["wk"]).reshape(s, kh, d)
            v = (hx @ layer["wv"]).reshape(s, kh, d)
            k_pool, v_pool = write_kv(k_pool, v_pool, li, k, v,
                                      write_pages, write_offsets)
            if w > 1:
                att = pallas_kernels.paged_spec_attention(
                    q, k_pool[li], v_pool[li], page_tables, seq_lens,
                    scale=self.scale)
            else:
                att = pallas_kernels.paged_attention(
                    q, k_pool[li], v_pool[li], page_tables, seq_lens,
                    scale=self.scale)
            x = x + att.reshape(s, h * d) @ layer["wo"]
            x = x + self._mlp(self._norm(x, layer["ln2"]), layer)
        logits = self._norm(x, params["lnf"]) @ params["unembed"]
        return logits, k_pool, v_pool

    # -- oracle ---------------------------------------------------------
    def reference_generate(self, params, prompt, max_new_tokens,
                           eos_id=None):
        """No-cache greedy decode: re-runs the full dense forward per
        token. O(T^2) per token — the correctness oracle the engine's
        paged path is tested against, never a serving path."""
        import jax.numpy as jnp

        toks = [int(t) for t in np.asarray(prompt).ravel()]
        out: List[int] = []
        for _ in range(int(max_new_tokens)):
            arr = jnp.asarray(np.asarray(toks, np.int32))
            t = arr.shape[0]
            h, kh, d = self.num_heads, self.num_kv_heads, self.head_dim
            positions = jnp.arange(t, dtype=jnp.int32)
            x = params["embed"][arr] + self._pe(positions)
            for layer in params["layers"]:
                hx = self._norm(x, layer["ln1"])
                q = (hx @ layer["wq"]).reshape(t, h, d)
                k = (hx @ layer["wk"]).reshape(t, kh, d)
                v = (hx @ layer["wv"]).reshape(t, kh, d)
                att = self._dense_causal(q, k, v, self.scale)
                x = x + att.reshape(t, h * d) @ layer["wo"]
                x = x + self._mlp(self._norm(x, layer["ln2"]), layer)
            logits = self._norm(x, params["lnf"]) @ params["unembed"]
            # the batched-fetch idiom even for one value: the transfer is
            # explicit, and greedy decode is inherently per-token (the
            # fetched token IS the next input)
            nxt = int(fetch_host([jnp.argmax(logits[-1])])[0])  # tpulint: disable=decode-host-sync -- correctness oracle, never a serving path; per-token fetch is the point
            out.append(nxt)
            toks.append(nxt)
            if eos_id is not None and nxt == eos_id:
                break
        return np.asarray(out, np.int32)
