"""FleetRouter — N decode replicas behind one DecodeEngine-shaped door.

One :class:`~mxnet_tpu.serving.decode.DecodeEngine` tops out at its slot
count; the next unit of scale is a *fleet* of process-local replicas.
The router keeps the single-engine surface (``submit()`` → Future,
``stats()``, ``close(drain=)``) so callers cannot tell a fleet from one
engine, and adds exactly the mechanics a fleet needs:

**Prefix-affinity placement.** A replica's prefix cache only pays off if
requests sharing a prefix land on the SAME replica — random spraying
divides every shared prefix's hit rate by N. The router hashes each
prompt's leading page-aligned chunks with the prefix cache's own rolling
chain hash (:func:`~mxnet_tpu.serving.kvcache._chain_key` — byte-for-byte
the keys the replica's index will hold) and keeps a bounded
prefix→replica map: the deepest known chunk wins, so a fleet's hit ratio
tracks a single replica's. Cold prefixes place by rendezvous (highest-
random-weight) hashing over live replicas — deterministic, no
coordination, minimal reshuffling when membership changes.

**Tenant-aware spillover.** Affinity is a preference, not a pin: when the
affine replica sheds (queue full, tenant breaker) or is already loaded
past ``MXNET_FLEET_SPILL_DEPTH`` in-flight requests, the router spills to
the live replica carrying the least of THIS tenant's traffic (then least
total) — per-tenant weights, budgets and breakers keep holding fleet-wide
because every replica runs the same tenancy config and the spill order
follows the tenant's own footprint.

**Replica lifecycle.** ``add_replica()`` / ``drain_replica()`` ride the
engine's own ``close(drain=True)`` (which reports how many requests
finished during the drain), and ``rolling_swap()`` upgrades weights one
replica at a time so a bad artifact is caught after 1/N of the fleet,
with zero dropped requests end to end.

**Failure containment.** Each replica sits behind its own
:class:`~mxnet_tpu.resilience.breaker.CircuitBreaker` (site
``serving.fleet.<fleet>.replica.<i>``), one level above the engine's
internal breaker. When a replica dies (``kill_replica``, or the chaos
site ``serving.fleet.replica.<i>``), its in-flight requests fail inside
the engine, and each failure's done-callback re-routes the request
through the router — dedup-guarded by the router-owned caller Future, so
a request can never complete twice — while the dead replica's index
entries are tombstoned and a daemon thread rebuilds the replica.

**SLO-driven autoscaling.** ``autoscale_tick()`` (optionally a background
loop via ``MXNET_FLEET_AUTOSCALE_S``) reads the telemetry SLO engine:
a firing ``QueueDepthBurn`` on any replica spawns one (up to
``MXNET_FLEET_MAX_REPLICAS``); sustained occupancy collapse across every
replica drains the coldest. Every decision lands in the flight recorder
(``fleet.scale``).

Lock discipline (the tpulint contract): the router owns ONE plain lock
guarding its maps and counters. Engine calls — ``submit``, ``close``,
``swap_params``, ``stats``, anything that takes the engine's condition
variable or joins a thread — happen strictly OUTSIDE it. The engine
resolves request futures off its own lock, so done-callbacks may take
the router lock without forming a cycle. Replica leases (the
``replica-lease`` protocol row) are acquired when a request routes and
released on its terminal — or transferred when it re-routes.

The router registers a ``fleet`` view on ``/debug/state``
(:func:`~mxnet_tpu.telemetry.httpd.register_debug_view`): per-replica
breaker state, queue depth, pages in use, and the last scale event.
"""
from __future__ import annotations

import hashlib
import itertools
import threading
import time
import weakref
from collections import OrderedDict
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..telemetry import flightrec as _flightrec
from ..telemetry import httpd as _httpd
from ..telemetry import slo as _slo
from ..telemetry import tracing as _tracing
from ..base import MXNetError, get_env
from ..resilience import CircuitBreaker, chaos
from .batcher import (EngineUnavailableError, QueueFullError,
                      RequestTimeoutError, ServerClosedError)
from .decode import DecodeEngine
from .kvcache import _chain_key
from .tenancy import (DEFAULT_TENANT, TenantUnavailableError,
                      aggregate_snapshots)

__all__ = ["FleetRouter", "fleet_debug_state"]

_F_REPLICAS = telemetry.gauge(
    "mxnet_fleet_replicas",
    "live replicas behind the fleet router",
    labels=("fleet",))
_F_ROUTED = telemetry.counter(
    "mxnet_fleet_routed_total",
    "routing decisions: affine (prefix-index hit), rendezvous (cold "
    "placement), spill (affinity overridden by load/shed)",
    labels=("fleet", "decision"))
_F_RESUBMITS = telemetry.counter(
    "mxnet_fleet_resubmits_total",
    "requests re-routed off a dead replica (each re-routed request "
    "still completes exactly once)",
    labels=("fleet",))
_F_SCALE = telemetry.counter(
    "mxnet_fleet_scale_events_total",
    "autoscaler decisions (action=up|down)",
    labels=("fleet", "action"))
_F_IMBALANCE = telemetry.gauge(
    "mxnet_fleet_load_imbalance",
    "max/mean in-flight requests over live replicas (1.0 = perfectly "
    "balanced; FleetImbalanceBurn watches this)",
    labels=("fleet",))

_FLEET_SEQ = itertools.count(1)


def _rendezvous_score(key: bytes, name: str) -> int:
    return int.from_bytes(
        hashlib.sha1(key + name.encode("utf-8")).digest()[:8], "big")


class _FleetRequest:
    """One caller request: the router-owned Future plus everything needed
    to (re-)route it. The caller's Future is resolved exactly once —
    every resolution site checks ``done()`` first, and the fleet trace's
    idempotent terminal is the audit trail."""

    __slots__ = ("rid", "prompt", "max_new", "eos_id", "tenant",
                 "tenant_id", "keys", "deadline", "timeout_disabled",
                 "future", "trace", "attempts", "tried", "t0", "replica")

    _RID = itertools.count(1)

    def __init__(self, prompt: np.ndarray, max_new: int,
                 eos_id: Optional[int], tenant: Optional[str],
                 tenant_id: str):
        self.rid = next(self._RID)
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.tenant = tenant
        self.tenant_id = tenant_id
        self.keys: List[bytes] = []
        self.deadline: Optional[float] = None
        self.timeout_disabled = False
        self.future: Future = Future()
        self.trace = None
        self.attempts = 0
        self.tried: set = set()
        self.t0 = time.perf_counter()
        self.replica: Optional[int] = None

    def remaining_ms(self) -> Optional[float]:
        """Per-attempt engine deadline: the ORIGINAL deadline's remaining
        budget, so re-routes don't reset the caller's clock."""
        if self.timeout_disabled:
            return 0.0
        if self.deadline is None:
            return None
        return max(1.0, (self.deadline - time.perf_counter()) * 1e3)


class _Replica:
    """Router-side record of one engine: its state machine (live →
    draining|dead → restarting → live), breaker, and the lease
    bookkeeping behind spillover and imbalance tracking.

    Lease methods are called with the router lock HELD (they touch
    shared maps); they never call into the engine."""

    __slots__ = ("index", "name", "engine", "state", "breaker", "routed",
                 "deaths", "inflight", "tenant_inflight", "__weakref__")

    def __init__(self, index: int, name: str, engine: DecodeEngine,
                 breaker: CircuitBreaker):
        self.index = index
        self.name = name
        self.engine = engine
        self.state = "live"
        self.breaker = breaker
        self.routed = 0
        self.deaths = 0
        self.inflight: Dict[int, _FleetRequest] = {}
        self.tenant_inflight: Dict[str, int] = {}

    def acquire_lease(self, fr: _FleetRequest) -> None:
        """Route-time: the request now occupies one of this replica's
        slots/queue entries (router's view)."""
        self.inflight[fr.rid] = fr
        self.tenant_inflight[fr.tenant_id] = \
            self.tenant_inflight.get(fr.tenant_id, 0) + 1
        self.routed += 1
        fr.replica = self.index

    def release_lease(self, fr: _FleetRequest) -> None:
        """Terminal: the request left this replica (completed, failed, or
        was rejected at its door). Idempotent."""
        if self.inflight.pop(fr.rid, None) is None:
            return
        n = self.tenant_inflight.get(fr.tenant_id, 0) - 1
        if n > 0:
            self.tenant_inflight[fr.tenant_id] = n
        else:
            self.tenant_inflight.pop(fr.tenant_id, None)

    def transfer_lease(self, fr: _FleetRequest) -> None:
        """Re-route: the lease leaves WITH the request (released here,
        re-acquired on whichever replica the router picks next)."""
        self.release_lease(fr)


# every live router, for the /debug/state "fleet" view — weak so a
# dropped router disappears from the view without an unregister call
_ROUTERS: "weakref.WeakValueDictionary[str, FleetRouter]" = \
    weakref.WeakValueDictionary()


def fleet_debug_state() -> Dict[str, dict]:
    """The ``fleet`` key of ``/debug/state``: every live router's
    :meth:`FleetRouter.debug_state`, keyed by fleet name."""
    out = {}
    for name, router in sorted(_ROUTERS.items()):
        try:
            out[name] = router.debug_state()
        except Exception as exc:  # noqa: BLE001 - one wedged fleet must
            # not blank the debug view for the others
            out[name] = {"error": repr(exc)}
    return out


_httpd.register_debug_view("fleet", fleet_debug_state)


class FleetRouter:
    """M process-local :class:`DecodeEngine` replicas behind the
    single-engine surface. See the module docstring for the design.

    ``factory(name)`` must return a fresh, independently-warmed-up-able
    ``DecodeEngine`` named ``name`` — the router calls it at
    construction (``replicas`` times), on ``add_replica()``, and when
    rebuilding a dead replica. Replicas must NOT share tenancy
    registries or caches (each engine owns its own).
    """

    def __init__(self, factory: Callable[[str], DecodeEngine],
                 replicas: Optional[int] = None,
                 name: Optional[str] = None,
                 max_replicas: Optional[int] = None,
                 min_replicas: Optional[int] = None):
        if replicas is None:
            replicas = get_env("MXNET_FLEET_REPLICAS", 1, int, cache=False)
        replicas = max(1, int(replicas))
        self._name = name or ("fleet%d" % next(_FLEET_SEQ))
        self._factory = factory
        self._lock = threading.Lock()
        self._closed = False
        self._replicas: List[_Replica] = []
        self._next_index = 0
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self._index_cap = max(
            256, get_env("MXNET_FLEET_INDEX_CAP", 65536, int, cache=False))
        self._affinity_pages = max(
            1, get_env("MXNET_FLEET_AFFINITY_PAGES", 8, int, cache=False))
        self._max_reroutes = max(
            0, get_env("MXNET_FLEET_MAX_REROUTES", 3, int, cache=False))
        self._breaker_threshold = max(
            1, get_env("MXNET_FLEET_BREAKER_THRESHOLD", 1, int, cache=False))
        self._breaker_reset_s = get_env(
            "MXNET_FLEET_BREAKER_RESET_S", 5.0, float, cache=False)
        self._cooldown_s = get_env(
            "MXNET_FLEET_SCALE_COOLDOWN_S", 30.0, float, cache=False)
        self._down_occ = get_env(
            "MXNET_FLEET_SCALE_DOWN_OCC", 0.1, float, cache=False)
        self._down_window_s = get_env(
            "MXNET_FLEET_SCALE_DOWN_WINDOW_S", 60.0, float, cache=False)
        if min_replicas is None:
            min_replicas = get_env("MXNET_FLEET_MIN_REPLICAS", 1, int,
                                   cache=False)
        self._min_replicas = max(1, int(min_replicas))
        if max_replicas is None:
            max_replicas = get_env("MXNET_FLEET_MAX_REPLICAS", 0, int,
                                   cache=False)
        # 0 = "whatever the fleet started with": scale-UP is opt-in
        self._max_replicas = int(max_replicas) if max_replicas else replicas
        self._variants: Dict[str, object] = {}
        # per-tenant speculative draft caps, re-applied to every replica
        # a restart or scale-up builds (mirrors the variant store)
        self._spec_overrides: Dict[str, Optional[int]] = {}
        self._last_scale: Optional[dict] = None
        self._last_scale_t = -float("inf")
        self._restarts: List[threading.Thread] = []
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._resubmitted = 0
        for _ in range(replicas):
            self._replicas.append(self._build_replica())
        first = self._replicas[0].engine
        self._page_size = int(first.page_size)
        spill = get_env("MXNET_FLEET_SPILL_DEPTH", 0, int, cache=False)
        # auto: twice the slot count — past that the affine replica's
        # queue is deep enough that a cold prefill elsewhere wins
        self._spill_depth = int(spill) if spill > 0 else 2 * first.num_slots
        _F_REPLICAS.set(float(len(self._replicas)), fleet=self._name)
        _ROUTERS[self._name] = self
        self._stop_autoscale = threading.Event()
        self._autoscale_thread: Optional[threading.Thread] = None
        autoscale_s = get_env("MXNET_FLEET_AUTOSCALE_S", 0.0, float,
                              cache=False)
        if autoscale_s > 0:
            self._autoscale_thread = threading.Thread(
                target=self._autoscale_loop, args=(autoscale_s,),
                name="mxnet-fleet-autoscale-%s" % self._name, daemon=True)
            self._autoscale_thread.start()

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    def _build_replica(self) -> _Replica:
        """Build replica #next via the factory — NOT yet in the routing
        set (the caller appends under the lock once it's ready). The
        factory itself runs lock-free: it compiles."""
        with self._lock:
            index = self._next_index
            self._next_index += 1
            variants = list(self._variants.items())
            spec_caps = list(self._spec_overrides.items())
        rname = "%s.r%d" % (self._name, index)
        engine = self._factory(rname)
        for vname, vparams in variants:
            engine.register_variant(vname, vparams)
        for tid, cap in spec_caps:
            engine.set_tenant_spec_k(tid, cap)
        breaker = CircuitBreaker(
            "serving.%s.replica.%d" % (self._name, index),
            failure_threshold=self._breaker_threshold,
            reset_timeout_s=self._breaker_reset_s)
        return _Replica(index, rname, engine, breaker)

    @property
    def name(self) -> str:
        return self._name

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _prefix_keys(self, arr: np.ndarray) -> List[bytes]:
        """The prompt's leading page-aligned chunk keys — the SAME rolling
        chain the replica prefix caches index by, capped at
        ``MXNET_FLEET_AFFINITY_PAGES`` chunks (placement needs the head
        of the prefix, not the whole prompt)."""
        ps = self._page_size
        n = min(arr.size // ps, self._affinity_pages)
        keys: List[bytes] = []
        parent = b""
        for c in range(n):
            parent = _chain_key(parent, arr[c * ps:(c + 1) * ps])
            keys.append(parent)
        if not keys:
            # sub-page prompt: no shareable pages, but a whole-prompt
            # digest still makes repeat placement deterministic
            keys.append(_chain_key(b"", arr))
        return keys

    def _routable_locked(self, fr: _FleetRequest) -> List[_Replica]:
        return [r for r in self._replicas
                if r.state == "live" and r.index not in fr.tried
                and r.breaker.state != "open"]

    def _pick_replica_locked(self, fr: _FleetRequest):
        """Choose a replica (and acquire its lease) under the router
        lock. Returns ``(replica, decision)`` or ``(None, None)`` when
        every live replica has been tried or is breaker-open."""
        live = self._routable_locked(fr)
        if not live:
            return None, None
        rep = None
        decision = "affine"
        for key in reversed(fr.keys):  # deepest known chunk wins
            idx = self._index.get(key)
            if idx is None:
                continue
            rep = next((r for r in live if r.index == idx), None)
            if rep is not None:
                break
        if rep is None:
            decision = "rendezvous"
            rep = max(live, key=lambda r: _rendezvous_score(fr.keys[0],
                                                            r.name))
        if len(rep.inflight) >= self._spill_depth and len(live) > 1:
            # tenant-aware spillover: least of THIS tenant's in-flight
            # traffic first, then least total — weights/budgets keep
            # holding fleet-wide because the spill follows the tenant
            decision = "spill"
            rep = min(live, key=lambda r: (
                r.tenant_inflight.get(fr.tenant_id, 0),
                len(r.inflight), r.index))
        rep.acquire_lease(fr)
        fr.attempts += 1
        fr.tried.add(rep.index)
        self._update_imbalance_locked()
        return rep, decision

    def _update_imbalance_locked(self) -> None:
        counts = [len(r.inflight) for r in self._replicas
                  if r.state == "live"]
        if not counts or sum(counts) == 0:
            val = 1.0
        else:
            val = max(counts) / (sum(counts) / float(len(counts)))
        _F_IMBALANCE.set(val, fleet=self._name)

    def _upsert_index_locked(self, keys: List[bytes], index: int) -> None:
        for key in keys:
            self._index[key] = index
            self._index.move_to_end(key)
        while len(self._index) > self._index_cap:
            self._index.popitem(last=False)

    def _tombstone_locked(self, index: int) -> int:
        """Drop every index entry pointing at a dead/drained replica —
        its pages are gone; affinity to it would be pure miss."""
        stale = [k for k, v in self._index.items() if v == index]
        for k in stale:
            del self._index[k]
        return len(stale)

    # ------------------------------------------------------------------
    # submit path
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Single-engine surface: enqueue one sequence on SOME replica;
        returns a Future resolving to the generated ``np.int32`` token
        ids. Thread-safe. Same rejection semantics as
        :meth:`DecodeEngine.submit` — a request every replica sheds
        raises, with the last replica's reason."""
        arr = np.asarray(prompt, np.int32).ravel()
        if arr.size < 1:
            raise MXNetError("fleet submit needs >= 1 prompt token")
        tid = str(tenant) if tenant is not None else DEFAULT_TENANT
        fr = _FleetRequest(arr, int(max_new_tokens), eos_id, tenant, tid)
        if timeout_ms is not None:
            if float(timeout_ms) <= 0:
                fr.timeout_disabled = True
            else:
                fr.deadline = time.perf_counter() + float(timeout_ms) / 1e3
        fr.keys = self._prefix_keys(arr)
        fr.trace = _tracing.start_trace("fleet", self._name, tid)
        _tracing.event(fr.trace, "submit", prompt_tokens=int(arr.size),
                       max_new=fr.max_new, rid=fr.rid)
        with self._lock:
            if self._closed:
                _tracing.finish(fr.trace, "rejected", reason="closed")
                raise ServerClosedError("submit() on a closed FleetRouter")
            self._submitted += 1
        self._route_and_submit(fr, sync=True)
        return fr.future

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None) -> np.ndarray:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, max_new_tokens, eos_id=eos_id,
                           tenant=tenant).result(timeout)

    def _route_and_submit(self, fr: _FleetRequest, sync: bool) -> None:
        """Route ``fr`` to a replica and hand it to that engine. Spills
        to the next candidate on door-rejects; exhausting every live
        replica fails the request with the last reason. ``sync`` raises
        (submit-path) instead of failing the caller Future (re-route
        path). Never called with the router lock held."""
        last_exc: Optional[Exception] = None
        while True:
            if fr.future.done():
                return  # dedup guard: the request already resolved
            if fr.deadline is not None and \
                    time.perf_counter() > fr.deadline:
                self._finish_error(
                    fr, RequestTimeoutError(
                        "deadline expired while routing (after %d attempts)"
                        % fr.attempts), sync)
                return
            with self._lock:
                if self._closed:
                    rep = None
                    last_exc = ServerClosedError(
                        "FleetRouter closed while routing")
                else:
                    rep, decision = self._pick_replica_locked(fr)
            if rep is None:
                exc = last_exc or EngineUnavailableError(
                    "no live replica admits the request "
                    "(every breaker open or replica tried)")
                self._finish_error(fr, exc, sync)
                return
            try:
                # the chaos site that kills replica <i> at routing time —
                # the acceptance drill for failure containment
                chaos.maybe_fail("serving.fleet.replica.%d" % rep.index)
            except Exception as exc:  # noqa: BLE001 - any injected fault
                # means "this replica just died": contain and re-route
                with self._lock:
                    rep.transfer_lease(fr)
                self._kill_replica(rep, exc)
                last_exc = exc
                continue
            try:
                sub = rep.engine.submit(
                    fr.prompt, fr.max_new, eos_id=fr.eos_id,
                    timeout_ms=fr.remaining_ms(), tenant=fr.tenant)
            except (QueueFullError, TenantUnavailableError,
                    ServerClosedError) as exc:
                # door-reject: this replica sheds, the next may not —
                # spillover continues through the remaining candidates
                with self._lock:
                    rep.release_lease(fr)
                    self._update_imbalance_locked()
                last_exc = exc
                continue
            except Exception as exc:  # noqa: BLE001 - validation and
                # everything else is request-shaped, identical on every
                # replica: propagate, don't spin through the fleet
                with self._lock:
                    rep.release_lease(fr)
                    self._update_imbalance_locked()
                self._finish_error(fr, exc, sync)
                return
            _F_ROUTED.inc(fleet=self._name, decision=decision)
            _tracing.event(fr.trace, "replica_route", replica=rep.name,
                           decision=decision, attempt=fr.attempts)
            with self._lock:
                self._upsert_index_locked(fr.keys, rep.index)
            sub.add_done_callback(
                lambda f, fr=fr, rep=rep: self._on_replica_done(fr, rep, f))
            return

    def _on_replica_done(self, fr: _FleetRequest, rep: _Replica,
                         sub: Future) -> None:
        """Replica future resolved. Runs on the engine worker (or the
        killer thread) with NO engine lock held — taking the router lock
        here is acyclic by construction."""
        exc = None if sub.cancelled() else sub.exception()
        if exc is None:
            with self._lock:
                rep.release_lease(fr)
                self._update_imbalance_locked()
            rep.breaker.on_success()
            self._finish_ok(fr, rep, sub.result())
            return
        with self._lock:
            reroute = (isinstance(exc, ServerClosedError)
                       and rep.state != "live" and not self._closed
                       and fr.attempts <= self._max_reroutes
                       and not fr.future.done())
            if reroute:
                rep.transfer_lease(fr)
                fr.tried.clear()  # new round: every live replica eligible
                self._resubmitted += 1
            else:
                rep.release_lease(fr)
            self._update_imbalance_locked()
        if reroute:
            _F_RESUBMITS.inc(fleet=self._name)
            _tracing.event(fr.trace, "resubmit", from_replica=rep.name,
                           error=type(exc).__name__)
            self._route_and_submit(fr, sync=False)
        else:
            self._finish_error(fr, exc, sync=False)

    def _finish_ok(self, fr: _FleetRequest, rep: _Replica, tokens) -> None:
        _tracing.finish(
            fr.trace, "complete", replica=rep.name, attempts=fr.attempts,
            tokens=int(np.asarray(tokens).size),
            latency_ms=round((time.perf_counter() - fr.t0) * 1e3, 3))
        if fr.future.done():
            return
        if fr.future.set_running_or_notify_cancel():
            with self._lock:
                self._completed += 1
            fr.future.set_result(tokens)

    def _finish_error(self, fr: _FleetRequest, exc: Exception,
                      sync: bool) -> None:
        if isinstance(exc, (QueueFullError, TenantUnavailableError,
                            EngineUnavailableError)):
            kind = "shed"
        elif isinstance(exc, RequestTimeoutError):
            kind = "timeout"
        else:
            kind = "error"
        _tracing.finish(fr.trace, kind, error=type(exc).__name__,
                        attempts=fr.attempts)
        with self._lock:
            self._failed += 1
        if sync:
            raise exc
        if fr.future.done():
            return
        if fr.future.set_running_or_notify_cancel():
            fr.future.set_exception(exc)

    # ------------------------------------------------------------------
    # replica lifecycle
    # ------------------------------------------------------------------
    def _resolve_replica(self, which) -> _Replica:
        with self._lock:
            for rep in self._replicas:
                if rep.index == which or rep.name == which:
                    return rep
        raise MXNetError("fleet %r has no replica %r" % (self._name, which))

    def add_replica(self, warmup: bool = True) -> str:
        """Spawn (and by default warm up) one more replica; returns its
        name. The new replica takes traffic as soon as it is appended —
        cold prefixes rendezvous onto it, warm ones stay put."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("add_replica() on a closed fleet")
        rep = self._build_replica()
        if warmup:
            rep.engine.warmup()
        stale = False
        with self._lock:
            if self._closed:
                stale = True
            else:
                self._replicas.append(rep)
            n = len([r for r in self._replicas if r.state == "live"])
        if stale:
            rep.engine.close(drain=False)
            raise ServerClosedError("fleet closed while adding a replica")
        _F_REPLICAS.set(float(n), fleet=self._name)
        _flightrec.record("fleet.replica_added", fleet=self._name,
                          replica=rep.name, live=n)
        return rep.name

    def drain_replica(self, which, timeout: Optional[float] = None) -> int:
        """Gracefully retire one replica: stop routing to it, let its
        queued + in-flight requests finish (``close(drain=True)``), then
        drop it from the fleet. Returns the number of requests that
        completed during the drain — the zero-drop receipt."""
        rep = self._resolve_replica(which)
        with self._lock:
            if rep.state != "live":
                raise MXNetError("replica %s is %s, not live"
                                 % (rep.name, rep.state))
            rep.state = "draining"
            tombstoned = self._tombstone_locked(rep.index)
        drained = rep.engine.close(drain=True, timeout=timeout)
        with self._lock:
            rep.state = "drained"
            self._replicas.remove(rep)
            n = len([r for r in self._replicas if r.state == "live"])
        _F_REPLICAS.set(float(n), fleet=self._name)
        _flightrec.record("fleet.replica_drained", fleet=self._name,
                          replica=rep.name, drained_completed=drained,
                          tombstoned=tombstoned, live=n)
        return drained

    def kill_replica(self, which, restart: bool = True,
                     exc: Optional[Exception] = None) -> None:
        """Abruptly kill one replica (the failure-containment drill the
        chaos site automates): its in-flight requests re-route through
        the router, its breaker opens, its index entries tombstone, and
        (by default) a daemon thread rebuilds it."""
        rep = self._resolve_replica(which)
        self._kill_replica(
            rep, exc or MXNetError("replica %s killed" % rep.name),
            restart=restart)

    def _kill_replica(self, rep: _Replica, exc: Exception,
                      restart: bool = True) -> None:
        with self._lock:
            if rep.state != "live":
                return  # racing kills: first one wins
            rep.state = "dead"
            rep.deaths += 1
            tombstoned = self._tombstone_locked(rep.index)
            inflight = len(rep.inflight)
            restart = restart and not self._closed
        rep.breaker.on_failure()  # threshold 1 → open: routing skips it
        _flightrec.record("fleet.replica_dead", fleet=self._name,
                          replica=rep.name, error=repr(exc),
                          inflight=inflight, tombstoned=tombstoned,
                          restarting=restart)
        # fail-fast close: every queued/slotted future fails with
        # ServerClosedError on THIS thread; each failure's done-callback
        # re-routes its request (dedup-guarded) before close() returns
        rep.engine.close(drain=False)
        if restart:
            t = threading.Thread(
                target=self._restart_replica, args=(rep,),
                name="mxnet-fleet-restart-%s" % rep.name, daemon=True)
            with self._lock:
                self._restarts.append(t)
            t.start()

    def _restart_replica(self, rep: _Replica) -> None:
        with self._lock:
            if self._closed:
                return
            rep.state = "restarting"
            variants = list(self._variants.items())
            spec_caps = list(self._spec_overrides.items())
        try:
            engine = self._factory(rep.name)
            for vname, vparams in variants:
                engine.register_variant(vname, vparams)
            for tid, cap in spec_caps:
                engine.set_tenant_spec_k(tid, cap)
            engine.warmup()
        except Exception as exc:  # noqa: BLE001 - a replica that cannot
            # be rebuilt stays failed; the rest of the fleet carries on
            with self._lock:
                rep.state = "failed"
            _flightrec.record("fleet.restart_failed", fleet=self._name,
                              replica=rep.name, error=repr(exc))
            return
        stale = None
        with self._lock:
            if self._closed:
                stale = engine
            else:
                rep.engine = engine
                rep.state = "live"
        if stale is not None:
            stale.close(drain=False)
            return
        rep.breaker.on_success()  # probe passed: close the breaker
        _flightrec.record("fleet.replica_restarted", fleet=self._name,
                          replica=rep.name, deaths=rep.deaths)

    def register_variant(self, name: str, params) -> None:
        """Stage a named weight set on every replica (current AND future
        — restarts and scale-ups re-register it), for
        :meth:`rolling_swap` by variant name."""
        with self._lock:
            self._variants[str(name)] = params
            reps = [r for r in self._replicas if r.state == "live"]
        for rep in reps:
            rep.engine.register_variant(name, params)

    def configure_speculation(self, tenant_id: str,
                              spec_k: Optional[int]) -> None:
        """Set (or clear, with ``None``) one tenant's speculative draft
        cap fleet-wide: applied to every live replica now and re-applied
        to every replica a restart or scale-up builds — the lever that
        stops one slow-accepting tenant burning every replica's tick
        budget on rejected verify rows. Caps only lower the engines'
        compiled ``spec_k``; no replica recompiles."""
        with self._lock:
            self._spec_overrides[str(tenant_id)] = spec_k
            reps = [r for r in self._replicas if r.state == "live"]
        for rep in reps:
            rep.engine.set_tenant_spec_k(tenant_id, spec_k)

    def rolling_swap(self, params=None, variant: Optional[str] = None,
                     timeout: Optional[float] = None) -> int:
        """Upgrade weights one replica at a time — each swap applies at
        that replica's next tick boundary with zero dropped requests and
        zero recompiles (the engine's live-swap contract), so a bad
        artifact is caught after 1/N of the fleet. Pass ``params`` (with
        an optional ``variant`` label) or just ``variant`` to promote a
        :meth:`register_variant` set. Returns replicas swapped."""
        if params is None and variant is None:
            raise MXNetError("rolling_swap needs params or a variant name")
        with self._lock:
            reps = [r for r in self._replicas if r.state == "live"]
        swapped = 0
        for rep in reps:
            with self._lock:
                if rep.state != "live":
                    continue
            if params is not None:
                rep.engine.swap_params(params, variant=variant, wait=True,
                                       timeout=timeout)
            else:
                rep.engine.use_variant(variant, wait=True, timeout=timeout)
            swapped += 1
            _flightrec.record("fleet.rolling_swap_step", fleet=self._name,
                              replica=rep.name, variant=variant,
                              step=swapped, of=len(reps))
        return swapped

    def warmup(self) -> int:
        """Compile every replica's ladder; returns total compiles."""
        with self._lock:
            reps = [r for r in self._replicas if r.state == "live"]
        return sum(rep.engine.warmup() for rep in reps)

    # ------------------------------------------------------------------
    # autoscaling
    # ------------------------------------------------------------------
    def autoscale_tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One control-loop step against the SLO engine: a firing
        ``QueueDepthBurn`` on any replica spawns one (up to the max);
        occupancy collapse across EVERY live replica (window mean below
        ``MXNET_FLEET_SCALE_DOWN_OCC``) drains the coldest. Returns the
        scale event (also flight-recorded), or None."""
        with self._lock:
            if self._closed:
                return None
        alerts = _slo.evaluate()
        if now is None:
            now = time.monotonic()
        with self._lock:
            if now - self._last_scale_t < self._cooldown_s:
                return None
            live = [r for r in self._replicas if r.state == "live"]
            names = {r.name for r in live}
            # dead/restarting replicas still count toward capacity: a
            # restart in flight IS the scale-up for that deficit
            occupied = len([r for r in self._replicas
                            if r.state in ("live", "dead", "restarting")])
        burning = sorted({a["instance"] for a in alerts
                          if a["alert"] == "QueueDepthBurn"
                          and a["instance"] in names})
        event = None
        if burning and occupied < self._max_replicas:
            added = self.add_replica()
            event = {"action": "up", "replica": added,
                     "reason": "QueueDepthBurn", "instances": burning}
        elif len(live) > self._min_replicas:
            eng = _slo.engine()
            occs = [(eng.mean("mxnet_decode_slot_occupancy", r.name,
                              self._down_window_s), r) for r in live]
            known = [(v, r) for v, r in occs if v is not None]
            if len(known) == len(live) and \
                    all(v < self._down_occ for v, _ in known):
                coldest = min(known, key=lambda t: t[0])[1]
                drained = self.drain_replica(coldest.index)
                event = {"action": "down", "replica": coldest.name,
                         "reason": "occupancy_collapse",
                         "drained_completed": drained}
        if event is not None:
            with self._lock:
                self._last_scale_t = now
                self._last_scale = dict(event)
            _F_SCALE.inc(fleet=self._name, action=event["action"])
            _flightrec.record("fleet.scale", fleet=self._name, **event)
        return event

    def _autoscale_loop(self, interval: float) -> None:
        while not self._stop_autoscale.wait(interval):
            with self._lock:
                if self._closed:
                    return
            try:
                self.autoscale_tick()
            except Exception as exc:  # noqa: BLE001 - the control loop
                # must outlive one bad tick
                _flightrec.record("fleet.autoscale_error",
                                  fleet=self._name, error=repr(exc))

    # ------------------------------------------------------------------
    # observation
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        """Single-engine surface: fleet-aggregated counters plus each
        replica's full ``DecodeEngine.stats()`` under ``replicas``.
        ``tenants`` is the fleet-wide per-tenant merge
        (:func:`~mxnet_tpu.serving.tenancy.aggregate_snapshots`)."""
        with self._lock:
            reps = list(self._replicas)
            doc = {
                "fleet": self._name,
                "replicas_live": len([r for r in reps
                                      if r.state == "live"]),
                "router": {
                    "submitted": self._submitted,
                    "completed": self._completed,
                    "failed": self._failed,
                    "resubmitted": self._resubmitted,
                    "index_entries": len(self._index),
                    "last_scale": (dict(self._last_scale)
                                   if self._last_scale else None),
                },
            }
        per: Dict[str, dict] = {}
        for rep in reps:
            if rep.state != "live" or rep.engine.closed:
                continue
            try:
                per[rep.name] = rep.engine.stats()
            except Exception as exc:  # noqa: BLE001 - a replica mid-
                # teardown must not fail the fleet-wide read
                per[rep.name] = {"error": repr(exc)}
        good = [s for s in per.values() if "error" not in s]
        hits = sum(s["kvcache"].get("prefix_hits", 0) for s in good)
        misses = sum(s["kvcache"].get("prefix_misses", 0) for s in good)
        doc["replicas"] = per
        doc["queued"] = sum(s.get("queued", 0) for s in good)
        doc["active_slots"] = sum(s.get("active_slots", 0) for s in good)
        doc["slots"] = sum(s.get("slots", 0) for s in good)
        doc["tokens_generated"] = sum(s.get("tokens_generated", 0)
                                      for s in good)
        doc["completed"] = sum(s.get("completed", 0) for s in good)
        doc["steady_state_recompiles"] = sum(
            s.get("steady_state_recompiles", 0) for s in good)
        doc["prefix_hits"] = hits
        doc["prefix_misses"] = misses
        doc["prefix_hit_ratio"] = (hits / (hits + misses)
                                   if hits + misses else 0.0)
        doc["tenants"] = aggregate_snapshots(
            [s.get("tenants", {}) for s in good])
        return doc

    def debug_state(self) -> dict:
        """The ``/debug/state`` ``fleet`` view: cheap, per-replica — no
        full engine stats, no SLO evaluation."""
        with self._lock:
            reps = list(self._replicas)
            doc = {
                "closed": self._closed,
                "replicas": {},
                "index_entries": len(self._index),
                "router": {"submitted": self._submitted,
                           "completed": self._completed,
                           "failed": self._failed,
                           "resubmitted": self._resubmitted},
                "last_scale": (dict(self._last_scale)
                               if self._last_scale else None),
            }
            rows = [(r, len(r.inflight), r.routed, r.deaths, r.state)
                    for r in reps]
        # the pressure governor is process-global (one HBM), so the
        # fleet view carries one tier, not a per-replica copy
        try:
            from ..resilience import hbm as _hbm

            doc["hbm"] = _hbm.governor().healthz_view()
        except Exception:  # noqa: BLE001 - debug view stays up
            doc["hbm"] = None
        for rep, inflight, routed, deaths, state in rows:
            row = {"state": state, "breaker": rep.breaker.state,
                   "inflight": inflight, "routed": routed,
                   "deaths": deaths}
            if state == "live" and not rep.engine.closed:
                try:
                    kv = rep.engine.kvcache_stats()
                    row["pages_in_use"] = kv.get("pages_in_use")
                    row["queue_depth"] = rep.engine.queue_depth()
                except Exception as exc:  # noqa: BLE001 - debug view
                    # stays up when one replica is mid-teardown
                    row["pages_in_use"] = row["queue_depth"] = None
                    row["stats_error"] = repr(exc)
            doc["replicas"][rep.name] = row
        return doc

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> int:
        """Close every replica (``drain=True`` finishes queued + in-
        flight work first). Returns total requests completed during the
        drain across the fleet. Idempotent."""
        with self._lock:
            if self._closed:
                return 0
            self._closed = True
            reps = list(self._replicas)
            restarts = list(self._restarts)
        self._stop_autoscale.set()
        total = 0
        for rep in reps:
            if rep.state in ("live", "draining"):
                total += rep.engine.close(drain=drain, timeout=timeout)
        for t in restarts:
            t.join(timeout if timeout is not None else 10.0)
        if self._autoscale_thread is not None:
            self._autoscale_thread.join(
                timeout if timeout is not None else 10.0)
        _F_REPLICAS.set(0.0, fleet=self._name)
        _flightrec.record("fleet.closed", fleet=self._name,
                          drain=drain, drained_completed=total)
        return total
