"""mxnet_tpu.resilience — unified retry/backoff/breaker policies + chaos.

The fault story of the framework, in one place (ROADMAP north star: a
system serving millions of users treats failure as a tested input, not an
exception path). The reference's equivalents live in ps-lite — resender
timeouts, scheduler heartbeats, ``GetDeadNodes``, ``is_recovery``
re-rendezvous (SURVEY §5.3); on this stack there is no parameter server to
absorb faults, so the policies move to the call sites themselves:

====================  =====================================================
piece                 what it gives you
====================  =====================================================
:mod:`.policies`      :class:`RetryPolicy` (exponential backoff + jitter,
                      budget-capped), :class:`Deadline`,
                      :class:`TransientError`; ``mxnet_retries_total``
:mod:`.breaker`       :class:`CircuitBreaker` closed/open/half-open per
                      site; ``mxnet_breaker_state`` /
                      ``mxnet_breaker_transitions_total``
:mod:`.chaos`         deterministic seeded fault injection at named sites
                      (``MXNET_CHAOS="seed=7,site=kvstore.*,p=0.1"``);
                      free when disabled; ``mxnet_faults_injected_total``
:mod:`.hbm`           :class:`PressureGovernor` — hysteresis-latched HBM
                      pressure tiers (green/yellow/orange/red) over
                      watermarks + plane-registered bounds, the
                      degradation ladder the decode admission path
                      consults, and OOM classification/survival
                      (``classify``/``oom_survival``);
                      ``mxnet_hbm_pressure_tier`` / ``mxnet_hbm_oom_total``
====================  =====================================================

Hardened call sites (site label → module): ``transfer.fetch_host`` /
``transfer.asnumpy`` (base, ndarray), ``jit.compile`` (telemetry
accounting), ``kvstore.push/pull/pushpull`` (kvstore), ``io.prefetch``
(io prefetchers), ``serving.engine`` (serving batcher — plus per-engine
breakers with AOT→Block fallback and load-shed), ``ckpt.commit``
(elastic CheckpointManager), ``zoo.download`` (gluon model zoo).

Knobs: ``MXNET_RESILIENCE_*`` and ``MXNET_CHAOS`` via ``base.get_env``
(registry in ``docs/env_var.md``); architecture + runbook in
``docs/resilience.md``.
"""
from __future__ import annotations

from typing import Dict, Optional

from . import breaker as breaker_mod
from . import chaos
from . import hbm
from . import policies
from .breaker import CircuitBreaker, CircuitOpenError, breaker
from .chaos import (ChaosAction, DropShard, FaultInjected, Killed,
                    OOMInjected, TornWrite, maybe_fail)
from .hbm import PressureGovernor, classify, governor, oom_survival
from .policies import DEFAULT_RETRY_ON, Deadline, RetryPolicy, TransientError

__all__ = [
    "RetryPolicy", "Deadline", "TransientError", "DEFAULT_RETRY_ON",
    "CircuitBreaker", "CircuitOpenError", "breaker",
    "chaos", "FaultInjected", "ChaosAction", "Killed", "TornWrite",
    "DropShard", "OOMInjected", "maybe_fail",
    "hbm", "PressureGovernor", "classify", "governor", "oom_survival",
    "call", "default_policy", "reset_default_policy", "snapshot",
]

_DEFAULT_POLICY: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    """The process-wide policy the framework call sites share, built from
    the ``MXNET_RESILIENCE_*`` knobs on first use."""
    global _DEFAULT_POLICY
    if _DEFAULT_POLICY is None:
        _DEFAULT_POLICY = RetryPolicy.from_env()
    return _DEFAULT_POLICY


def reset_default_policy() -> None:
    """Drop the cached default policy so changed env knobs take effect
    (tests; a production process configures the environment up front)."""
    global _DEFAULT_POLICY
    _DEFAULT_POLICY = None


def call(site: str, fn, *args, deadline: Optional[Deadline] = None,
         **kwargs):
    """Run ``fn`` under the default retry policy, attributed to ``site``.
    The one-liner the framework call sites use::

        agg = resilience.call("kvstore.push", attempt)
    """
    return default_policy().call(fn, *args, site=site, deadline=deadline,
                                 **kwargs)


def snapshot() -> Dict:
    """Point-in-time resilience picture: retry counters by site/outcome,
    injected-fault counts, breaker states — the dict bench lines and
    post-mortems attach."""
    from .. import telemetry

    retries: Dict[str, float] = {}
    metric = telemetry.REGISTRY.get("mxnet_retries_total")
    if metric is not None:
        for row in metric.series():
            labels = row["labels"]
            retries["%s/%s" % (labels["site"], labels["outcome"])] = \
                row["value"]
    faults: Dict[str, float] = {}
    metric = telemetry.REGISTRY.get("mxnet_faults_injected_total")
    if metric is not None:
        for row in metric.series():
            faults[row["labels"]["site"]] = row["value"]
    # every breaker (registry-shared AND privately constructed, e.g. the
    # serving Server's per-engine ones) publishes its state to the gauge;
    # read it back so the snapshot sees them all
    state_names = {v: k for k, v in breaker_mod.STATE_VALUE.items()}
    breakers: Dict[str, str] = {}
    metric = telemetry.REGISTRY.get("mxnet_breaker_state")
    if metric is not None:
        for row in metric.series():
            breakers[row["labels"]["site"]] = state_names.get(
                int(row["value"]), str(row["value"]))
    return {
        "retries": retries,
        "faults_injected": faults,
        "breakers": breakers,
        "chaos": chaos.summary(),
        "hbm": hbm.governor().healthz_view(),
    }
