"""Circuit breakers — stop hammering a dependency that is down.

A :class:`CircuitBreaker` guards one *site* (a serving engine, in
practice) with the classic three-state machine:

* **closed** — traffic flows; consecutive failures are counted, and
  hitting ``failure_threshold`` trips the breaker **open**;
* **open** — :meth:`allow` refuses immediately (the caller degrades:
  serving falls to the next engine, then load-sheds) until
  ``reset_timeout_s`` has elapsed, at which point the next :meth:`allow`
  admits a **half-open** probe;
* **half-open** — up to ``half_open_max`` probes may run; one success
  closes the breaker, one failure re-opens it and restarts the clock.

State is visible two ways: :attr:`state` / :func:`snapshot` for in-process
consumers (``Server.stats()``), and the telemetry gauge
``mxnet_breaker_state{site}`` (0 closed, 1 half-open, 2 open) plus
``mxnet_breaker_transitions_total{site,to}`` for a scraper — a dashboard
sees the trip before the pager does. Thresholds default from the
``MXNET_RESILIENCE_BREAKER_*`` knobs (``docs/env_var.md``).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..base import MXNetError, get_env

__all__ = ["CircuitBreaker", "CircuitOpenError", "breaker", "snapshot",
           "STATE_VALUE"]

_DEF_THRESHOLD = 5
_DEF_RESET_S = 30.0

#: Gauge encoding of breaker states (``mxnet_breaker_state{site}``).
STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitOpenError(MXNetError):
    """Refused without trying: the site's breaker is open."""

    def __init__(self, site: str):
        super().__init__("circuit breaker for %r is open" % site)
        self.site = site


_GAUGE = None
_TRANSITIONS = None


def _metrics():
    global _GAUGE, _TRANSITIONS
    if _GAUGE is None:
        from .. import telemetry

        _GAUGE = telemetry.gauge(
            "mxnet_breaker_state",
            "circuit breaker state per site (0 closed, 1 half-open, 2 open)",
            labels=("site",))
        _TRANSITIONS = telemetry.counter(
            "mxnet_breaker_transitions_total",
            "circuit breaker state transitions per site",
            labels=("site", "to"))
    return _GAUGE, _TRANSITIONS


class CircuitBreaker:
    """Per-site closed/open/half-open breaker. Thread-safe; every method is
    O(1) under one lock (the serving batcher calls :meth:`allow` per
    batch, not per request)."""

    def __init__(self, site: str, failure_threshold: Optional[int] = None,
                 reset_timeout_s: Optional[float] = None,
                 half_open_max: int = 1):
        if failure_threshold is None:
            failure_threshold = get_env("MXNET_RESILIENCE_BREAKER_THRESHOLD",
                                        _DEF_THRESHOLD, int, cache=False)
        if reset_timeout_s is None:
            reset_timeout_s = get_env("MXNET_RESILIENCE_BREAKER_RESET_S",
                                      _DEF_RESET_S, float, cache=False)
        self.site = site
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = max(0.0, float(reset_timeout_s))
        self.half_open_max = max(1, int(half_open_max))
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        gauge, _ = _metrics()
        gauge.set(STATE_VALUE["closed"], site=site)

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            # an elapsed open breaker reads as half-open: the next allow()
            # would admit a probe, and stats should say so
            if self._state == "open" and self._elapsed():
                return "half_open"
            return self._state

    def _elapsed(self) -> bool:
        return time.monotonic() - self._opened_at >= self.reset_timeout_s

    def _transition(self, to: str) -> None:
        # caller holds self._lock
        self._state = to
        gauge, transitions = _metrics()
        gauge.set(STATE_VALUE[to], site=self.site)
        transitions.inc(site=self.site, to=to)
        # black box: breaker trips are the canonical "what changed right
        # before the death" event (telemetry resolved by _metrics above)
        from ..telemetry import flightrec

        flightrec.record("breaker", site=self.site, to=to)

    # ------------------------------------------------------------------
    def allow(self) -> bool:
        """May a call proceed right now? Open->half-open promotion happens
        here (time-based), so a caller that only ever asks ``allow`` still
        drives the full state machine."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if not self._elapsed():
                    return False
                self._transition("half_open")
                self._probes = 1
                return True
            # half-open: bounded number of in-flight probes
            if self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def on_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != "closed":
                self._transition("closed")
                self._probes = 0

    def on_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open":
                self._transition("open")
                self._opened_at = time.monotonic()
                self._probes = 0
            elif self._state == "closed" and \
                    self._failures >= self.failure_threshold:
                self._transition("open")
                self._opened_at = time.monotonic()

    def call(self, fn, *args, **kwargs):
        """Run ``fn`` under the breaker: :class:`CircuitOpenError` when the
        breaker refuses, success/failure reported automatically."""
        if not self.allow():
            raise CircuitOpenError(self.site)
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.on_failure()
            raise
        self.on_success()
        return out

    def __repr__(self) -> str:
        return "CircuitBreaker(%r, state=%s, failures=%d/%d)" % (
            self.site, self.state, self._failures, self.failure_threshold)


# ---------------------------------------------------------------------------
# per-site registry (get-or-create, like telemetry metrics)
# ---------------------------------------------------------------------------

_REG_LOCK = threading.Lock()
_REGISTRY: Dict[str, CircuitBreaker] = {}


def breaker(site: str, **kwargs) -> CircuitBreaker:
    """Get-or-create the process-wide breaker for ``site``. ``kwargs`` only
    apply on first creation (matching telemetry's get-or-create contract)."""
    with _REG_LOCK:
        br = _REGISTRY.get(site)
        if br is None:
            br = _REGISTRY[site] = CircuitBreaker(site, **kwargs)
        return br


def snapshot() -> Dict[str, str]:
    """``{site: state}`` for every registered breaker."""
    with _REG_LOCK:
        items = list(_REGISTRY.items())
    return {site: br.state for site, br in items}
