"""HBM pressure governor + OOM classification: survive memory exhaustion.

HBM exhaustion is the canonical production TPU failure, and before this
module it was the one fault class the stack could not survive: a
``RESOURCE_EXHAUSTED`` out of XLA killed the decode worker's pools or
the train step with no classification, no degradation ladder and no
recovery path. The planes already own every lever that matters —
refcounted cached-LRU prefix pages (PR 14), tenancy deferral (PR 13),
periodic HBM watermarks (PR 18), pools-dead full eviction (PR 4) — this
module closes the loop from *measuring* pressure to *acting* on it.

Two halves:

**The governor** (:class:`PressureGovernor`, one per process via
:func:`governor`). Planes register worst-case byte *bounds* (the KV
pool, pending-prefill worst case, ZeRO bucket bytes) with
:meth:`~PressureGovernor.register_bound`; the devprof watermark ticks
feed real device samples through
:meth:`~PressureGovernor.observe_device`. Pressure = max(device in-use,
sum of registered bounds) over the capacity (``MXNET_HBM_CAPACITY_BYTES``
or the backend's reported limit; unknown capacity = no tier pressure —
the governor then acts only on classified OOMs). Pressure maps to
**hysteresis-latched tiers** and a declarative degradation ladder the
planes consult at admission:

==========  ===============================================================
tier        ladder rung (consumed by the decode admission path)
==========  ===============================================================
``green``   normal admission
``yellow``  proactively shed prefix cached-LRU ref-0 pages
            (``mxnet_kvcache_pressure_sheds_total``) — warm capacity is
            the first thing traded for headroom
``orange``  shrink admission quanta (one admission per tick) and defer
            ``batch``-class tenants through the tenancy deferral
            primitive — interactive traffic is never blocked
``red``     stop new admissions, serve 503 on ``/healthz`` (with a
            ``pressure`` field), fire the ``HBMPressureBurn`` SLO alert
==========  ===============================================================

Hysteresis: a tier is entered the sample its threshold is crossed and
released only when pressure falls ``MXNET_HBM_HYSTERESIS`` below that
threshold — a ratio oscillating on a boundary cannot flap the ladder.
Every transition lands in the flight recorder as an ``hbm.pressure``
edge and moves the ``mxnet_hbm_pressure_tier`` gauge.

**OOM classification and survival.** :func:`classify` recognizes
``RESOURCE_EXHAUSTED``/allocator failures out of XLA (and the chaos
harness's injected :class:`~mxnet_tpu.resilience.chaos.OOMInjected`, so
injected and real OOM take the identical code path).
:func:`oom_survival` is the one survival routine every plane routes a
classified OOM through: it records a structured diagnostic (per-plane
registered bounds + the watermark history — the post-mortem breakdown)
as an ``hbm.oom`` flight-recorder event, commits the ring to a dump,
**latches the governor red** and ticks ``mxnet_hbm_oom_total{plane}``.
The red latch holds for ``MXNET_HBM_RED_HOLD`` observations before
pressure is allowed to speak again — re-admitting the instant the
failed allocation freed its memory would just OOM again. The decode
engine re-admits at a governed sequence count
(:meth:`~PressureGovernor.governed_admit`: slot shapes stay static — we
admit *fewer*, never reshape); the training planes emit the diagnostic
and fall back per the never-a-crash discipline instead of dying bare.

Knobs (registry: ``docs/env_var.md``): ``MXNET_HBM_CAPACITY_BYTES``,
``MXNET_HBM_YELLOW`` / ``MXNET_HBM_ORANGE`` / ``MXNET_HBM_RED``,
``MXNET_HBM_HYSTERESIS``, ``MXNET_HBM_HISTORY``, ``MXNET_HBM_RED_HOLD``,
``MXNET_HBM_RED_ADMIT``. Runbook: ``docs/resilience.md``.
"""
from __future__ import annotations

import collections
import logging
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Union

from .. import telemetry
from ..base import get_env

_LOG = logging.getLogger(__name__)

__all__ = ["PressureGovernor", "TIERS", "governor", "reset",
           "classify", "oom_survival", "register_bound"]

#: The ladder, least to most severe; gauge value = index.
TIERS = ("green", "yellow", "orange", "red")

_DEF_YELLOW = 0.70
_DEF_ORANGE = 0.85
_DEF_RED = 0.95
_DEF_HYSTERESIS = 0.05
_DEF_HISTORY = 64
_DEF_RED_HOLD = 2

_T_TIER = telemetry.gauge(
    "mxnet_hbm_pressure_tier",
    "HBM pressure governor tier (0=green 1=yellow 2=orange 3=red); red "
    "stops admissions and degrades /healthz")

_T_PRESSURE = telemetry.gauge(
    "mxnet_hbm_pressure_ratio",
    "governor pressure: max(device in-use, sum of plane-registered "
    "bounds) over capacity (0 when capacity is unknown)")

_T_OOMS = telemetry.counter(
    "mxnet_hbm_oom_total",
    "classified out-of-memory failures survived, per plane "
    "(injected chaos OOMs and real RESOURCE_EXHAUSTED count alike)",
    labels=("plane",))

#: substrings that mark an exception text as an allocator/HBM failure —
#: XLA spells it RESOURCE_EXHAUSTED, PJRT/BFC allocators say "out of
#: memory"/"failed to allocate"; matched case-insensitively where noted
_OOM_PATTERNS = ("RESOURCE_EXHAUSTED", "out of memory",
                 "failed to allocate", "allocation failure",
                 "resource exhausted")


def classify(exc: BaseException) -> Optional[str]:
    """Classify an exception as an out-of-memory failure.

    Returns the OOM kind (``injected`` for the chaos harness's
    ``action=oom``, ``host`` for :class:`MemoryError`, ``device`` for
    XLA ``RESOURCE_EXHAUSTED``/allocator text) or ``None`` for anything
    that is not an OOM. Text-matched rather than type-matched for the
    device case: jaxlib's ``XlaRuntimeError`` moved modules across
    versions, and the status *string* is the stable contract.
    """
    if exc is None:
        return None
    from . import chaos

    if isinstance(exc, chaos.OOMInjected):
        return "injected"
    if isinstance(exc, MemoryError):
        return "host"
    text = "%s: %s" % (type(exc).__name__, exc)
    low = text.lower()
    for pat in _OOM_PATTERNS:
        if pat.lower() in low:
            return "device"
    # the bare acronym only as a whole word — "zoom"/"room" in an
    # unrelated message must not latch the governor red
    if re.search(r"\boom\b", low):
        return "device"
    return None


class PressureGovernor:
    """Hysteresis-latched HBM pressure tiers over watermarks + bounds.

    Thread-safe: planes register bounds and observe from their own
    threads; the /healthz handler and the fleet read the tier
    concurrently. All state sits behind one lock; :meth:`tier` is a
    lock-free read of the latest verdict.
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 yellow: Optional[float] = None,
                 orange: Optional[float] = None,
                 red: Optional[float] = None,
                 hysteresis: Optional[float] = None,
                 history: Optional[int] = None,
                 red_hold: Optional[int] = None):
        if capacity_bytes is None:
            capacity_bytes = get_env("MXNET_HBM_CAPACITY_BYTES", 0, int,
                                     cache=False)
        if yellow is None:
            yellow = get_env("MXNET_HBM_YELLOW", _DEF_YELLOW, float,
                             cache=False)
        if orange is None:
            orange = get_env("MXNET_HBM_ORANGE", _DEF_ORANGE, float,
                             cache=False)
        if red is None:
            red = get_env("MXNET_HBM_RED", _DEF_RED, float, cache=False)
        if hysteresis is None:
            hysteresis = get_env("MXNET_HBM_HYSTERESIS", _DEF_HYSTERESIS,
                                 float, cache=False)
        if history is None:
            history = get_env("MXNET_HBM_HISTORY", _DEF_HISTORY, int,
                              cache=False)
        if red_hold is None:
            red_hold = get_env("MXNET_HBM_RED_HOLD", _DEF_RED_HOLD, int,
                               cache=False)
        # thresholds must ascend or the ladder is ill-formed
        self.yellow = max(0.0, float(yellow))
        self.orange = max(self.yellow, float(orange))
        self.red = max(self.orange, float(red))
        self.hysteresis = max(0.0, float(hysteresis))
        self.red_hold = max(1, int(red_hold))
        self._lock = threading.Lock()
        self._capacity = int(capacity_bytes) or None
        self._device_limit: Optional[int] = None
        self._device_used = 0
        #: plane -> worst-case bytes (int) or a zero-arg callable
        self._bounds: Dict[str, Union[int, Callable[[], int]]] = {}
        self._tier = "green"
        self._latched = False
        self._latch_reason: Optional[str] = None
        self._hold_left = 0
        self._oom_count = 0
        self._last_shed: Optional[Dict] = None
        #: (monotonic t, pressure, tier, source) — the watermark history
        #: the oom diagnostic and /debug/state hbm view carry
        self._history: "collections.deque" = collections.deque(
            maxlen=max(4, int(history)))
        #: (monotonic t, from, to, reason) — bounded transition log
        self._transitions: "collections.deque" = collections.deque(
            maxlen=64)

    # -- inputs ------------------------------------------------------------
    def register_bound(self, plane: str,
                       nbytes: Union[int, Callable[[], int]]) -> None:
        """Register (or replace) a plane's worst-case HBM bound: an int
        byte count, or a zero-arg callable re-evaluated per observation
        (exception-isolated — a broken bound reads 0, never breaks a
        sample)."""
        with self._lock:
            self._bounds[str(plane)] = nbytes

    def set_capacity(self, nbytes: Optional[int]) -> None:
        """Override the capacity bound (the bench's pressure ramp and
        tests; production reads ``MXNET_HBM_CAPACITY_BYTES`` or the
        device limit)."""
        with self._lock:
            self._capacity = int(nbytes) if nbytes else None

    def observe_device(self, stats: Dict[int, tuple],
                       source: str = "devprof") -> None:
        """Feed one :func:`~mxnet_tpu.telemetry.accounting.sample_hbm`
        result (``{device_id: (in_use, peak)}``) — the devprof watermark
        tick calls this, so real device usage joins the pressure signal
        wherever the backend has memory stats."""
        if not stats:
            return
        with self._lock:
            self._device_used = max(u for (u, _p) in stats.values())
        self.observe(source=source)

    def _bounds_bytes(self) -> Dict[str, int]:
        with self._lock:
            items = list(self._bounds.items())
        out: Dict[str, int] = {}
        for plane, b in items:
            try:
                out[plane] = int(b() if callable(b) else b)
            except Exception:  # noqa: BLE001 - a bound probe must never
                # break an observation (it may read live engine state)
                out[plane] = 0
        return out

    def capacity_bytes(self) -> Optional[int]:
        with self._lock:
            return self._capacity or self._device_limit

    def set_device_limit(self, nbytes: Optional[int]) -> None:
        """Backend-reported memory limit (``bytes_limit`` where PJRT
        exposes it); the explicit capacity knob wins over it."""
        with self._lock:
            self._device_limit = int(nbytes) if nbytes else None

    # -- evaluation --------------------------------------------------------
    def _natural_tier(self, pressure: float) -> str:
        if pressure >= self.red:
            return "red"
        if pressure >= self.orange:
            return "orange"
        if pressure >= self.yellow:
            return "yellow"
        return "green"

    def _entry_threshold(self, tier: str) -> float:
        return {"yellow": self.yellow, "orange": self.orange,
                "red": self.red}.get(tier, 0.0)

    def observe(self, source: str = "admission") -> str:
        """One governor sample: recompute pressure from the registered
        bounds + the last device reading, step the tier with hysteresis
        (and the OOM red latch), record the watermark and any edge.
        Returns the resulting tier. Cheap — pure host arithmetic over
        the bound registry; the decode admission path calls this every
        worker pass."""
        bounds = self._bounds_bytes()
        cap = self.capacity_bytes()
        with self._lock:
            used = max([self._device_used, sum(bounds.values())] or [0])
            pressure = (used / cap) if cap else 0.0
            natural = self._natural_tier(pressure)
            prev = self._tier
            if self._latched:
                # the OOM latch outranks pressure for red_hold samples;
                # after the hold, pressure speaks again (on a stat-less
                # backend with no capacity signal pressure reads 0.0, so
                # the latch releases to green after the hold — the CPU
                # CI recovery path)
                self._hold_left -= 1
                if self._hold_left > 0 or natural == "red":
                    nxt = "red"
                else:
                    self._latched = False
                    self._latch_reason = None
                    nxt = natural
            elif TIERS.index(natural) >= TIERS.index(prev):
                nxt = natural
            else:
                # stepping DOWN: release one tier at a time, and only
                # once pressure clears the current tier's entry
                # threshold by the hysteresis margin
                if pressure < self._entry_threshold(prev) \
                        - self.hysteresis:
                    nxt = TIERS[TIERS.index(prev) - 1]
                else:
                    nxt = prev
            now = time.monotonic()
            self._history.append((now, round(pressure, 4), nxt, source))
            changed = nxt != prev
            if changed:
                self._transitions.append((now, prev, nxt, source))
                self._tier = nxt
        _T_PRESSURE.set(pressure)
        _T_TIER.set(TIERS.index(nxt))
        if changed:
            from ..telemetry import flightrec

            flightrec.record("hbm.pressure", tier=nxt, prev=prev,
                             pressure=round(pressure, 4), source=source)
        return nxt

    def tier(self) -> str:
        """The latest verdict (no new sample)."""
        return self._tier

    @property
    def latched(self) -> bool:
        return self._latched

    # -- the OOM latch -----------------------------------------------------
    def latch_red(self, reason: str) -> str:
        """Force red for at least ``red_hold`` observations — the OOM
        survival path's backstop: whatever pressure claims, the
        allocation just failed."""
        with self._lock:
            prev = self._tier
            self._latched = True
            self._latch_reason = str(reason)
            self._hold_left = self.red_hold
            self._tier = "red"
            now = time.monotonic()
            self._history.append((now, -1.0, "red", "latch"))
            if prev != "red":
                self._transitions.append((now, prev, "red", reason))
        _T_TIER.set(TIERS.index("red"))
        if prev != "red":
            from ..telemetry import flightrec

            flightrec.record("hbm.pressure", tier="red", prev=prev,
                             pressure=-1.0, source="latch",
                             reason=str(reason))
        return prev

    def governed_admit(self, active: int) -> int:
        """The sequence count the decode plane re-admits at after an
        OOM: ``MXNET_HBM_RED_ADMIT`` when set, else half the count in
        flight when the allocation failed (floor 1). Slot shapes stay
        static — the engine admits fewer sequences, it never reshapes."""
        fixed = get_env("MXNET_HBM_RED_ADMIT", 0, int, cache=False)
        if fixed > 0:
            return fixed
        return max(1, int(active) // 2)

    def note_oom(self, plane: str, kind: str) -> None:
        with self._lock:
            self._oom_count += 1
        _T_OOMS.inc(plane=plane)

    def note_shed(self, pages: int, cache: str) -> None:
        """Record the ladder's last yellow-tier shed for the debug view."""
        with self._lock:
            self._last_shed = {"pages": int(pages), "cache": str(cache),
                               "t": time.monotonic()}

    # -- reporting ---------------------------------------------------------
    def oom_report(self) -> Dict:
        """The structured OOM diagnostic: tier + latch state, capacity,
        the per-plane registered HBM breakdown and the watermark history
        — what a post-mortem needs to see *which plane's* bound ate the
        headroom (docs/resilience.md runbook walks this)."""
        bounds = self._bounds_bytes()
        with self._lock:
            return {
                "tier": self._tier,
                "latched": self._latched,
                "latch_reason": self._latch_reason,
                "oom_count": self._oom_count,
                "capacity_bytes": self._capacity or self._device_limit,
                "device_used_bytes": self._device_used,
                "bounds_bytes": bounds,
                "watermarks": [
                    {"t": round(t, 3), "pressure": p, "tier": tr,
                     "source": src}
                    for (t, p, tr, src) in list(self._history)[-16:]],
            }

    def debug_view(self) -> Dict:
        """The ``/debug/state`` ``hbm`` view: the report plus the
        transition log and the last yellow-tier shed."""
        out = self.oom_report()
        with self._lock:
            out["transitions"] = [
                {"t": round(t, 3), "from": a, "to": b, "reason": r}
                for (t, a, b, r) in list(self._transitions)]
            out["last_shed"] = dict(self._last_shed) \
                if self._last_shed else None
        out["thresholds"] = {"yellow": self.yellow, "orange": self.orange,
                             "red": self.red,
                             "hysteresis": self.hysteresis}
        return out

    def tiers_seen(self) -> List[str]:
        """Distinct tiers in transition order (green first implicit) —
        what the bench's tier-transition gate asserts against."""
        with self._lock:
            return [b for (_t, _a, b, _r) in self._transitions]

    def healthz_view(self) -> Dict:
        """The small dict /healthz attaches as its ``pressure`` field."""
        with self._lock:
            return {"tier": self._tier, "latched": self._latched,
                    "oom_count": self._oom_count,
                    "latch_reason": self._latch_reason}


# ---------------------------------------------------------------------------
# process-wide governor + the one OOM survival routine
# ---------------------------------------------------------------------------

_GOV_LOCK = threading.Lock()
_GOV: Optional[PressureGovernor] = None


def governor() -> PressureGovernor:
    """The process-wide governor (lazy; thresholds from the knobs). The
    first construction also registers the ``hbm`` debug view with the
    telemetry endpoint."""
    global _GOV
    with _GOV_LOCK:
        if _GOV is None:
            _GOV = PressureGovernor()
            try:
                from ..telemetry import httpd

                httpd.register_debug_view("hbm", _GOV.debug_view)
            except Exception:  # noqa: BLE001 - introspection wiring must
                # never block the governor itself
                _LOG.debug("hbm debug view registration failed",
                           exc_info=True)
        return _GOV


def reset() -> None:
    """Drop the process governor (tests re-read knobs on next use)."""
    global _GOV
    with _GOV_LOCK:
        _GOV = None
    _T_TIER.set(0)
    _T_PRESSURE.set(0.0)


def register_bound(plane: str,
                   nbytes: Union[int, Callable[[], int]]) -> None:
    governor().register_bound(plane, nbytes)


def oom_survival(plane: str, exc: BaseException, dump: bool = True) -> bool:
    """THE classified-OOM survival routine, shared by every plane (and
    by injected and real OOM alike). Returns False — untouched — for a
    non-OOM exception. For an OOM: records the structured diagnostic as
    an ``hbm.oom`` flight-recorder event, commits the ring to a dump
    (``dump=True``; the decode worker's catch-all already dumps, train
    planes want it here), latches the governor red and counts
    ``mxnet_hbm_oom_total{plane}``. The caller then runs its own
    recovery — full eviction + governed re-admission on the decode
    plane, controlled fallback on the train planes."""
    kind = classify(exc)
    if kind is None:
        return False
    gov = governor()
    gov.note_oom(plane, kind)
    from ..telemetry import flightrec

    # the diagnostic goes into the RING before the dump commits it, so
    # the dump file carries the per-plane breakdown next to the edge
    flightrec.record("hbm.oom", plane=plane, oom_kind=kind,
                     error=repr(exc), report=gov.oom_report())
    gov.latch_red("oom:%s" % plane)
    if dump:
        flightrec.dump("hbm oom at %s: %r" % (plane, exc))
    return True
