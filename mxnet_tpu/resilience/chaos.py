"""Deterministic fault injection — failures as a *tested, first-class input*.

Every hardened path in the framework passes through a named **injection
site** before doing its fault-prone work::

    chaos.maybe_fail("kvstore.push")

With chaos disabled (the default, and whenever ``MXNET_CHAOS`` is unset)
that call is a single module-global boolean read — no lock, no environment
read, no allocation — the same discipline as ``MXNET_TELEMETRY=0``, and
the poisoned-state test in ``tests/test_resilience.py`` proves it.

Enabled, faults are **seeded and schedule-driven**, so a chaos run is a
reproducible experiment, not a flake generator::

    MXNET_CHAOS="seed=7,site=kvstore.*,p=0.1"

Spec DSL — ``;``-separated rules of ``,``-separated ``key=value`` pairs:

========  ==================================================================
key       meaning
========  ==================================================================
seed      global RNG seed (any rule may set it; the last one wins)
site      glob matched against the site name (default ``*``)
p         per-call fault probability in [0, 1] (default 0)
at        colon-separated 1-based call indices that *always* fault
          (per rule, per site), e.g. ``at=2:5`` — the deterministic
          schedule for "the 3rd push fails" tests
max       cap on total faults injected by the rule (default unlimited)
action    what an injection does (default ``fault``):

          * ``fault`` — raise :class:`FaultInjected` (a transient error,
            exercised by the retry/breaker machinery);
          * ``kill`` — raise :class:`Killed`: an abrupt process death at
            that call (kill-at-step preemption). NOT transient — nothing
            retries it; it unwinds to the elastic supervisor
            (``elastic.run_elastic``), which restarts from the last
            committed checkpoint;
          * ``torn-write`` — raise :class:`TornWrite`: the elastic shard
            writer catches it and commits deliberately truncated bytes
            (a silently torn write — bitrot, a filesystem that lied
            about fsync), proving restore's content-hash fallback;
          * ``drop-shard`` — raise :class:`DropShard`: the shard writer
            skips that shard's file entirely (post-commit loss), proving
            the missing-file fallback;
          * ``oom`` — raise :class:`OOMInjected`: a synthetic
            ``RESOURCE_EXHAUSTED`` allocator failure that
            :func:`~mxnet_tpu.resilience.hbm.classify` recognizes, so an
            injected OOM takes the *identical* survival path as a real
            one (eviction + governor red latch on the decode plane,
            diagnostic dump + fallback on the train planes). Subclasses
            :class:`FaultInjected` but is exempted from retry by the
            retry policy's OOM guard — retrying a failed allocation
            against a full device is not recovery. Aim it at the
            dispatch/transfer/page-write sites: ``serving.decode``
            (mid-tick), ``serving.decode.prefill`` (page writes),
            ``jit.compile`` (any jitted dispatch, incl. train steps),
            ``transfer.fetch_host``.
========  ==================================================================

Determinism contract: each (rule, site) pair draws from its own
``random.Random`` stream seeded by ``seed/rule-index/site``, so the k-th
call at a site faults identically across runs regardless of how other
sites interleave (thread timing cannot leak between streams). Retries
consume draws like any other call, which keeps retried schedules
reproducible too.

Registered sites (grep ``maybe_fail`` for ground truth):
``transfer.fetch_host``, ``transfer.asnumpy``, ``jit.compile``,
``kvstore.push``, ``kvstore.pull``, ``kvstore.pushpull``, ``io.prefetch``,
``serving.engine``, ``serving.decode``, ``serving.decode.prefill``,
``serving.decode.tenant.<id>`` (one site per tenant — scope a schedule to
ONE tenant's requests with e.g. ``site=serving.decode.tenant.A`` to prove
tenant isolation; see docs/resilience.md),
``serving.fleet.replica.<i>`` (one site per fleet replica — a fault
there kills the whole replica at routing time and must cost zero
requests: the router re-routes its in-flight set and restarts it),
``ckpt.commit``, ``zoo.download``.

Injected faults raise :class:`FaultInjected` — a
:class:`~mxnet_tpu.resilience.policies.TransientError` — so they exercise
exactly the retry/breaker machinery a real transient fault would, and
every injection ticks ``mxnet_faults_injected_total{site}``.
"""
from __future__ import annotations

import fnmatch
import random
import threading
from typing import Dict, List, Optional, Tuple

from ..base import MXNetError, get_env
from .policies import TransientError

__all__ = ["FaultInjected", "ChaosAction", "Killed", "TornWrite",
           "DropShard", "OOMInjected", "maybe_fail", "configure",
           "disable", "active", "parse_spec", "injected_counts",
           "summary", "ENABLED"]


class FaultInjected(TransientError):
    """A synthetic fault raised by :func:`maybe_fail`."""

    def __init__(self, site: str, call_index: int):
        super().__init__("chaos: injected fault at %s (call #%d)"
                         % (site, call_index))
        self.site = site
        self.call_index = call_index


class ChaosAction(MXNetError):
    """Base of the non-``fault`` schedule actions. Deliberately NOT a
    :class:`TransientError`: a simulated process kill or torn write must
    reach the layer that owns that failure mode (the elastic supervisor,
    the shard writer) — a retry policy "recovering" it would fake the
    very resilience the schedule exists to prove."""

    action = "action"

    def __init__(self, site: str, call_index: int):
        super().__init__("chaos: injected %s at %s (call #%d)"
                         % (self.action, site, call_index))
        self.site = site
        self.call_index = call_index


class Killed(ChaosAction):
    """Simulated abrupt process death (``action=kill`` — kill-at-step)."""

    action = "kill"


class TornWrite(ChaosAction):
    """Simulated silently-torn file write (``action=torn-write``)."""

    action = "torn-write"


class DropShard(ChaosAction):
    """Simulated post-commit loss of one shard file (``action=drop-shard``)."""

    action = "drop-shard"


class OOMInjected(FaultInjected):
    """Simulated allocator exhaustion (``action=oom``): the message
    carries the literal ``RESOURCE_EXHAUSTED`` status text a real XLA
    OOM would, and ``hbm.classify`` recognizes the type directly —
    injected and real OOM share one survival code path. A
    :class:`FaultInjected` by inheritance (the issue contract), but the
    retry policy's OOM guard refuses to retry it: allocation failures
    are cured by freeing memory, not by calling again."""

    def __init__(self, site: str, call_index: int):
        # deliberately bypass FaultInjected.__init__'s message
        TransientError.__init__(
            self, "chaos: injected oom at %s (call #%d): "
            "RESOURCE_EXHAUSTED: out of memory (synthetic)"
            % (site, call_index))
        self.site = site
        self.call_index = call_index


_ACTIONS = {"fault": None, "kill": Killed, "torn-write": TornWrite,
            "torn": TornWrite, "drop-shard": DropShard, "drop": DropShard,
            "oom": OOMInjected}


#: THE disabled-path switch: ``maybe_fail`` reads this module global and
#: nothing else when chaos is off. Flip only through configure()/disable().
ENABLED = False

_STATE: Optional["_ChaosState"] = None

_FAULTS = None


def _faults_counter():
    global _FAULTS
    if _FAULTS is None:
        from .. import telemetry

        _FAULTS = telemetry.counter(
            "mxnet_faults_injected_total",
            "synthetic faults raised by the chaos harness per site",
            labels=("site",))
    return _FAULTS


class _Rule:
    __slots__ = ("pattern", "p", "at", "max_faults", "injected", "action")

    def __init__(self, pattern: str = "*", p: float = 0.0,
                 at: Tuple[int, ...] = (), max_faults: Optional[int] = None,
                 action: str = "fault"):
        self.pattern = pattern
        self.p = p
        self.at = frozenset(at)
        self.max_faults = max_faults
        self.injected = 0
        self.action = action


def parse_spec(spec: str) -> Tuple[int, List[_Rule]]:
    """Parse the chaos DSL; raises :class:`MXNetError` on malformed input
    (a silently-ignored typo in a chaos spec would fake resilience)."""
    seed = 0
    rules: List[_Rule] = []
    for chunk in str(spec).split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        rule = _Rule()
        for tok in chunk.split(","):
            tok = tok.strip()
            if not tok:
                continue
            key, sep, val = tok.partition("=")
            key, val = key.strip(), val.strip()
            if not sep or not val:
                raise MXNetError("chaos spec: %r is not key=value" % tok)
            try:
                if key == "seed":
                    seed = int(val)
                elif key == "site":
                    rule.pattern = val
                elif key == "p":
                    rule.p = float(val)
                    if not 0.0 <= rule.p <= 1.0:
                        raise ValueError(val)
                elif key == "at":
                    rule.at = frozenset(int(x) for x in val.split(":"))
                    if any(i < 1 for i in rule.at):
                        raise ValueError(val)
                elif key == "max":
                    rule.max_faults = int(val)
                elif key == "action":
                    if val not in _ACTIONS:
                        raise MXNetError(
                            "chaos spec: unknown action %r (choose from %s)"
                            % (val, "/".join(sorted(set(_ACTIONS)))))
                    rule.action = val
                else:
                    raise MXNetError("chaos spec: unknown key %r in %r"
                                     % (key, tok))
            except (TypeError, ValueError):
                raise MXNetError("chaos spec: bad value in %r" % tok)
        if rule.p == 0.0 and not rule.at:
            raise MXNetError(
                "chaos spec: rule %r injects nothing (set p= or at=)" % chunk)
        rules.append(rule)
    return seed, rules


class _ChaosState:
    """All enabled-path state behind one lock: per-(rule, site) call
    counters and RNG streams, per-site injected totals."""

    def __init__(self, spec: str):
        self.spec = spec
        self.seed, self.rules = parse_spec(spec)
        self._lock = threading.Lock()
        self._calls: Dict[Tuple[int, str], int] = {}
        self._rngs: Dict[Tuple[int, str], random.Random] = {}
        self._injected: Dict[str, int] = {}

    def maybe_fail(self, site: str) -> None:
        with self._lock:
            for idx, rule in enumerate(self.rules):
                if not fnmatch.fnmatchcase(site, rule.pattern):
                    continue
                key = (idx, site)
                n = self._calls.get(key, 0) + 1
                self._calls[key] = n
                hit = n in rule.at
                if not hit and rule.p > 0.0:
                    rng = self._rngs.get(key)
                    if rng is None:
                        # string seeding is stable across runs and python
                        # versions — the determinism contract rests on it
                        rng = self._rngs[key] = random.Random(
                            "%d/%d/%s" % (self.seed, idx, site))
                    hit = rng.random() < rule.p
                if hit and (rule.max_faults is None
                            or rule.injected < rule.max_faults):
                    rule.injected += 1
                    self._injected[site] = self._injected.get(site, 0) + 1
                    _faults_counter().inc(site=site)
                    # black box: the injected fault is very often THE
                    # event that precedes a death — the dump must name it
                    from ..telemetry import flightrec

                    flightrec.record("chaos.fault", site=site,
                                     action=rule.action, call=n)
                    exc_cls = _ACTIONS.get(rule.action)
                    if exc_cls is not None:
                        raise exc_cls(site, n)
                    raise FaultInjected(site, n)

    def injected_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)


def maybe_fail(site: str) -> None:
    """Raise a seeded synthetic fault at ``site`` per the active schedule.
    Disabled (the default): one boolean read, nothing else."""
    if not ENABLED:
        return
    # snapshot: disable() on another thread clears ENABLED then _STATE, and
    # a caller between the two reads must degrade to a no-op, not crash
    state = _STATE
    if state is not None:
        state.maybe_fail(site)


def configure(spec: Optional[str]) -> None:
    """Install a chaos schedule (empty/None disables). Counters and RNG
    streams restart from zero — configure() begins a fresh experiment."""
    global ENABLED, _STATE
    if not spec:
        ENABLED = False
        _STATE = None
        return
    _STATE = _ChaosState(str(spec))
    ENABLED = True


def disable() -> None:
    configure(None)


class active:
    """Context manager scoping a chaos schedule to a block (tests)::

        with chaos.active("seed=7,site=kvstore.*,p=0.1"):
            train()
    """

    def __init__(self, spec: str):
        self.spec = spec
        self._prev = None

    def __enter__(self):
        self._prev = (ENABLED, _STATE)
        configure(self.spec)
        return self

    def __exit__(self, *exc):
        global ENABLED, _STATE
        ENABLED, _STATE = self._prev
        return False


def injected_counts() -> Dict[str, int]:
    """Per-site totals of faults injected by the active schedule (empty
    when disabled — or when nothing fired yet)."""
    state = _STATE
    return state.injected_counts() if state is not None else {}


def summary() -> Dict:
    """One dict for bench/report lines: the active spec + per-site fault
    counts (``{"enabled": False}`` when off)."""
    state = _STATE
    if not ENABLED or state is None:
        return {"enabled": False}
    return {"enabled": True, "spec": state.spec, "seed": state.seed,
            "faults_injected": state.injected_counts()}


# Import-time activation: a launcher exporting MXNET_CHAOS gets injection
# without code changes (tests use configure()/active() instead — the knob
# is read ONCE here, never per call).
_spec = get_env("MXNET_CHAOS", "", str, cache=False)
if _spec:
    configure(_spec)
del _spec
