"""Retry policies and deadlines — the *decide* half of the resilience layer.

The reference framework's fault handling lives in ps-lite (resender
timeouts, scheduler heartbeats, ``is_recovery`` re-rendezvous); this stack
has no parameter server, so transient faults surface as exceptions at the
call site — a flaky device->host transfer, an ICI collective hiccup, a
checkpoint write racing a disk stall. :class:`RetryPolicy` is the one
uniform answer wired into those sites (kvstore push/pull, io prefetch,
``base.fetch_host``, serving engine runs, checkpoint commits): exponential
backoff with jitter, capped per-delay and by a total sleep budget, retrying
only *transient* error classes so programming errors still fail fast.

Every knob flows through ``base.get_env`` (registry: ``docs/env_var.md``,
all ``MXNET_RESILIENCE_*``, read with ``cache=False`` so launchers and
tests can set them after import). Every retry event lands in telemetry as
``mxnet_retries_total{site,outcome}`` with outcomes:

* ``retry``     — one backoff sleep is about to happen;
* ``recovered`` — the call succeeded after at least one retry;
* ``exhausted`` — attempts/budget/deadline ran out; the last error is
  re-raised unchanged (callers keep their exception types);
* ``oom``       — the failure classified as out-of-memory
  (``hbm.classify``): surfaced immediately without a single retry, even
  when transient-typed — re-dispatching an allocation against a full
  device is not recovery; the owning plane's survival path handles it.

Nothing here is chaos-specific: :mod:`.chaos` raises
:class:`~mxnet_tpu.resilience.chaos.FaultInjected` (a
:class:`TransientError`), so injected faults exercise exactly the retry
machinery real faults would.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Sequence, Tuple

from ..base import MXNetError, get_env

__all__ = ["TransientError", "Deadline", "RetryPolicy", "DEFAULT_RETRY_ON"]


class TransientError(MXNetError):
    """An error the caller may safely retry (nothing was committed).
    Chaos-injected faults subclass this; runtime code can raise it to mark
    a failure as retry-safe."""


#: Error classes retried by default: the explicit transient marker plus the
#: OS-level classes a storage/network hiccup raises. Everything else
#: (ValueError, tracer leaks, assertion failures...) is a bug and fails
#: fast.
DEFAULT_RETRY_ON: Tuple[type, ...] = (TransientError, ConnectionError,
                                      TimeoutError, OSError)

_DEF_MAX_ATTEMPTS = 4
_DEF_BASE_DELAY_MS = 5.0
_DEF_MAX_DELAY_MS = 2000.0
_DEF_MULTIPLIER = 2.0
_DEF_JITTER = 0.1
_DEF_BUDGET_MS = 10000.0


class Deadline:
    """A wall-clock budget carried through a call chain. ``None`` timeout
    means unbounded (``remaining()`` is ``inf``, never expires)."""

    __slots__ = ("_end",)

    def __init__(self, timeout_s: Optional[float] = None):
        self._end = None if timeout_s is None else time.monotonic() + timeout_s

    @classmethod
    def after_ms(cls, timeout_ms: Optional[float]) -> "Deadline":
        return cls(None if timeout_ms is None else float(timeout_ms) / 1e3)

    def remaining(self) -> float:
        if self._end is None:
            return float("inf")
        return max(0.0, self._end - time.monotonic())

    def expired(self) -> bool:
        return self._end is not None and time.monotonic() >= self._end

    def __repr__(self) -> str:
        if self._end is None:
            return "Deadline(unbounded)"
        return "Deadline(%.3fs remaining)" % self.remaining()


def _is_oom(exc: BaseException) -> bool:
    """True when ``exc`` classifies as an out-of-memory failure (lazy
    import: :mod:`.hbm` sits above this module in the package graph).
    OOMs are the one transient-typed class the policy refuses to retry
    — see the ``outcome="oom"`` branch in :meth:`RetryPolicy.call`."""
    try:
        from . import hbm

        return hbm.classify(exc) is not None
    except Exception:  # noqa: BLE001 - the guard must never turn a
        return False   # retryable failure into a policy crash


_RETRIES = None


def retries_counter():
    """``mxnet_retries_total{site,outcome}`` — THE definition of the retry
    counter, resolved lazily because the resilience layer sits below
    telemetry in the import order. Every publisher (the policy itself,
    ``elastic.run_elastic``) goes through here so the name/label schema
    lives in one place."""
    global _RETRIES
    if _RETRIES is None:
        from .. import telemetry

        _RETRIES = telemetry.counter(
            "mxnet_retries_total",
            "retry-policy events per call site "
            "(outcome: retry/recovered/exhausted/oom)",
            labels=("site", "outcome"))
    return _RETRIES


class RetryPolicy:
    """Budget-capped exponential backoff with jitter.

    Delay before retry ``n`` (1-based) is
    ``min(base_delay * multiplier**(n-1), max_delay)`` scaled by a uniform
    jitter in ``[1-jitter, 1+jitter]``; the *total* slept time across one
    :meth:`call` never exceeds ``budget_ms``. Arguments left ``None`` come
    from the ``MXNET_RESILIENCE_*`` environment knobs at construction time.
    """

    def __init__(self, max_attempts: Optional[int] = None,
                 base_delay_ms: Optional[float] = None,
                 max_delay_ms: Optional[float] = None,
                 multiplier: Optional[float] = None,
                 jitter: Optional[float] = None,
                 budget_ms: Optional[float] = None,
                 retry_on: Optional[Sequence[type]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts is None:
            max_attempts = get_env("MXNET_RESILIENCE_MAX_ATTEMPTS",
                                   _DEF_MAX_ATTEMPTS, int, cache=False)
        if base_delay_ms is None:
            base_delay_ms = get_env("MXNET_RESILIENCE_BASE_DELAY_MS",
                                    _DEF_BASE_DELAY_MS, float, cache=False)
        if max_delay_ms is None:
            max_delay_ms = get_env("MXNET_RESILIENCE_MAX_DELAY_MS",
                                   _DEF_MAX_DELAY_MS, float, cache=False)
        if multiplier is None:
            multiplier = get_env("MXNET_RESILIENCE_MULTIPLIER",
                                 _DEF_MULTIPLIER, float, cache=False)
        if jitter is None:
            jitter = get_env("MXNET_RESILIENCE_JITTER", _DEF_JITTER, float,
                             cache=False)
        if budget_ms is None:
            budget_ms = get_env("MXNET_RESILIENCE_BUDGET_MS", _DEF_BUDGET_MS,
                                float, cache=False)
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = max(0.0, float(base_delay_ms)) / 1e3
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self.multiplier = max(1.0, float(multiplier))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self.budget_s = max(0.0, float(budget_ms)) / 1e3
        self.retry_on: Tuple[type, ...] = tuple(retry_on) \
            if retry_on is not None else DEFAULT_RETRY_ON
        self._sleep = sleep

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Policy built entirely from the ``MXNET_RESILIENCE_*`` knobs."""
        return cls()

    def delay_s(self, retry_index: int) -> float:
        """Pre-jitter delay before retry ``retry_index`` (1-based)."""
        d = self.base_delay_s * (self.multiplier ** (retry_index - 1))
        return min(d, self.max_delay_s)

    def delays(self):
        """The full pre-jitter backoff schedule (``max_attempts - 1``
        delays) — what the unit tests assert against."""
        return [self.delay_s(i) for i in range(1, self.max_attempts)]

    def call(self, fn: Callable, *args, site: str = "unspecified",
             deadline: Optional[Deadline] = None, **kwargs):
        """Invoke ``fn(*args, **kwargs)``, retrying transient failures.

        Non-transient exceptions (anything outside ``retry_on``) propagate
        immediately. When retries run out — attempts, sleep budget, or the
        optional ``deadline`` — the *last* exception is re-raised unchanged
        and ``mxnet_retries_total{site,outcome="exhausted"}`` ticks.
        """
        spent = 0.0
        attempt = 0
        while True:
            attempt += 1
            try:
                out = fn(*args, **kwargs)
            except self.retry_on as exc:
                if _is_oom(exc):
                    # a classified OOM is transient-shaped (OOMInjected
                    # subclasses TransientError) but NOT retry-curable:
                    # the device is full, and re-dispatching the same
                    # allocation burns the backoff budget against a wall.
                    # Surface it immediately to the owning plane's
                    # survival path (hbm.oom_survival).
                    retries_counter().inc(site=site, outcome="oom")
                    raise
                if attempt >= self.max_attempts:
                    retries_counter().inc(site=site, outcome="exhausted")
                    raise
                delay = self.delay_s(attempt)
                if self.jitter:
                    delay *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
                if spent + delay > self.budget_s:
                    retries_counter().inc(site=site, outcome="exhausted")
                    raise
                if deadline is not None and deadline.remaining() < delay:
                    retries_counter().inc(site=site, outcome="exhausted")
                    raise
                retries_counter().inc(site=site, outcome="retry")
                if delay > 0.0:
                    self._sleep(delay)
                spent += delay
                continue
            if attempt > 1:
                retries_counter().inc(site=site, outcome="recovered")
            return out

    def __repr__(self) -> str:
        return ("RetryPolicy(attempts=%d, base=%.1fms, max=%.0fms, x%.1f, "
                "jitter=%.2f, budget=%.0fms)"
                % (self.max_attempts, self.base_delay_s * 1e3,
                   self.max_delay_s * 1e3, self.multiplier, self.jitter,
                   self.budget_s * 1e3))
