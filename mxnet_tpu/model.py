"""Model helpers: checkpointing and kvstore-update plumbing.

API parity with reference ``python/mxnet/model.py`` (save_checkpoint :383,
load_checkpoint :413, _create_kvstore, _update_params[_on_kvstore] :145,
BatchEndParam, FeedForward kept as a thin legacy shim).
"""
from __future__ import annotations

import logging
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .ndarray import io_utils
from .ndarray import ndarray as nd_mod

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "FeedForward"]

BatchEndParam = namedtuple(
    "BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore + decide update_on_kvstore (reference model.py:_create_kvstore)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            from . import kvstore as kvs_mod

            kv = kvs_mod.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape) for param in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        kv = kvstore
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """push grad → server update → pull weight (reference model.py:145-155).

    With fastpath on and a server-side updater set, every key batches
    through ONE ``kvstore.pushpull_update_multi`` exchange (one retried
    aggregate phase + one fused optimizer dispatch) instead of a per-key
    push/pull pair; ``MXNET_FASTPATH=0`` restores the loop."""
    from . import fastpath

    entries = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        entries.append((index, arg_list, grad_list))
    if (fastpath.enabled() and getattr(kvstore, "_updater", None) is not None
            and getattr(kvstore, "_compression", None) is None
            and hasattr(kvstore, "pushpull_update_multi")):
        kvstore.pushpull_update_multi(
            [i for i, _, _ in entries],
            [g for _, _, g in entries],
            [a for _, a, _ in entries])
        return
    for index, arg_list, grad_list in entries:
        kvstore.push(index, grad_list, priority=-index)
        kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None,
                   param_names=None):
    """kvstore reduce (optional) + host-side updater (reference
    model.py:_update_params). Fastpath: the gradient exchange fuses into
    one ``pushpull_multi`` and the updater applies once per device position
    over the whole parameter tree (``fastpath.apply_updater``) instead of
    one jitted call per parameter."""
    from . import fastpath
    from . import optimizer as opt_mod

    entries = []
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        entries.append((i, arg_list, grad_list))
    if kvstore:
        if kvstore._can_fuse_pushpull():
            grad_lists = [g for _, _, g in entries]
            kvstore.pushpull_multi([i for i, _, _ in entries],
                                   grad_lists, grad_lists)
        else:
            for index, _, grad_list in entries:
                kvstore.push(index, grad_list, priority=-index)
                kvstore.pull(index, grad_list, priority=-index)
    n_pos = max((len(a) for _, a, _ in entries), default=1)
    if (fastpath.enabled() and isinstance(updater, opt_mod.Updater)
            and fastpath.supports(updater.optimizer, n_positions=n_pos)):
        by_pos = {}
        for index, arg_list, grad_list in entries:
            for k, (w, g) in enumerate(zip(arg_list, grad_list)):
                by_pos.setdefault(k, []).append(
                    (index * num_device + k, g, w))
        for k in sorted(by_pos):
            fastpath.apply_updater(updater, by_pos[k],
                                   positions=len(by_pos))
        return
    for index, arg_list, grad_list in entries:
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save symbol JSON + params (reference model.py:383; two-artifact
    contract from SURVEY §5.4)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    io_utils.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Load symbol + params (reference model.py:413)."""
    from . import symbol as sym_mod

    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = io_utils.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward(object):
    """Legacy pre-Module API (reference model.py:FeedForward) implemented as
    a thin shim over Module; kept so old scripts keep running."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .context import cpu
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx or [cpu()]
        if not isinstance(self.ctx, list):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs.copy()
        self._module = None

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    def _init_module(self, data, label_name="softmax_label"):
        from .module import Module

        data_names = [x[0] for x in data.provide_data]
        label_names = [x[0] for x in (data.provide_label or [])]
        mod = Module(self.symbol, data_names=data_names,
                     label_names=label_names or None, context=self.ctx)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """Train (reference model.py:FeedForward.fit). Rides Module.fit, so
        the in-graph training plane applies: with ``MXNET_TRAINSTEP`` at
        auto/1 and a single-context traceable symbol, every step runs as
        ONE compiled fwd+bwd+update module (``mxnet_tpu.trainplane``)."""
        self._module = self._init_module(X)
        self._module.fit(
            X, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer,
            optimizer_params=self.kwargs or (("learning_rate", 0.01),),
            initializer=self.initializer, arg_params=self.arg_params,
            aux_params=self.aux_params, num_epoch=self.num_epoch,
            begin_epoch=self.begin_epoch, monitor=monitor,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        if self._module is None:
            self._module = self._init_module(X)
            self._module.bind(data_shapes=X.provide_data, for_training=False)
            self._module.init_params(arg_params=self.arg_params,
                                     aux_params=self.aux_params)
        outputs = self._module.predict(X, num_batch=num_batch, reset=reset)
        return outputs.asnumpy() if hasattr(outputs, "asnumpy") else outputs
