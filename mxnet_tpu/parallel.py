"""Multi-device / multi-host execution: meshes, collectives, SPMD training.

TPU-native replacement for the reference's entire distribution stack
(SURVEY §5.8): the CPU/GPU reduce trees (``src/kvstore/comm.h:43``,
``comm_tree.h:50``), NCCL backend (``kvstore_nccl.h:62``) and the ps-lite
parameter server (``kvstore_dist.h:44``, ``kvstore_dist_server.h``) all
collapse onto two primitives:

* ``all_reduce`` — an eager cross-device allreduce over per-device gradient
  copies, lowered to one XLA collective riding ICI (DCN across hosts). This
  backs ``kvstore=tpu`` push/pull, keeping the imperative KVStore API.
* ``TrainStep`` — the in-graph path: ONE jitted SPMD module per step
  containing forward, loss, backward, gradient allreduce, and the optimizer
  update. Parameters and optimizer state are replicated over the mesh; the
  batch is sharded along ``dp``; XLA's GSPMD partitioner inserts the
  collectives (the scaling-book recipe: pick a mesh, annotate shardings,
  let XLA do the rest). Because reductions over the sharded batch axis are
  global, every BatchNorm inside a TrainStep is a cross-device SyncBatchNorm
  (reference ``src/operator/contrib/sync_batch_norm-inl.h``) for free.

Multi-host: under ``jax.distributed`` the same code spans processes —
``jax.devices()`` is the global device set, each process feeds its local
shards, and the collectives ride ICI within a slice / DCN across slices.
The PS server process of the reference disappears: weights stay resident
in HBM (SURVEY §5.8 north star).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _global, autograd
from .base import MXNetError
from .context import Context, cpu
from .ndarray.ndarray import NDArray

__all__ = ["device_mesh", "all_reduce", "all_reduce_multi",
           "broadcast_to_devices", "TrainStep", "InferStep",
           "pipeline_apply", "shard_to_mesh", "batch_sharding",
           "fresh_replicate"]


# ---------------------------------------------------------------------------
# mesh construction
# ---------------------------------------------------------------------------


def device_mesh(n_devices: Optional[int] = None, axis_names=("dp",),
                shape: Optional[Sequence[int]] = None, devices=None) -> Mesh:
    """Build a ``jax.sharding.Mesh``.

    One axis (``dp``) by default — the reference's parity scope is data
    parallelism (SURVEY §2.5). Pass ``shape``/``axis_names`` for 2-D+
    meshes (e.g. ``shape=(4, 2), axis_names=('dp', 'mp')``).
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    devices = np.asarray(devices)
    if shape is not None:
        devices = devices.reshape(tuple(shape))
        if len(axis_names) != devices.ndim:
            raise MXNetError("axis_names must match mesh shape rank")
    return Mesh(devices, tuple(axis_names))


# ---------------------------------------------------------------------------
# eager collectives (kvstore=tpu backend)
# ---------------------------------------------------------------------------

_REDUCE_JITS: Dict[Any, Any] = {}


def _reduce_fn(mesh: Mesh, op: str):
    key = (tuple(d.id for d in mesh.devices.flat), op)
    fn = _REDUCE_JITS.get(key)
    if fn is None:
        red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
               "mean": jnp.mean}[op]
        fn = jax.jit(lambda x: red(x, axis=0),
                     out_shardings=NamedSharding(mesh, P()))
        _REDUCE_JITS[key] = fn
    return fn


def _acc_reduce(datas, op):
    """Sequential on-device accumulation of copies for sum/mean/max/min."""
    acc = datas[0]
    for d in datas[1:]:
        if op in ("sum", "mean"):
            acc = acc + d
        elif op == "max":
            acc = jnp.maximum(acc, d)
        elif op == "min":
            acc = jnp.minimum(acc, d)
        else:
            raise MXNetError("unsupported all_reduce op %r" % (op,))
    return acc


def all_reduce(arrays: List[Any], op: str = "sum"):
    """Allreduce per-device copies into one replicated jax.Array.

    ``arrays`` is one array per participating device (jax arrays or
    NDArrays). The copies are assembled zero-copy into a single array
    sharded over a device axis and reduced with the output replicated on
    every participating device — one fused XLA allreduce over ICI instead
    of the reference's tree/P2P/NCCL reduce hierarchy (comm.h:103,451,
    comm_tree.h:50, kvstore_nccl.h:285).

    Across processes (``jax.distributed``), every process passes its local
    copies and the reduction spans the global device set.
    """
    datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
             for a in arrays]
    if len(datas) == 1 and jax.process_count() == 1:
        return datas[0]
    devs = []
    for d in datas:
        ds = list(d.devices())
        devs.append(ds[0] if len(ds) == 1 else None)
    distinct = None not in devs and len(set(devs)) == len(devs)
    if jax.process_count() == 1 and not distinct:
        # single process, copies not on distinct devices: plain on-device
        # reduce (multi-process must NOT take this shortcut — the local
        # arrangement is irrelevant, the cross-process reduce still runs)
        acc = _acc_reduce(datas, op)
        if op == "mean":
            acc = acc / len(datas)
        return acc
    mean_unpack = None  # (shape, dtype) when mean rides a sum (see below)
    if jax.process_count() > 1:
        # SPMD contract: branch selection must agree across processes, so
        # either EVERY process passes exactly one copy per local device
        # (fast path: one collective over the global device mesh) or none
        # does (pre-reduce path). Mixed arrangements are a caller error and
        # would run mismatched collectives.
        local = jax.local_devices()
        if len(datas) == len(local) and distinct:
            mesh = Mesh(np.asarray(jax.devices()), ("dev",))
        else:
            # arbitrary number of local copies: pre-reduce them on-device,
            # then reduce the partials across processes on a one-device-per-
            # process mesh (every process computes the same global ordering)
            acc = _acc_reduce(datas, op)
            if op == "mean":
                # mean = global sum / global copy count. The local copy
                # count rides along as one extra element through the SAME
                # cross-process sum, so per-process copy counts may differ
                # (within this branch — see the SPMD contract above).
                mean_unpack = (acc.shape, acc.dtype)
                pack_dtype = jnp.result_type(acc.dtype, jnp.float32)
                acc = jnp.concatenate(
                    [acc.reshape(-1).astype(pack_dtype),
                     jnp.asarray([float(len(datas))], pack_dtype)])
                op = "sum"
            by_proc: Dict[int, Any] = {}
            for d in jax.devices():
                if d.process_index not in by_proc or d.id < by_proc[d.process_index].id:
                    by_proc[d.process_index] = d
            datas = [jax.device_put(acc, by_proc[jax.process_index()])]
            mesh_devs = [by_proc[p] for p in sorted(by_proc)]
            mesh = Mesh(np.asarray(mesh_devs), ("dev",))
    else:
        mesh = Mesh(np.asarray(devs), ("dev",))
    shape = (len(mesh.devices.flat),) + datas[0].shape
    sharding = NamedSharding(mesh, P("dev"))
    shards = [d.reshape((1,) + d.shape) for d in datas]  # leading shard axis
    stacked = jax.make_array_from_single_device_arrays(shape, sharding, shards)
    reduced = _reduce_fn(mesh, op)(stacked)
    if jax.process_count() > 1:
        # The jit output is replicated over the GLOBAL mesh; a global jax.Array
        # is not addressable (asnumpy would raise) outside collectives, so hand
        # back this process's fully-replicated local shard as a plain array.
        reduced = reduced.addressable_shards[0].data
    if mean_unpack is not None:
        out_shape, out_dtype = mean_unpack
        # match the other mean paths' dtype promotion (acc / count, the
        # true-divide result type) — NOT a cast back to the input dtype,
        # which would truncate integer means
        div_dtype = jnp.result_type(out_dtype, jnp.float32) \
            if not jnp.issubdtype(out_dtype, jnp.floating) else out_dtype
        reduced = (reduced[:-1] / reduced[-1]).reshape(out_shape) \
            .astype(div_dtype)
    return reduced


_MULTI_REDUCE_JITS: Dict[Any, Any] = {}


def _multi_reduce_fn(mesh: Mesh, op: str):
    key = (tuple(d.id for d in mesh.devices.flat), op)
    fn = _MULTI_REDUCE_JITS.get(key)
    if fn is None:
        red = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
               "mean": jnp.mean}[op]
        fn = jax.jit(lambda xs: [red(x, axis=0) for x in xs],
                     out_shardings=NamedSharding(mesh, P()))
        _MULTI_REDUCE_JITS[key] = fn
    return fn


def all_reduce_multi(groups: List[List[Any]], op: str = "sum"):
    """Allreduce MANY tensors in ONE compiled XLA module.

    ``groups[k]`` is one per-device copy list for tensor ``k``; every group
    must span the same device set. All reductions compile into a single
    module so XLA can schedule/fuse the collectives together — the
    TPU-native analogue of the reference NCCL store's batched key grouping
    (kvstore_nccl.h:285) and the tree store's multi-tree reduce
    (comm_tree.h:50). Returns one replicated array per group.
    """
    if not groups:
        return []
    datas = [[a._data if isinstance(a, NDArray) else jnp.asarray(a)
              for a in g] for g in groups]
    devs = []
    for d in datas[0]:
        ds = list(d.devices())
        devs.append(ds[0] if len(ds) == 1 else None)
    uniform = None not in devs and len(set(devs)) == len(devs) and all(
        len(g) == len(devs) for g in datas)
    if not uniform or len(devs) == 1:
        return [all_reduce(g, op) for g in groups]
    mesh = Mesh(np.asarray(devs), ("dev",))
    sharding = NamedSharding(mesh, P("dev"))
    stacked = []
    for g in datas:
        by_dev = {next(iter(d.devices())): d for d in g}
        if len(by_dev) != len(devs) or any(dv not in by_dev for dv in devs):
            return [all_reduce(gg, op) for gg in groups]
        shape = (len(devs),) + g[0].shape
        shards = [by_dev[dv].reshape((1,) + by_dev[dv].shape) for dv in devs]
        stacked.append(jax.make_array_from_single_device_arrays(
            shape, sharding, shards))
    return _multi_reduce_fn(mesh, op)(stacked)


def pipeline_apply(stage_fn, stage_params, microbatches, mesh,
                   axis: str = "pp"):
    """GPipe-style pipeline parallelism over a mesh axis.

    Beyond the reference's scope (SURVEY §2.5: MXNet 1.3 has no true
    pipeline parallelism — its overlap is async-engine scheduling), but
    first-class on TPU: stages are laid out along ``axis``, activations
    hop stage-to-stage over ICI via ``lax.ppermute``, and microbatches
    keep every stage busy after the fill phase (the GPipe schedule:
    M + S - 1 ticks for M microbatches over S stages).

    Parameters
    ----------
    stage_fn : callable(params_s, x) -> y — one stage's computation;
        activations must keep one shape across stages.
    stage_params : pytree whose leaves have a leading stage axis (S, ...)
        — sharded over ``axis``, one stage per device.
    microbatches : (M, B, ...) array, replicated.
    mesh : Mesh containing ``axis`` with S devices.

    Returns (M, B, ...) outputs (the last stage's results, in microbatch
    order), fully replicated.
    """
    try:  # jax >= 0.5 exports it at the top level
        from jax import shard_map
    except ImportError:  # the 0.4.x experimental home
        from jax.experimental.shard_map import shard_map

    n_stage = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    bad = [l.shape for l in jax.tree_util.tree_leaves(stage_params)
           if l.shape[0] != n_stage]
    if bad:
        raise MXNetError(
            "pipeline_apply: every stage_params leaf needs leading dim %d "
            "(one stage per '%s' device); got %s" % (n_stage, axis, bad))
    ticks = n_micro + n_stage - 1
    ring = [(i, (i + 1) % n_stage) for i in range(n_stage)]

    def per_device(params_blk, x_all):
        # params_blk leaves: (1, ...) — this device's stage
        my_params = jax.tree_util.tree_map(lambda a: a[0], params_blk)
        stage = jax.lax.axis_index(axis)

        def tick(act_in, t):
            # stage 0 feeds itself from the microbatch stream; later
            # stages consume what the previous stage sent last tick
            my_in = jnp.where(stage == 0,
                              x_all[jnp.clip(t, 0, n_micro - 1)], act_in)
            out = stage_fn(my_params, my_in)
            act_next = jax.lax.ppermute(out, axis, ring)
            return act_next, out

        # the carry crosses ppermute, which makes it device-varying along
        # the pp axis; the initial zeros must carry the same varying type
        zero = jax.lax.pvary(jnp.zeros_like(x_all[0]), (axis,))
        _, outs = jax.lax.scan(tick, zero, jnp.arange(ticks))
        return outs[None]  # (1, ticks, B, ...) — stacked over axis

    spec_p = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(spec_p, P()), out_specs=P(axis))
    outs = fn(stage_params, microbatches)  # (S, ticks, B, ...)
    # microbatch m leaves the last stage at tick (S-1) + m
    return outs[n_stage - 1, n_stage - 1:n_stage - 1 + n_micro]


def shard_for_device(array, device):
    """Extract the replica of a replicated array that lives on ``device``
    (zero-copy)."""
    for s in array.addressable_shards:
        if s.device == device:
            return s.data
    return jax.device_put(array, device)


def broadcast_to_devices(array, devices):
    """Replicate a host/single-device array onto each device; returns a list
    of per-device arrays (reference comm.h Broadcast)."""
    data = array._data if isinstance(array, NDArray) else jnp.asarray(array)
    return [jax.device_put(data, d) for d in devices]


# ---------------------------------------------------------------------------
# sharding helpers shared by the step executors and the input plane
# ---------------------------------------------------------------------------


def batch_sharding(mesh: Mesh, ndim: int, batch_axis: int = 0,
                   dp_axis: Optional[str] = None) -> NamedSharding:
    """The NamedSharding a training batch should arrive in: sharded over the
    mesh's data-parallel axis at ``batch_axis``, replicated elsewhere. The
    input plane (``io.DevicePrefetchIter``/``gluon.data.DataLoader``) uses
    this as its device-put target so batches land pre-sharded and the step's
    own ``shard_to_mesh`` degenerates to an equivalence check."""
    spec = [None] * ndim
    spec[batch_axis] = dp_axis or mesh.axis_names[0]
    return NamedSharding(mesh, P(*spec))


def resolve_sharding(sharding, ndim: int):
    """Resolve an input-plane sharding spec — a concrete ``Sharding`` or an
    ``ndim -> Sharding`` callable (how ``batch_sharding`` is usually
    curried) — to the target for one array, or ``None`` when no target is
    configured."""
    if sharding is None:
        return None
    return sharding(ndim) if callable(sharding) else sharding


def _evenly_shardable(target, shape) -> bool:
    """Whether ``target`` can lay an array of ``shape`` out without ragged
    shards (``device_put`` raises on a partitioned dim the mesh axis does
    not divide)."""
    mesh = getattr(target, "mesh", None)
    spec = getattr(target, "spec", None)
    if mesh is None or spec is None:
        return True
    for dim, names in enumerate(spec):
        if names is None:
            continue
        parts = 1
        for axis in (names if isinstance(names, tuple) else (names,)):
            parts *= mesh.shape[axis]
        if dim >= len(shape) or shape[dim] % parts:
            return False
    return True


def put_sharded(data, target):
    """THE home of the skip-put discipline: ``device_put`` a jax array onto
    ``target`` unless it is already laid out equivalently — re-putting
    issues a copy that serializes dispatch with the device queue (measured
    74-157ms/step through the TPU relay, and a wasted D2D copy even on
    directly-attached chips). Returns ``data`` itself on skip, so callers
    can ``is``-check whether a put happened. Shared by ``shard_to_mesh``,
    the ``io.DevicePrefetchIter`` worker and the gluon ``DataLoader``
    feed.

    A batch the target cannot split evenly — the ragged final batch of an
    epoch on a multi-device mesh — degrades to replication over the same
    mesh instead of raising: the training plane's never-a-crash contract
    reaches the input plane too (GSPMD still partitions the step; the odd
    shape pays one extra compile, which it would anyway)."""
    sh = getattr(data, "sharding", None)
    if sh is not None and sh.is_equivalent_to(target, data.ndim):
        return data
    if not _evenly_shardable(target, data.shape):
        target = NamedSharding(target.mesh, P())
    return jax.device_put(data, target)


def shard_to_mesh(data, mesh: Mesh, batch_axis: int = 0,
                  dp_axis: Optional[str] = None):
    """Lay a batch out over the mesh's dp axis via ``put_sharded`` (a batch
    already laid out equivalently — always true for device-resident data on
    a 1-device mesh, and for the pre-sharded feed path — is returned
    as-is)."""
    data = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    return put_sharded(
        data, batch_sharding(mesh, data.ndim, batch_axis, dp_axis))


_REPL_JITS: Dict[Any, Any] = {}


def _identity_copy_fn(mesh: Mesh, target=None):
    if target is None:
        target = NamedSharding(mesh, P())
    key = (tuple(d.id for d in mesh.devices.flat),
           str(getattr(target, "spec", target)))
    fn = _REPL_JITS.get(key)
    if fn is None:
        fn = jax.jit(lambda a: a, out_shardings=target)
        _REPL_JITS[key] = fn
    return fn


def _buffer_ptrs(a):
    """Set of device-buffer addresses behind an array, or None when
    unprobeable."""
    try:
        return {s.data.unsafe_buffer_pointer() for s in a.addressable_shards}
    except Exception:  # noqa: BLE001 - probe failure => caller plays safe
        return None


def fresh_replicate(x, mesh: Mesh, target=None):
    """Lay ``x`` out over ``mesh`` into FRESH buffers, without the eager
    ``jnp.copy`` intermediate the old TrainStep init paid (a transient
    second full copy of every parameter — the 2x-HBM init spike): the
    result must not alias the source, because the step jit donates its
    param inputs and donation would otherwise delete a buffer the caller
    still references.

    ``target`` is the destination ``Sharding`` (or an ``ndim ->
    Sharding`` callable, resolved through :func:`resolve_sharding`);
    default fully replicated. The alias guard is layout-aware: a source
    already laid out as ``target`` — INCLUDING a dp-sharded ZeRO state
    bucket re-initialized in place — takes one compiled identity copy
    UNDER THAT LAYOUT instead of being silently re-replicated (the
    pre-ZeRO guard only knew the replicated case, so re-initializing a
    sharded tree would have quietly undone its sharding and N-tupled its
    per-device bytes).

    * host (numpy) source: ``device_put`` allocates fresh device buffers
      by construction — one copy, done;
    * relaying-out device source: ``device_put`` to ``target``, then an
      isolation pass ONLY if a source buffer leaked into the result (a
      runtime may reuse the source as a co-located shard);
    * already-in-layout source (the alias-guaranteed case ``device_put``
      would no-op on): one compiled identity copy — jit outputs never
      alias non-donated inputs.
    """
    target = resolve_sharding(target, getattr(x, "ndim", 0))
    if target is None:
        target = NamedSharding(mesh, P())
    sh = getattr(x, "sharding", None)
    if sh is None:
        return jax.device_put(x, target)
    if sh.is_equivalent_to(target, x.ndim):
        return _identity_copy_fn(mesh, target)(x)
    src = _buffer_ptrs(x)
    moved = jax.device_put(x, target)
    dst = _buffer_ptrs(moved)
    if src is None or dst is None or (src & dst):
        moved = _identity_copy_fn(mesh, target)(moved)
    return moved


# ---------------------------------------------------------------------------
# in-graph SPMD training step
# ---------------------------------------------------------------------------


class TrainStep(object):
    """One fully-fused SPMD training step over a device mesh.

    ``step = TrainStep(net, loss_fn, optimizer, mesh)`` then
    ``loss = step(data, label)`` runs forward + loss + backward + gradient
    reduction + optimizer update as ONE compiled XLA module per shape
    signature. Parameters/optimizer state live replicated on the mesh; the
    batch is sharded over the ``dp`` axis; GSPMD inserts the ICI
    collectives. This is the TPU-native equivalent of the reference's
    whole training stack for data parallelism: GraphExecutor fwd+bwd
    (graph_executor.cc:231-295) + kvstore reduce (comm.h:43) + fused
    optimizer ops (optimizer_op.cc) — in a single HloModule.

    Parameters
    ----------
    net : HybridBlock — initialized (or deferred-init) model
    loss_fn : gluon Loss block, or callable (out_nd, label_nd) -> loss NDArray
    optimizer : str or Optimizer with ``pure_step``
    mesh : jax Mesh from ``device_mesh()``; defaults to all devices
    batch_axis : int — which axis of data/label to shard over ``dp``
    """

    def __init__(self, net, loss_fn, optimizer, mesh: Optional[Mesh] = None,
                 optimizer_params=None, batch_axis: int = 0,
                 remat: bool = False):
        from . import optimizer as opt_mod

        #: recompute activations in backward (jax.checkpoint) — trades FLOPs
        #: for HBM, the reference's MXNET_BACKWARD_DO_MIRROR policy
        self._remat = remat
        self._net = net
        self._loss = loss_fn
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self._optimizer = optimizer
        self._mesh = mesh if mesh is not None else device_mesh()
        self._batch_axis = batch_axis
        self._dp_axis = self._mesh.axis_names[0]
        self._pvals = None          # name -> replicated jax array
        self._opt_states = None     # name -> state pytree
        self._grad_reqs = None
        self._mults = None          # name -> (lr_mult, wd_mult)
        self._t = 0
        self._step_jits: Dict[Any, Any] = {}

    # ------------------------------------------------------------------
    def _repl(self, x):
        # fresh buffer (jit outputs never alias non-donated inputs): the
        # step jit donates its param inputs, and an alias would let that
        # donation delete a buffer the caller still references. No eager
        # copy intermediate — peak init memory stays ~1x model size.
        return fresh_replicate(x, self._mesh)

    def _shard_batch(self, x, extra_lead_axes=0):
        return shard_to_mesh(x, self._mesh,
                             self._batch_axis + extra_lead_axes,
                             self._dp_axis)

    def _ensure_init(self, data_nd):
        if self._pvals is not None:
            return
        params = self._net.collect_params()
        try:
            pvals = {n: p.data()._data for n, p in params.items()}
        except Exception:
            with autograd.pause():
                self._net(data_nd)  # finish deferred init
            pvals = {n: p.data()._data for n, p in params.items()}
        self._grad_reqs = {n: p.grad_req for n, p in params.items()}
        self._mults = {n: (p.lr_mult, p.wd_mult) for n, p in params.items()}
        self._pvals = {n: self._repl(v) for n, v in pvals.items()}
        self._opt_states = {}
        def _repl_state(x):
            # master optimizer state stays f32 regardless of param dtype
            # (the reference's multi-precision mp_sgd keeps an f32 master,
            # optimizer_op.cc mp_sgd_update); also required for lax.scan
            # carry stability in multi_call — pure_step math runs in f32,
            # so a bf16-created state would change dtype across steps
            x = jnp.asarray(x)
            if jnp.issubdtype(x.dtype, jnp.floating) and \
                    x.dtype != jnp.float32:
                x = x.astype(jnp.float32)
            return self._repl(x)

        for n, p in params.items():
            if self._grad_reqs[n] != "null":
                st = self._optimizer.create_state(n, p.data())
                self._opt_states[n] = jax.tree_util.tree_map(_repl_state, st) \
                    if st is not None else None

    # ------------------------------------------------------------------
    def _core_step(self, in_fmt):
        """The single-step function ``(pvals, opt_states, t, lr, data,
        label, rng) -> (loss, new_pvals, new_opt_states)`` shared by the
        per-call jit and the multi-step ``lax.scan`` executor."""
        # in_fmt is the gluon.block._flatten format of the net's inputs
        base_fn = self._net._base_fn(in_fmt, train=True)
        diff_names = tuple(n for n, r in self._grad_reqs.items() if r != "null")
        const_names = tuple(n for n in self._pvals if n not in diff_names)
        loss_fn = self._loss
        optimizer = self._optimizer
        mults = self._mults

        def step(pvals, opt_states, t, lr, data, label, rng):
            const = {n: pvals[n] for n in const_names}

            def loss_f(dp):
                pv = dict(const)
                pv.update(dp)
                outs, aux = base_fn(pv, rng, data)
                out0 = outs[0] if isinstance(outs, tuple) else outs
                with autograd._RecordingStateScope(False, None):
                    l_nd = loss_fn(NDArray(out0, cpu()), NDArray(label, cpu()))
                loss = jnp.mean(l_nd._data)
                return loss, aux

            diff = {n: pvals[n] for n in diff_names}
            lf = jax.checkpoint(loss_f) if self._remat else loss_f
            (loss, aux), grads = jax.value_and_grad(
                lf, has_aux=True)(diff)

            new_p = dict(const)
            new_states = {}
            for n in diff_names:
                lm, wm = mults[n]
                w, s = optimizer.pure_step(
                    pvals[n], grads[n], opt_states[n], t,
                    lr * lm, optimizer.wd * wm)
                # bf16 params: f32 grads/states would silently upcast the
                # weight each step (multi-precision keeps math in f32, the
                # stored weight stays in the model's dtype)
                new_p[n] = w.astype(pvals[n].dtype)
                new_states[n] = s
            new_p.update(aux)  # BN moving stats et al.
            return loss, new_p, new_states

        return step

    def _build_step(self, in_fmt):
        repl = NamedSharding(self._mesh, P())
        return jax.jit(
            self._core_step(in_fmt),
            out_shardings=(repl, repl, repl),
            donate_argnums=(0, 1),
        )

    def _build_multi(self, in_fmt, k):
        """K training steps fused into ONE XLA module via ``lax.scan``.

        Parameters and optimizer state live in the scan carry, so the
        per-parameter input/output layout copies a single-step module pays
        on every invocation happen once per K steps, and per-execute
        dispatch overhead is amortized K-fold. This is the standard JAX
        scan-over-steps training loop; the reference's analogue is engine
        op bulking (``MXNET_EXEC_BULK_EXEC_TRAIN``,
        src/engine/threaded_engine.cc:289) which batches engine ops to cut
        per-op dispatch cost the same way."""
        core = self._core_step(in_fmt)

        def multi(pvals, opt_states, t, lr, datas, labels, rng):
            keys = jax.random.split(rng, k)

            def body(carry, xs):
                pv, st, tt = carry
                d, l, kk = xs
                loss, new_p, new_s = core(pv, st, tt, lr, d, l, kk)
                return (new_p, new_s, tt + 1.0), loss

            (pvals, opt_states, t), losses = jax.lax.scan(
                body, (pvals, opt_states, t), (datas, labels, keys))
            return losses, pvals, opt_states

        repl = NamedSharding(self._mesh, P())
        return jax.jit(
            multi,
            out_shardings=(repl, repl, repl),
            donate_argnums=(0, 1),
        )

    # ------------------------------------------------------------------
    def __call__(self, data, label):
        data_nd = data if isinstance(data, NDArray) else NDArray(
            jnp.asarray(data), cpu())
        self._ensure_init(data_nd)
        # the step counter has ONE source of truth shared with the eager
        # Updater path (optimizer.num_update): a run that interleaves this
        # in-graph step with eager Trainer.step calls (warmup/eval) must
        # not replay or skip schedule steps on either side
        self._t = max(self._t, self._optimizer.num_update) + 1
        self._optimizer.sync_num_update(self._t)

        d = self._shard_batch(data)
        l = self._shard_batch(label)
        rng = _global.next_key()
        lr = jnp.float32(self._optimizer.learning_rate)
        t = jnp.float32(self._t)

        key = (tuple(d.shape), str(d.dtype), tuple(l.shape), str(l.dtype))
        if key not in self._step_jits:
            self._step_jits[key] = self._build_step([0])
        # avals only (no live buffers): memory_analysis() must not pin a
        # batch or donated-dead params on device
        def _aval(a):
            return jax.ShapeDtypeStruct(jnp.shape(a), a.dtype)
        self._last_call = (key, self._step_jits[key], jax.tree_util.tree_map(
            _aval, (self._pvals, self._opt_states, t, lr, d, l, rng)))
        loss, self._pvals, self._opt_states = self._step_jits[key](
            self._pvals, self._opt_states, t, lr, d, l, rng)
        return NDArray(loss, cpu())

    def memory_analysis(self):
        """XLA's compiled-buffer accounting for the last single-step
        executor (CompiledMemoryStats: ``temp_size_in_bytes`` is the
        stored-activation workspace — see example/memcost for where
        ``remat`` does and does not shrink it). Call the step at least
        once first; stats are cached per input signature."""
        if getattr(self, "_last_call", None) is None:
            raise MXNetError("memory_analysis: run the step once first")
        key, jit_fn, avals = self._last_call
        cache = getattr(self, "_mem_stats", None)
        if cache is None:
            cache = self._mem_stats = {}
        if key not in cache:
            cache[key] = jit_fn.lower(*avals).compile().memory_analysis()
        return cache[key]

    # ------------------------------------------------------------------
    def multi_call(self, datas, labels):
        """Run K fused training steps in ONE device call.

        ``datas``/``labels`` carry a leading steps axis: shape
        ``(K, batch, ...)`` — one slice per step. Returns the per-step
        losses as an NDArray of shape ``(K,)``. The learning rate is
        sampled once per call, so LR schedules advance at call
        granularity. Use this for steady-state training throughput —
        per-call dispatch and parameter-I/O cost is paid once per K steps
        (see ``_build_multi``).
        """
        datas_nd = datas if isinstance(datas, NDArray) else NDArray(
            jnp.asarray(datas), cpu())
        labels_nd = labels if isinstance(labels, NDArray) else NDArray(
            jnp.asarray(labels), cpu())
        self._ensure_init(NDArray(datas_nd._data[0], cpu()))
        k = int(datas_nd._data.shape[0])
        # counter coherence with eager interleaves — see __call__
        self._t = max(self._t, self._optimizer.num_update) + k
        self._optimizer.sync_num_update(self._t)

        d = self._shard_batch(datas_nd, extra_lead_axes=1)
        l = self._shard_batch(labels_nd, extra_lead_axes=1)
        rng = _global.next_key()
        lr = jnp.float32(self._optimizer.learning_rate)
        # first fused step must see the same 1-based counter __call__ uses
        # (t=0 would e.g. zero Adam's bias correction -> NaN weights)
        t = jnp.float32(self._t - k + 1)

        key = ("multi", k, tuple(d.shape), str(d.dtype), tuple(l.shape),
               str(l.dtype))
        if key not in self._step_jits:
            self._step_jits[key] = self._build_multi([0], k)
        losses, self._pvals, self._opt_states = self._step_jits[key](
            self._pvals, self._opt_states, t, lr, d, l, rng)
        return NDArray(losses, cpu())

    # ------------------------------------------------------------------
    def copy_to_net(self):
        """Write the trained replicated parameters back into the net's
        Parameter buffers (so save_parameters/export see the result)."""
        params = self._net.collect_params()
        for n, v in self._pvals.items():
            # fresh buffer: the next step() donates (deletes) self._pvals
            params[n].data()._data = jnp.copy(v)
        return self._net

    @property
    def params(self):
        return self._pvals


class InferStep(object):
    """Batched SPMD inference executor over a device mesh.

    ``infer = InferStep(net, mesh)`` then ``out = infer(x)`` runs one
    forward in predict mode; ``outs = infer.multi_call(xs)`` runs K
    forwards (leading steps axis on ``xs``) fused into ONE XLA module via
    ``lax.scan``, paying parameter input copies and per-call dispatch once
    per K batches. The scan analogue of the reference's inference-side
    engine bulking (``MXNET_EXEC_BULK_EXEC_INFERENCE``,
    docs/faq/env_var.md:74-80); the per-batch path matches
    ``benchmark_score.py``'s protocol.

    Parameters are snapshot on first use (deployment semantics, like the
    reference's ``HybridBlock.export`` artifact). If the net's weights
    change afterwards (training, ``load_parameters``), call
    ``refresh_params()`` to re-snapshot.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, batch_axis: int = 0):
        self._net = net
        self._mesh = mesh if mesh is not None else device_mesh()
        self._batch_axis = batch_axis
        self._dp_axis = self._mesh.axis_names[0]
        self._pvals = None
        self._jits: Dict[Any, Any] = {}

    _shard_batch = TrainStep._shard_batch

    def _ensure_init(self, data_nd):
        if self._pvals is not None:
            return
        params = self._net.collect_params()
        try:
            pvals = {n: p.data()._data for n, p in params.items()}
        except Exception:
            with autograd.pause():
                self._net(data_nd)
            pvals = {n: p.data()._data for n, p in params.items()}
        repl = NamedSharding(self._mesh, P())
        self._pvals = {n: jax.device_put(v, repl) for n, v in pvals.items()}

    def refresh_params(self):
        """Re-snapshot the net's current parameter values (compiled
        executables are kept — only the param buffers are replaced)."""
        self._pvals = None

    def _build(self, k):
        base_fn = self._net._base_fn([0], train=False)

        def single(pvals, data, rng):
            outs, _aux = base_fn(pvals, rng, data)
            return outs[0] if isinstance(outs, tuple) else outs

        if k is None:
            return jax.jit(single)

        def multi(pvals, datas, rng):
            keys = jax.random.split(rng, k)  # independent randomness per
            # scanned batch (predict-mode stochastic layers)

            def body(carry, xs):
                d, kk = xs
                return carry, single(pvals, d, kk)

            _, ys = jax.lax.scan(body, None, (datas, keys))
            return ys

        return jax.jit(multi)

    def __call__(self, data):
        data_nd = data if isinstance(data, NDArray) else NDArray(
            jnp.asarray(data), cpu())
        self._ensure_init(data_nd)
        d = self._shard_batch(data_nd)
        key = (None, tuple(d.shape), str(d.dtype))
        if key not in self._jits:
            self._jits[key] = self._build(None)
        return NDArray(self._jits[key](self._pvals, d, _global.next_key()),
                       cpu())

    def multi_call(self, datas):
        datas_nd = datas if isinstance(datas, NDArray) else NDArray(
            jnp.asarray(datas), cpu())
        self._ensure_init(NDArray(datas_nd._data[0], cpu()))
        k = int(datas_nd._data.shape[0])
        d = self._shard_batch(datas_nd, extra_lead_axes=1)
        key = (k, tuple(d.shape), str(d.dtype))
        if key not in self._jits:
            self._jits[key] = self._build(k)
        return NDArray(self._jits[key](self._pvals, d, _global.next_key()),
                       cpu())
