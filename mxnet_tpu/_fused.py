"""Closure→jaxpr conversion for the fused fwd/bwd training pair.

``jax.closure_convert`` hoists only inexact-dtype residuals out of a vjp
closure; any bool/int intermediate (relu masks, argmax indices, BN flags)
stays captured as a tracer and leaks across jit boundaries. This helper
hoists EVERY captured constant by materialising the closure's jaxpr
directly, so the backward half of the training pair is a fully pure
function of (residuals, cotangents) — the equivalent of the reference
splitting one nnvm graph into forward and backward segments that
communicate only through saved node outputs
(src/executor/graph_executor.cc:231-295).
"""
from __future__ import annotations

import jax

__all__ = ["convert_closure"]


def convert_closure(fun, *examples):
    """Convert closure ``fun`` into (pure_fn, residuals).

    ``fun`` is traced with abstract ``examples``; every value it captures
    from an enclosing trace is hoisted into the returned ``residuals`` list
    (valid jit outputs). ``pure_fn(residuals, *args)`` replays the jaxpr.
    """
    closed, shapes = jax.make_jaxpr(fun, return_shape=True)(*examples)
    out_tree = jax.tree_util.tree_structure(shapes)
    jaxpr, consts = closed.jaxpr, list(closed.consts)

    def pure_fn(residuals, *args):
        outs = jax.core.eval_jaxpr(jaxpr, list(residuals), *args)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    return pure_fn, consts
